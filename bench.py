"""Benchmark: MNIST CNN training steps/sec on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "steps/s", "vs_baseline": N}

Baseline: the reference's steady-state distributed rate — epochs 2-3 take ~9s
for 5 steps at global batch 256 on the 4-worker gRPC CollectiveAllReduce setup
(/root/reference/README.md:413-414, BASELINE.md) => 0.556 steps/s. The
north-star target is >=4x that (BASELINE.json).

Method: the same global-batch-256 train step (forward + backward + SGD update
+ metrics, exactly what fit() runs), steady-state: pre-staged device batches,
warmup for compile, then timed steps with a final block. Runs on whatever
devices are available (1 real chip here; a DP mesh if several).
"""

import json
import time

import jax
import numpy as np

import distributed_tpu as dtpu

BASELINE_STEPS_PER_SEC = 5.0 / 9.0  # README.md:413-414
GLOBAL_BATCH = 256  # reference's 4-worker global batch (README.md:366-367)
WARMUP, MEASURE = 10, 100


def main():
    n_dev = len(jax.devices())
    if n_dev > 1:
        strategy = dtpu.DataParallel()
    else:
        strategy = dtpu.SingleDevice()
    with strategy.scope():
        model = dtpu.Model(dtpu.models.mnist_cnn())
        model.compile(
            optimizer=dtpu.optim.SGD(0.001),
            loss="sparse_categorical_crossentropy",
            metrics=["accuracy"],
        )
    model.build((28, 28, 1))

    x, y = dtpu.data.synthetic_images(GLOBAL_BATCH * 4, (28, 28), 10, 0)
    x = x[..., None].astype(np.float32) / 255.0
    y = y.astype(np.int32)
    batches = [
        model.strategy.put_batch(
            {"x": x[i * GLOBAL_BATCH : (i + 1) * GLOBAL_BATCH],
             "y": y[i * GLOBAL_BATCH : (i + 1) * GLOBAL_BATCH]}
        )
        for i in range(4)
    ]

    step_fn = model._get_train_step()
    rng = jax.random.PRNGKey(0)
    params, state, opt = model.params, model.state, model.opt_state
    for i in range(WARMUP):
        b = batches[i % 4]
        params, state, opt, loss, _ = step_fn(params, state, opt, b["x"], b["y"], rng)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for i in range(MEASURE):
        b = batches[i % 4]
        params, state, opt, loss, _ = step_fn(params, state, opt, b["x"], b["y"], rng)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    steps_per_sec = MEASURE / dt
    print(
        json.dumps(
            {
                "metric": "mnist_cnn_train_steps_per_sec_gb256",
                "value": round(steps_per_sec, 2),
                "unit": "steps/s",
                "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
