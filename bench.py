"""Benchmarks: MNIST CNN (headline, vs-reference), ResNet-50, transformer LM.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "steps/s", "vs_baseline": N,
   "extra": [{resnet-50 ...}, {transformer-lm ...}]}

Headline baseline: the reference's steady-state distributed rate — epochs 2-3
take ~9s for 5 steps at global batch 256 on the 4-worker gRPC
CollectiveAllReduce setup (/root/reference/README.md:413-414, BASELINE.md)
=> 0.556 steps/s. The north-star target is >=4x that (BASELINE.json).

The reference publishes no model larger than the 347k-param MNIST CNN
(SURVEY.md §6), where a TPU step is dispatch-bound. The extra modes measure
the framework at scale on the real chip:

- resnet50: synthetic ImageNet (224x224), global batch 256, bf16 compute —
  BASELINE.json configs[3]'s model. Reports steps/s, achieved TFLOP/s, MFU.
- transformer_lm: ~136M-param GPT-2-small-shaped LM (untied head), 32k vocab, seq 1024,
  Pallas fused cross-entropy on the LM head. Reports steps/s, TFLOP/s, MFU.

MFU = achieved matmul TFLOP/s / the chip's peak bf16 TFLOP/s (null when the
device kind is unknown, e.g. CPU smoke runs). FLOP counts are the standard
analytic ones (3x forward for training; 6ND + attention for the LM), not
XLA's cost model.

Each mode is a function with size parameters so tests/test_bench.py can
smoke-run the exact code path on CPU with tiny shapes. Besides the default
modes, ``python bench.py longctx`` measures the long-context rows
(docs/PERF.md table) — opt-in, large compiles — and ``python bench.py
resilience`` measures supervisor heartbeat overhead and restart-to-first-
step latency (docs/RESILIENCE.md) — opt-in, spawns worker subprocesses.
``python bench.py zero`` compares per-device model-state memory and steps/s
for replicated DP vs ZeRO-1 vs FSDP, plus a simulated-HBM-cap row where
only FSDP fits (BENCH_zero.json) — opt-in, needs a multi-device mesh
(run under XLA_FLAGS=--xla_force_host_platform_device_count=8 on CPU).
``overlap2`` (opt-in, multi-device like zero) measures the FSDP scanned-
stack gather-prefetch overlap (BENCH_overlap2.json) and ``decode_kernel``
(opt-in) the fused paged-attention serving kernel vs the reference path
(BENCH_decode_kernel.json) — docs/PERF.md "Overlap round 2" / "Fused
paged attention".
"""

import contextlib
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

import distributed_tpu as dtpu

BASELINE_STEPS_PER_SEC = 5.0 / 9.0  # README.md:413-414
GLOBAL_BATCH = 256  # reference's 4-worker global batch (README.md:366-367)

# Peak dense bf16 TFLOP/s per chip, by device_kind substring (public specs).
_PEAK_TFLOPS = {
    "v6": 918.0,  # Trillium
    "v5p": 459.0,
    "v5e": 197.0,
    "v5 lite": 197.0,
    "v4": 275.0,
    "v3": 123.0,
    "v2": 45.0,
}


def _peak_tflops():
    kind = jax.devices()[0].device_kind.lower()
    for key, peak in _PEAK_TFLOPS.items():
        if key in kind:
            return peak
    return None


def _mfu(tflops_achieved):
    peak = _peak_tflops()
    if peak is None or tflops_achieved is None:
        return None
    return round(tflops_achieved / peak, 4)


def _strategy():
    return dtpu.DataParallel() if len(jax.devices()) > 1 else dtpu.SingleDevice()


def _sync(value):
    # jax.block_until_ready is a no-op on some remote-device transports
    # (observed on the tunneled 'axon' TPU platform: timing a matmul chain
    # with block_until_ready reported >1000x the chip's peak FLOP/s). A host
    # fetch of the value is an unambiguous barrier everywhere.
    np.asarray(jax.device_get(value))


def _time_steps(model, batch, warmup, measure, windows=3):
    """Steady-state steps/s of the compiled train step on pre-staged data.

    Times ``windows`` independent windows and returns
    ``(median_rate, per_window_rates)``: the tunneled transport's dispatch
    jitter swings small-model timings by +/-10-30% between single windows
    (docs/PERF.md), so every bench mode reports a median-of-3 and persists
    the raw window rates for spread inspection (VERDICT r4 weak #1: a
    one-window rate on this transport is a sample, not a number)."""
    step_fn = model._get_train_step()
    rng = jax.random.PRNGKey(0)
    params, state, opt = model.params, model.state, model.opt_state
    loss = None
    for _ in range(warmup):
        params, state, opt, loss, _ = step_fn(
            params, state, opt, batch["x"], batch["y"], rng
        )
    _sync(loss)
    rates = []
    for _ in range(max(1, windows)):
        t0 = time.perf_counter()
        for _ in range(measure):
            params, state, opt, loss, _ = step_fn(
                params, state, opt, batch["x"], batch["y"], rng
            )
        _sync(loss)
        rates.append(measure / (time.perf_counter() - t0))
    return float(np.median(rates)), [round(r, 3) for r in rates]


# ---------------------------------------------------------------- headline --
def bench_mnist(global_batch=GLOBAL_BATCH, warmup=10, measure=100):
    """The reference workload: 347k-param CNN, global batch 256."""
    strategy = _strategy()
    with strategy.scope():
        model = dtpu.Model(dtpu.models.mnist_cnn())
        model.compile(
            optimizer=dtpu.optim.SGD(0.001),
            loss="sparse_categorical_crossentropy",
            metrics=["accuracy"],
        )
    model.build((28, 28, 1))

    x, y = dtpu.data.synthetic_images(global_batch, (28, 28), 10, 0)
    batch = model.strategy.put_batch(
        {"x": x[..., None].astype(np.float32) / 255.0, "y": y.astype(np.int32)}
    )
    steps_per_sec, window_rates = _time_steps(model, batch, warmup, measure)
    return {
        "metric": "mnist_cnn_train_steps_per_sec_gb256",
        "value": round(steps_per_sec, 2),
        "unit": "steps/s",
        "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 1),
        "window_steps_per_sec": window_rates,
    }


# --------------------------------------------------------------- multi-step --
def bench_multi_step(global_batch=None, ks=(1, 8, 32), measure_steps=192):
    """Dispatch-overhead amortization curve: mnist_cnn trained through the
    REAL ``fit()`` hot path at ``compile(steps_per_execution=K)`` for each
    K. One fused dispatch runs K jitted steps (lax.scan over a
    [K, batch, ...] super-batch, metrics accumulated on device), so the
    per-step host work — batch placement, RNG folds, dispatch, the Python
    loop — divides by K. Unlike the pre-staged headline window, this mode
    times ``fit`` itself (epoch-end sync included): the host overhead the
    feature amortizes IS the measurement target.

    ``global_batch`` default: 256 (the reference shape) on accelerators,
    where the tunneled transport's per-dispatch gap dominates small-model
    steps; 2 on CPU, where JAX dispatch overhead is only ~1-2 ms and a
    bigger batch buries it under conv compute (docs/PERF.md "Multi-step
    execution")."""
    from distributed_tpu.utils.profiler import StepTimer

    if global_batch is None:
        if jax.default_backend() != "cpu":
            global_batch = GLOBAL_BATCH
        else:
            # 2 rows per replica: small enough that host dispatch overhead
            # is a visible fraction of the CPU step.
            n_dev = len(jax.devices())
            global_batch = 2 * (n_dev if n_dev > 1 else 1)
    x, y = dtpu.data.synthetic_images(512, (28, 28), 10, 0)
    xb = x[..., None].astype(np.float32) / 255.0
    yb = y.astype(np.int32)
    rows = []
    for k in ks:
        strategy = _strategy()
        with strategy.scope():
            model = dtpu.Model(dtpu.models.mnist_cnn())
            model.compile(
                optimizer=dtpu.optim.SGD(0.001),
                loss="sparse_categorical_crossentropy",
                metrics=["accuracy"],
                steps_per_execution=k,
            )
        model.build((28, 28, 1))
        steps = max(k, (measure_steps // k) * k)  # K-aligned window
        timer = StepTimer(warmup=0)
        cbs = [dtpu.callbacks.LambdaCallback(
            on_epoch_begin=lambda m, e: timer.tick(0),  # (re)arm the clock
            on_batch_end=lambda m, s, logs: timer.tick(steps=k),
        )]
        # Warmup epoch compiles the (possibly fused) step program.
        model.fit(xb, yb, batch_size=global_batch, epochs=1,
                  steps_per_epoch=k, verbose=0, seed=0)
        rates = []
        for _ in range(3):  # median-of-3, same protocol as every mode
            timer.__init__(warmup=0)
            model.fit(xb, yb, batch_size=global_batch, epochs=1,
                      steps_per_epoch=steps, verbose=0, seed=0,
                      callbacks=cbs)
            # fit returned AFTER its epoch-end device_get: the clock (read
            # now) covers dispatch AND compute of the whole window.
            rates.append(timer.steps_per_sec)
        rows.append({
            "metric": (
                f"mnist_cnn_multistep_k{k}_steps_per_sec_gb{global_batch}"
            ),
            "value": round(float(np.median(rates)), 2),
            "unit": "steps/s",
            "steps_per_execution": k,
            "window_steps_per_sec": [round(r, 3) for r in rates],
        })
    out = dict(rows[0])
    if len(rows) > 1:
        out["rows"] = rows[1:]
        if rows[0]["value"] > 0:
            out["speedup_vs_k1"] = {
                f"k{r['steps_per_execution']}":
                    round(r["value"] / rows[0]["value"], 2)
                for r in rows[1:]
            }
    return out


# ----------------------------------------------------------------- overlap --
class _HostBoundBatches:
    """Infinite (x, y) batch iterator shaped like a remote-storage input
    pipeline: each batch costs one blocking fetch wait (``latency_s`` —
    the RTT of a GCS/NFS read or a decode-service call, a sleep to the
    CPU, which is exactly what a remote read is) plus real numpy prep
    (gather + pad-crop shift + flip + normalize), deterministic in
    (seed, step). This is the host-bound shape prefetch exists for: the
    fetch wait and prep sit on the step's critical path unless something
    overlaps them with compute. Exposes the iterator surface fit()
    consumes (batch_size / steps_per_pass / batch_shape)."""

    def __init__(self, x_u8, y, batch_size, seed=0, latency_s=0.03):
        self._x = x_u8 if x_u8.ndim == 4 else x_u8[..., None]  # (n,h,w,1)
        self._y = y.astype(np.int32)
        self.batch_size = int(batch_size)
        self.steps_per_pass = len(self._x) // self.batch_size
        self.batch_shape = (self.batch_size,) + self._x.shape[1:]
        self.seed = int(seed)
        self.step = 0
        self.latency_s = float(latency_s)

    def __iter__(self):
        return self

    def __next__(self):
        r = np.random.default_rng((self.seed, self.step))
        self.step += 1
        idx = r.integers(0, len(self._x), self.batch_size)
        if self.latency_s:
            time.sleep(self.latency_s)  # the storage RTT, paid per batch
        rows = self._x[idx]
        p = np.pad(rows, ((0, 0), (2, 2), (2, 2), (0, 0)))
        dr, dc = r.integers(0, 5, 2)
        h, w = rows.shape[1:3]
        crop = p[:, dr:dr + h, dc:dc + w, :]
        flip = r.random(len(idx)) < 0.5
        crop = np.where(flip[:, None, None, None], crop[:, :, ::-1, :], crop)
        return crop.astype(np.float32) * (1.0 / 255.0), self._y[idx]


def bench_overlap(batch=32, measure_steps=24, depths=(0, 2), repeats=3,
                  n_rows=4096, image_hw=(28, 28), fetch_latency_ms=30.0):
    """Input-overlap win on a host-bound mnist_cnn config: a remote-
    storage-shaped source (per-batch fetch latency + numpy augment, see
    ``_HostBoundBatches``) feeds ``fit()`` through the device-prefetch
    stage at each depth. Depth 0 is the synchronous pre-overlap loop —
    the fetch wait and prep run on the main thread between dispatches, on
    the step's critical path; depth 2 is the double-buffered default,
    where the background producer absorbs them while the device computes.
    Reports steps/s per depth, the input-stall fraction measured by the
    fit loop's own stall accounting (``model.last_fit_telemetry``), and
    the depth-2-vs-0 speedup.

    Why latency and not pure CPU prep: overlap needs a second execution
    resource. Fetch latency (a blocked read) overlaps with compute on ANY
    machine, including this 1-core CI container; CPU-bound prep only
    overlaps where a spare core exists to run it (on multi-core hosts the
    augment here overlaps too — same mechanism, more win)."""
    from distributed_tpu.utils.profiler import StepTimer

    x, y = dtpu.data.synthetic_images(n_rows, image_hw, 10, 0)
    rows = []
    for depth in depths:
        strategy = _strategy()
        with strategy.scope():
            model = dtpu.Model(dtpu.models.mnist_cnn())
            model.compile(
                optimizer=dtpu.optim.SGD(0.001),
                loss="sparse_categorical_crossentropy",
                metrics=["accuracy"],
            )
        model.build(image_hw + (1,))
        source = _HostBoundBatches(
            x[..., None], y, batch_size=batch, seed=0,
            latency_s=fetch_latency_ms / 1e3,
        )
        # Warmup epoch compiles the step program outside the timing.
        model.fit(source, epochs=1, steps_per_epoch=2, verbose=0,
                  prefetch=depth)
        rates, stalls = [], []
        for _ in range(max(1, repeats)):
            timer = StepTimer(warmup=0)
            cbs = [dtpu.callbacks.LambdaCallback(
                on_batch_end=lambda m, s, logs: timer.tick()
            )]
            model.fit(source, epochs=1, steps_per_epoch=measure_steps,
                      verbose=0, prefetch=depth, callbacks=cbs)
            # fit returned after its epoch-end device_get: the clock covers
            # host prep, transfer, dispatch AND compute of the window.
            rates.append(timer.steps_per_sec)
            stalls.append(model.last_fit_telemetry["input_stall_fraction"])
        rows.append({
            "metric": f"mnist_cnn_overlap_d{depth}_steps_per_sec_b{batch}",
            "value": round(float(np.median(rates)), 3),
            "unit": "steps/s",
            "prefetch_depth": depth,
            "input_stall_fraction": round(float(np.median(stalls)), 4),
            "window_steps_per_sec": [round(r, 3) for r in rates],
        })
    out = dict(rows[0])
    if len(rows) > 1:
        out["rows"] = rows[1:]
        if rows[0]["value"] > 0:
            out["speedup_vs_depth0"] = {
                f"d{r['prefetch_depth']}":
                    round(r["value"] / rows[0]["value"], 2)
                for r in rows[1:]
            }
    return out


# ----------------------------------------------------------- streaming input --
def bench_input(batch=32, measure_steps=24, workers=(0, 1, 2, 4), repeats=3,
                n_records=2048, image_hw=(28, 28), decode_latency_ms=4.0,
                records_dir=None):
    """Decode-parallelism win on a DECODE-BOUND streaming config
    (``python bench.py input``, artifact BENCH_input.json; docs/PERF.md
    "Streaming input"). A directory of indexed record shards
    (``data.write_records``: zlib-compressed synthetic images, one
    variable-length record each) feeds a cheap mnist_cnn through
    ``Pipeline(RecordSource(...), decode_workers=W)`` for each W. The
    decode_fn is genuinely costly per record — a blocking stage
    (``decode_latency_ms``: the RTT of a remote decode service or
    object-store read, a sleep to the CPU, which is exactly what a
    blocked read is) plus a real zlib decompress + unpack — so at W=0 the
    input side, not the device, bounds the step rate even under
    ``fit(prefetch=2)``: prefetch's single producer hides input LATENCY
    behind compute but serializes the decodes themselves. decode_workers
    adds the missing PARALLELISM: W workers decode W batches' records
    concurrently (work assigned by step, reassembled in order — the
    stream stays bit-identical, which tests/test_records.py pins).

    Reports steps/s and the fit loop's own input_stall_fraction per W,
    plus speedup_vs_w0. Same honesty note as bench_overlap: on this
    1-core container the parallelizable cost is the blocking stage;
    CPU-bound decode (the zlib part) additionally parallelizes wherever
    spare cores exist — same mechanism, more win."""
    import tempfile
    import zlib as _zlib

    from distributed_tpu.data import Pipeline, RecordSource, write_records
    from distributed_tpu.utils.profiler import StepTimer

    x, y = dtpu.data.synthetic_images(n_records, image_hw, 10, 0)
    x = x[..., None]
    row_shape = x.shape[1:]
    directory = records_dir or tempfile.mkdtemp(prefix="dtpu-bench-records-")
    write_records(
        directory,
        (bytes([int(lbl)]) + _zlib.compress(img.tobytes(), 6)
         for img, lbl in zip(x, y)),
    )
    lat = float(decode_latency_ms) / 1e3

    def decode(b):
        if lat:
            time.sleep(lat)  # the remote-decode/storage RTT, per record
        raw = _zlib.decompress(b[1:])
        row = np.frombuffer(raw, np.uint8).reshape(row_shape)
        return row.astype(np.float32) * np.float32(1.0 / 255.0), int(b[0])

    rows = []
    for w in workers:
        strategy = _strategy()
        with strategy.scope():
            model = dtpu.Model(dtpu.models.mnist_cnn())
            model.compile(
                optimizer=dtpu.optim.SGD(0.001),
                loss="sparse_categorical_crossentropy",
                metrics=["accuracy"],
            )
        model.build(row_shape)
        with Pipeline(RecordSource(directory, decode_fn=decode), None,
                      batch, seed=0, decode_workers=w) as pipe:
            # Warmup epoch compiles the step program outside the timing.
            model.fit(pipe, epochs=1, steps_per_epoch=2, verbose=0)
            rates, stalls = [], []
            for _ in range(max(1, repeats)):
                timer = StepTimer(warmup=0)
                cbs = [dtpu.callbacks.LambdaCallback(
                    on_batch_end=lambda m, s, logs: timer.tick()
                )]
                model.fit(pipe, epochs=1, steps_per_epoch=measure_steps,
                          verbose=0, callbacks=cbs)
                rates.append(timer.steps_per_sec)
                stalls.append(
                    model.last_fit_telemetry["input_stall_fraction"]
                )
        rows.append({
            "metric": f"records_decode_w{w}_steps_per_sec_b{batch}",
            "value": round(float(np.median(rates)), 3),
            "unit": "steps/s",
            "decode_workers": w,
            "input_stall_fraction": round(float(np.median(stalls)), 4),
            "window_steps_per_sec": [round(r, 3) for r in rates],
        })
    out = dict(rows[0])
    out["decode_latency_ms_per_record"] = float(decode_latency_ms)
    if len(rows) > 1:
        out["rows"] = rows[1:]
        if rows[0]["value"] > 0:
            out["speedup_vs_w0"] = {
                f"w{r['decode_workers']}":
                    round(r["value"] / rows[0]["value"], 2)
                for r in rows[1:]
            }
    return out


# ------------------------------------------------------------- convergence --
def _augment_shifts(x, y, shifts=(-2, -1, 0, 1, 2)):
    """Static shift augmentation (every (dr, dc) pair in ``shifts``^2):
    the standard small-data trick for digit images. Input is NHWC."""
    xs, ys = [], []
    for dr in shifts:
        for dc in shifts:
            xs.append(np.roll(np.roll(x, dr, axis=1), dc, axis=2))
            ys.append(y)
    return np.concatenate(xs), np.concatenate(ys)


def _convergence_data(train_n, test_n, source):
    """Resolve the convergence data source, most-real first.

    Order: MNIST cache -> network-guarded MNIST fetch -> scikit-learn's
    bundled REAL handwritten digits (UCI, 1,797 genuine scans) -> the
    synthetic class-template stand-in (last resort; proves the harness,
    not the model). Returns (x_train, y_train, x_test, y_test, label,
    recipe) where recipe tunes training for tiny real sets: static shift
    augmentation + stepped LR decay (small data overfits a constant-LR
    Adam run before it generalizes past 98%).
    """
    recipe = {"augment": False, "lr_drops": {}}
    if source not in ("auto", "synthetic"):
        raise ValueError(f"unknown convergence source {source!r}")
    if source == "auto":
        try:
            # Both splits must come from the same source: a machine with
            # only one split cached must not train on real data and score
            # on synthetic (or vice versa).
            x_train, y_train = dtpu.data.load_mnist(
                "train", synthetic_ok=False)
            x_test, y_test = dtpu.data.load_mnist("test", synthetic_ok=False)
            return x_train, y_train, x_test, y_test, "mnist (local cache)", recipe
        except FileNotFoundError:
            pass
        # Network-guarded fetch of the real IDX files (no-op without
        # egress): the north-star convergence row should be real MNIST
        # wherever the bench machine permits it.
        if dtpu.data.fetch_mnist() is not None:
            x_train, y_train = dtpu.data.load_mnist(
                "train", synthetic_ok=False)
            x_test, y_test = dtpu.data.load_mnist("test", synthetic_ok=False)
            return x_train, y_train, x_test, y_test, "mnist (fetched)", recipe
        try:
            x_train, y_train = dtpu.data.load_digits_real("train")
            x_test, y_test = dtpu.data.load_digits_real("test")
            # batch 128 (not the reference's 256): 1,438 base images at
            # batch 256 is 5 gradient steps per base-set epoch — too few
            # to converge past 98% in a bounded run.
            recipe = {"augment": True, "lr_drops": {12: 3e-4, 18: 1e-4},
                      "batch": 128}
            label = ("real handwritten digits (sklearn/UCI bundled set, "
                     "1,797 genuine scans, bilinear 8x8->28x28, stratified "
                     "80/20 holdout; MNIST IDX files absent and no network "
                     "egress on this machine)")
            return x_train, y_train, x_test, y_test, label, recipe
        except (FileNotFoundError, ImportError):
            pass
    x_train, y_train = dtpu.data.load_mnist(
        "train", force_synthetic=True, synthetic_train_n=train_n)
    x_test, y_test = dtpu.data.load_mnist(
        "test", force_synthetic=True, synthetic_test_n=test_n)
    label = ("synthetic (class-template MNIST stand-in; no MNIST cache, no "
             "network egress, and no sklearn digits on this machine)"
             if source == "auto" else
             "synthetic (class-template MNIST stand-in, forced)")
    return x_train, y_train, x_test, y_test, label, recipe


def bench_convergence(batch=GLOBAL_BATCH, max_epochs=25, target=0.98,
                      train_n=60000, test_n=10000, source="auto"):
    """North-star accuracy: train the reference CNN to >= ``target`` top-1.

    The reference's own captured runs never exceed ~20% because they are
    15-step smoke tests (/root/reference/README.md:306-312, 413-415);
    BASELINE.json's north star demands >=98% at convergence. Trains on the
    most-real data source available (see ``_convergence_data``) — the
    output names which (``data`` field).

    Reports final test top-1, wall-clock seconds until the target was first
    met, and the epoch count. Evaluation happens after every epoch; eval
    time is excluded from ``seconds_to_target`` (the metric is training
    cost, not eval cost). Augmentation time (tiny real sets only) counts as
    training cost.
    """
    x_train, y_train, x_test, y_test, data_label, recipe = _convergence_data(
        train_n, test_n, source
    )
    batch = recipe.get("batch", batch)
    x_train, y_train = x_train[:train_n], y_train[:train_n]
    x_test, y_test = x_test[:test_n], y_test[:test_n]
    base_train_n = int(x_train.shape[0])

    train_seconds = 0.0
    if recipe["augment"]:
        t0 = time.perf_counter()
        x_train, y_train = _augment_shifts(x_train, y_train)
        train_seconds += time.perf_counter() - t0

    strategy = _strategy()
    with strategy.scope():
        model = dtpu.Model(dtpu.models.mnist_cnn())
        model.compile(
            optimizer=dtpu.optim.Adam(1e-3),
            loss="sparse_categorical_crossentropy",
            metrics=["accuracy"],
        )
    model.build((28, 28, 1))

    seconds_to_target = None
    epochs_to_target = None
    best_acc = acc = 0.0
    for epoch in range(1, max_epochs + 1):
        if epoch in recipe["lr_drops"]:
            model.set_learning_rate(recipe["lr_drops"][epoch])
        t0 = time.perf_counter()
        model.fit(x_train, y_train, batch_size=batch, epochs=1, verbose=0)
        train_seconds += time.perf_counter() - t0
        acc = float(model.evaluate(x_test, y_test, batch_size=batch,
                                   verbose=0)["accuracy"])
        best_acc = max(best_acc, acc)
        if seconds_to_target is None and acc >= target:
            seconds_to_target = round(train_seconds, 2)
            epochs_to_target = epoch
            break
    return {
        "metric": "mnist_cnn_convergence_top1",
        "value": round(best_acc, 4),
        "unit": "top-1 accuracy",
        "accuracy": round(acc, 4),
        "best_accuracy": round(best_acc, 4),
        "target": target,
        "seconds_to_target": seconds_to_target,
        "epochs_to_target": epochs_to_target,
        "train_seconds_total": round(train_seconds, 2),
        "data": data_label,
        "train_n": base_train_n,
        "test_n": int(x_test.shape[0]),
    }


# ------------------------------------------------------------------- cifar --
def bench_cifar(global_batch=GLOBAL_BATCH, warmup=5, measure=50):
    """CIFAR-10-scale CNN (BASELINE.json configs[2]): the VGG-ish
    ``cifar_cnn`` at 32x32x3, data-parallel when >1 device."""
    strategy = _strategy()
    with strategy.scope():
        model = dtpu.Model(dtpu.models.cifar_cnn())
        model.compile(
            optimizer=dtpu.optim.SGD(0.01, momentum=0.9),
            loss="sparse_categorical_crossentropy",
            metrics=["accuracy"],
        )
    model.build((32, 32, 3))

    rng = np.random.default_rng(0)
    batch = model.strategy.put_batch({
        "x": rng.standard_normal((global_batch, 32, 32, 3),
                                 dtype=np.float32),
        "y": rng.integers(0, 10, (global_batch,), dtype=np.int64)
            .astype(np.int32),
    })
    steps_per_sec, window_rates = _time_steps(model, batch, warmup, measure)
    return {
        "metric": f"cifar_cnn_train_steps_per_sec_gb{global_batch}",
        "value": round(steps_per_sec, 2),
        "unit": "steps/s",
        "images_per_sec": round(steps_per_sec * global_batch, 1),
        "window_steps_per_sec": window_rates,
    }


# ---------------------------------------------------------------- resnet50 --
def bench_resnet50(global_batch=256, image_size=224, warmup=3, measure=20,
                   num_classes=1000, depth=50):
    """ResNet-50 ImageNet training step (BASELINE.json configs[3]), bf16."""
    strategy = _strategy()
    with strategy.scope():
        model = dtpu.Model(
            dtpu.models.resnet(depth, num_classes, dtype=jnp.bfloat16)
        )
        model.compile(
            optimizer=dtpu.optim.SGD(0.1, momentum=0.9),
            loss="sparse_categorical_crossentropy",
            metrics=["accuracy"],
        )
    model.build((image_size, image_size, 3))

    rng = np.random.default_rng(0)
    batch = model.strategy.put_batch({
        "x": rng.standard_normal(
            (global_batch, image_size, image_size, 3), dtype=np.float32
        ),
        "y": rng.integers(0, num_classes, (global_batch,), dtype=np.int64)
            .astype(np.int32),
    })
    steps_per_sec, window_rates = _time_steps(model, batch, warmup, measure)

    # Forward FLOPs: ~4.089 GFLOP per 224x224 image for ResNet-50 (the
    # standard published count, 2x MACs); scale quadratically for other
    # resolutions, linearly-ish for other depths via a conv-count ratio.
    if depth == 50:
        fwd_per_image = 4.089e9 * (image_size / 224.0) ** 2
    else:
        fwd_per_image = None
    out = {
        "metric": f"resnet{depth}_train_steps_per_sec_gb{global_batch}",
        "value": round(steps_per_sec, 3),
        "unit": "steps/s",
        "images_per_sec": round(steps_per_sec * global_batch, 1),
        "window_steps_per_sec": window_rates,
    }
    if fwd_per_image is not None:
        tflops = steps_per_sec * 3.0 * fwd_per_image * global_batch / 1e12
        out["tflops"] = round(tflops, 4)
        out["mfu"] = _mfu(tflops)
    return out


# ---------------------------------------------------------- transformer LM --
def _lm_fwd_flops_per_token(num_layers, d_model, seq_len, vocab):
    """Analytic matmul FLOPs per token, forward: per block qkv+proj
    (8 d^2) + MLP (2 d d_ff * 2, d_ff = 4d) + attention scores/values
    (4 s d); LM head (2 d V). Shared by every LM bench row so the
    TFLOP/MFU columns stay comparable."""
    d_ff = 4 * d_model
    return (
        num_layers * (8 * d_model**2 + 4 * d_model * d_ff
                      + 4 * seq_len * d_model)
        + 2 * d_model * vocab
    )


def _lm_bench_run(batch, seq_len, vocab, num_layers, d_model, num_heads,
                  warmup, measure, metrics=("accuracy",), **model_kw):
    """Build + compile + stage + time one LM config; returns
    (model, steps_per_sec, window_rates). Shared by bench_transformer_lm/
    bench_longctx so setup (loss, dtype, staging) can't drift between
    them."""
    rng = np.random.default_rng(0)
    tok = rng.integers(0, vocab, (batch, seq_len + 1), dtype=np.int64)
    head_chunks = model_kw.pop("head_chunks", None)
    strategy = _strategy()
    with strategy.scope():
        model = dtpu.Model(
            dtpu.models.transformer_lm(
                vocab, num_layers=num_layers, d_model=d_model,
                num_heads=num_heads, max_len=seq_len,
                dtype=jnp.bfloat16, **model_kw,
            )
        )
        model.compile(
            optimizer=dtpu.optim.Adam(1e-4),
            loss="pallas_sparse_categorical_crossentropy",
            metrics=metrics,
            head_chunks=head_chunks,
        )
    model.build((seq_len,))
    dev_batch = model.strategy.put_batch({
        "x": tok[:, :-1].astype(np.int32),
        "y": tok[:, 1:].astype(np.int32),
    })
    sps, window_rates = _time_steps(model, dev_batch, warmup, measure)
    return model, sps, window_rates


def bench_transformer_lm(batch=32, seq_len=1024, vocab=32768, num_layers=12,
                         d_model=768, num_heads=12, warmup=3, measure=15,
                         with_remat_variant=True):
    """~136M-param LM (GPT-2-small shape, untied head), Pallas fused xent on
    the 32k-vocab head. Also reports a remat-policy variant (per-block
    jax.checkpoint with dots_with_no_batch_dims_saveable) — the memory/
    recompute trade long-context configs run with.

    batch 32 (round 5; was 8): per-op profiling showed the B=8 step leaves
    the chip under-occupied AND pays the tunneled transport's per-dispatch
    gap every 68 ms — B=32 runs the same model at 4x tokens/step, lifting
    measured MFU 0.47 -> 0.53 on the same day/chip (docs/PERF.md round-5
    notes). Fits comfortably without remat at T=1024 on a 16GB v5e."""
    def run(**model_kw):
        return _lm_bench_run(batch, seq_len, vocab, num_layers, d_model,
                             num_heads, warmup, measure, **model_kw)

    model, steps_per_sec, window_rates = run()
    n_params = sum(
        int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(model.params)
    )
    del model  # free the base model's params/opt-state before the variant

    tokens = batch * seq_len
    fwd_per_token = _lm_fwd_flops_per_token(num_layers, d_model, seq_len,
                                            vocab)
    tflops = steps_per_sec * 3.0 * fwd_per_token * tokens / 1e12
    out = {
        "metric": f"transformer_lm_{n_params//1_000_000}M_train_steps_per_sec",
        "value": round(steps_per_sec, 3),
        "unit": "steps/s",
        "tokens_per_sec": round(steps_per_sec * tokens, 1),
        "params": n_params,
        "seq_len": seq_len,
        "vocab": vocab,
        "tflops": round(tflops, 4),
        "mfu": _mfu(tflops),
        "window_steps_per_sec": window_rates,
    }
    if with_remat_variant:
        _, sps_remat, win_remat = run(
            remat=True,
            remat_policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
        tfl_r = sps_remat * 3.0 * fwd_per_token * tokens / 1e12
        out["remat_policy_variant"] = {
            "policy": "dots_with_no_batch_dims_saveable",
            "value": round(sps_remat, 3),
            "tflops": round(tfl_r, 4),
            "mfu": _mfu(tfl_r),
            "window_steps_per_sec": win_remat,
        }
    return out


# -------------------------------------------------------------------- zero --
def bench_zero(vocab=512, num_layers=2, d_model=256, num_heads=4, seq_len=64,
               batch=32, warmup=2, measure=10, windows=3,
               big_vocab=2048, big_layers=4, big_d_model=768,
               hbm_cap_mb=256):
    """ZeRO memory/throughput comparison (``python bench.py zero``,
    artifact BENCH_zero.json).

    Part 1 — fixed global batch: a small Adam transformer LM trained under
    ``DataParallel`` (replicated), ``ZeroDataParallel`` (ZeRO-1) and
    ``FSDP`` (ZeRO-3 over 'data'). Reports steps/s on the compiled train
    step (median-of-3 windows, same protocol as every mode) and the
    MEASURED per-device model-state bytes (params + opt state, summed from
    shard buffer sizes — exact on any backend; the allocator peak is also
    reported where the backend exposes one, which XLA:CPU does not).
    With Adam the expected ratio vs replicated is (1+2/N)/3 for ZeRO-1 and
    ~1/N for FSDP on an N-way mesh.

    Part 2 — simulated HBM cap: a ~4x bigger LM whose replicated model
    state exceeds ``hbm_cap_mb`` per device. Replication would OOM a chip
    with that HBM; FSDP's per-device share fits, and the bench proves the
    config TRAINS by running real optimizer steps under FSDP. Replicated
    bytes are computed from the same tree's global leaf sizes (building
    the replicated model just to watch it not fit would be the OOM).
    """
    from distributed_tpu.utils.profiler import (
        device_memory_stats, tree_bytes_per_device)

    rng = np.random.default_rng(0)
    tok = rng.integers(0, vocab, (batch, seq_len + 1), dtype=np.int64)
    xb, yb = tok[:, :-1].astype(np.int32), tok[:, 1:].astype(np.int32)

    n_dev = len(jax.devices())
    strategies = [("replicated_dp", dtpu.DataParallel)]
    if n_dev > 1:
        strategies += [("zero1", dtpu.ZeroDataParallel), ("fsdp", dtpu.FSDP)]
    rows = []
    for name, strategy_cls in strategies:
        strategy = strategy_cls() if n_dev > 1 else dtpu.SingleDevice()
        with strategy.scope():
            model = dtpu.Model(dtpu.models.transformer_lm(
                vocab, num_layers=num_layers, d_model=d_model,
                num_heads=num_heads, max_len=seq_len))
            model.compile(optimizer=dtpu.optim.Adam(1e-3),
                          loss="sparse_categorical_crossentropy")
        model.build((seq_len,))
        dev_batch = model.strategy.put_batch({"x": xb, "y": yb})
        # Before timing: _time_steps donates the model's buffers into the
        # step, deleting the originals.
        state_bytes = tree_bytes_per_device(
            model.params, model.state, model.opt_state)
        sps, win = _time_steps(model, dev_batch, warmup, measure,
                               windows=windows)
        rows.append({
            "metric": f"lm_zero_{name}_steps_per_sec_gb{batch}",
            "value": round(sps, 3),
            "unit": "steps/s",
            "strategy": name,
            "model_state_bytes_per_device": state_bytes["max_bytes_per_device"],
            "allocator": device_memory_stats(),
            "window_steps_per_sec": win,
        })
        del model, dev_batch

    out = dict(rows[0])
    by_name = {r["strategy"]: r for r in rows}
    if "zero1" in by_name:
        rep = by_name["replicated_dp"]
        out["hbm_ratio_vs_replicated"] = {
            n: round(rep["model_state_bytes_per_device"]
                     / by_name[n]["model_state_bytes_per_device"], 2)
            for n in by_name if n != "replicated_dp"
        }
        out["steps_per_sec_vs_replicated"] = {
            n: round(by_name[n]["value"] / rep["value"], 2)
            for n in by_name if n != "replicated_dp"
        }

    # ---- part 2: the config replication cannot hold under the HBM cap ----
    if n_dev > 1:
        cap = int(hbm_cap_mb) * 1024 * 1024
        big_tok = rng.integers(0, big_vocab, (n_dev, seq_len + 1),
                               dtype=np.int64)
        strategy = dtpu.FSDP()
        with strategy.scope():
            big = dtpu.Model(dtpu.models.transformer_lm(
                big_vocab, num_layers=big_layers, d_model=big_d_model,
                num_heads=num_heads, max_len=seq_len))
            big.compile(optimizer=dtpu.optim.Adam(1e-3),
                        loss="sparse_categorical_crossentropy")
        big.build((seq_len,))
        fsdp_bytes = tree_bytes_per_device(
            big.params, big.state, big.opt_state)["max_bytes_per_device"]
        # Replicated per-device state = the SAME tree at global leaf sizes.
        replicated_bytes = sum(
            int(l.nbytes) for tree in (big.params, big.state, big.opt_state)
            for l in jax.tree_util.tree_leaves(tree)
            if isinstance(l, jax.Array)
        )
        hist = big.fit(big_tok[:, :-1].astype(np.int32),
                       big_tok[:, 1:].astype(np.int32),
                       batch_size=n_dev, epochs=1, steps_per_epoch=2,
                       verbose=0, seed=0)
        out["hbm_cap_row"] = {
            "hbm_cap_bytes": cap,
            "replicated_state_bytes_per_device": replicated_bytes,
            "replicated_fits": replicated_bytes <= cap,
            "fsdp_state_bytes_per_device": fsdp_bytes,
            "fsdp_fits": fsdp_bytes <= cap,
            "fsdp_trained_steps": 2,
            "fsdp_final_loss": round(float(hist.history["loss"][-1]), 4),
            "params": int(sum(
                int(np.prod(p.shape))
                for p in jax.tree_util.tree_leaves(big.params))),
        }
        del big
    if len(rows) > 1:
        out["rows"] = rows[1:]
    return out


# --------------------------------------------------------------- precision --
def bench_precision(vocab=2048, num_layers=2, d_model=512, num_heads=8,
                    seq_len=128, batch=32, warmup=2, measure=10, windows=3):
    """Mixed-precision comparison (``python bench.py precision``, artifact
    BENCH_precision.json): a matmul-bound transformer LM trained under
    ``FSDP`` (multi-device; ``SingleDevice`` on one) with
    ``compile(precision="float32")`` vs ``"mixed_bfloat16"``.

    Reports, per policy: steps/s on the compiled train step (median-of-3
    windows, the standard protocol), measured per-device model-state bytes
    (masters + Adam moments stay f32 under BOTH policies — mixed precision
    is a compute/comms lever, not an optimizer-memory one), and the
    per-step collective-traffic estimate (``comm_bytes_estimate``): under
    FSDP the per-layer param all-gathers move compute-dtype bytes, so
    mixed_bfloat16 halves ``gathered_param_bytes_per_device`` — the
    headline ratio. The MECHANISM is verified by dtype assertions (the
    policy-cast forward must produce compute-dtype logits; the cast tree
    must be bf16); steps/s is best-effort on CPU, where XLA emulates bf16
    matmuls and the 2x MXU-rate win only materializes on real TPUs.
    """
    from distributed_tpu.utils.profiler import tree_bytes_per_device

    rng = np.random.default_rng(0)
    tok = rng.integers(0, vocab, (batch, seq_len + 1), dtype=np.int64)
    xb, yb = tok[:, :-1].astype(np.int32), tok[:, 1:].astype(np.int32)
    n_dev = len(jax.devices())
    rows = []
    for pol_name in ("float32", "mixed_bfloat16"):
        strategy = dtpu.FSDP() if n_dev > 1 else dtpu.SingleDevice()
        with strategy.scope():
            model = dtpu.Model(dtpu.models.transformer_lm(
                vocab, num_layers=num_layers, d_model=d_model,
                num_heads=num_heads, max_len=seq_len))
            model.compile(optimizer=dtpu.optim.Adam(1e-3),
                          loss="sparse_categorical_crossentropy",
                          metrics=(), precision=pol_name)
        model.build((seq_len,))
        policy = model.precision
        # Dtype assertion: the policy-aware forward must actually compute
        # in the policy's dtype (this is the "mechanism verified" half of
        # the CPU story — throughput alone can't prove bf16 ran).
        with strategy.scope(), policy.scope():
            cast = policy.cast_to_compute(model.params, model._dtype_hints)
            logits_dtype = jax.eval_shape(
                lambda p, xx: model.module.apply(p, {}, xx)[0],
                cast, jax.ShapeDtypeStruct((batch, seq_len), jnp.int32),
            ).dtype
        assert logits_dtype == policy.compute_dtype, (
            f"policy {pol_name}: forward produced {logits_dtype}, expected "
            f"{policy.compute_dtype}")
        cast_dtypes = {
            str(jnp.result_type(l))
            for l in jax.tree_util.tree_leaves(cast)}
        comm = model.strategy.comm_bytes_estimate(
            model.params, compute_dtype=policy.compute_dtype)
        state_bytes = tree_bytes_per_device(
            model.params, model.state, model.opt_state)
        dev_batch = model.strategy.put_batch({"x": xb, "y": yb})
        sps, win = _time_steps(model, dev_batch, warmup, measure,
                               windows=windows)
        rows.append({
            "metric": f"lm_precision_{pol_name}_steps_per_sec_gb{batch}",
            "value": round(sps, 3),
            "unit": "steps/s",
            "precision": pol_name,
            "compute_dtype": str(policy.compute_dtype),
            "forward_logits_dtype": str(logits_dtype),
            "compute_cast_dtypes": sorted(cast_dtypes),
            "model_state_bytes_per_device":
                state_bytes["max_bytes_per_device"],
            "comm_bytes_estimate": comm,
            "window_steps_per_sec": win,
        })
        del model, dev_batch
    out = dict(rows[0])
    by = {r["precision"]: r for r in rows}
    f32, bf16 = by["float32"], by["mixed_bfloat16"]

    def _gather_ratio(key):
        a = f32["comm_bytes_estimate"][key]
        b = bf16["comm_bytes_estimate"][key]
        return round(a / b, 2) if b else None

    out["gathered_param_bytes_ratio_f32_vs_mixed"] = _gather_ratio(
        "gathered_param_bytes_per_device")
    out["grad_reduce_bytes_ratio_f32_vs_mixed"] = _gather_ratio(
        "grad_reduce_bytes_per_device")
    if f32["value"] > 0:
        out["steps_per_sec_ratio_mixed_vs_f32"] = round(
            bf16["value"] / f32["value"], 2)
    out["strategy"] = "fsdp" if n_dev > 1 else "single_device"
    if jax.default_backend() == "cpu":
        out["note"] = (
            "steps/s is best-effort on XLA:CPU, which EMULATES bf16 "
            "matmuls (often slower than f32); the mixed-precision win "
            "this artifact pins portably is the dtype mechanism "
            "(forward_logits_dtype/compute_cast_dtypes) and the 2x lower "
            "gathered-param/gradient collective bytes under FSDP — the "
            "MXU-rate speedup materializes on TPU backends"
        )
    out["rows"] = rows[1:]
    return out


# -------------------------------------------------------------- resilience --
def bench_resilience(throttled_calls=1_000_000, beats=50_000,
                     train_steps=8, kill_step=3, save_freq=2):
    """Resilience subsystem cost: (a) heartbeat overhead at steady state —
    the per-batch liveness publish Model.fit performs under a gang
    launcher, measured both on its throttled fast path (the common case:
    a monotonic-clock check) and per actual beat (file touch); (b)
    restart-to-first-step latency — a supervised single-worker training
    run is fault-injected (kill mid-epoch), and the event log's
    timestamps give the wall-clock from failure detection to the
    restarted worker's first optimizer step (process spawn + imports +
    checkpoint restore + jit recompile; the supervisor's backoff is set
    near zero so the number measures the machinery, not the policy).

    Runs the worker on XLA:CPU regardless of the bench machine's chip —
    the subsystem under test is the process lifecycle, not the matmuls.
    """
    import os
    import tempfile
    import textwrap
    from pathlib import Path

    from distributed_tpu.launch import core as launch_core
    from distributed_tpu.resilience import RestartPolicy, Supervisor
    from distributed_tpu.utils.events import EventLog

    # -- (a) heartbeat cost ------------------------------------------------
    tmp = Path(tempfile.mkdtemp(prefix="dtpu_bench_resil_"))
    hb_file = tmp / "hb"
    saved_env = os.environ.get(launch_core.HEARTBEAT_ENV)
    os.environ[launch_core.HEARTBEAT_ENV] = str(hb_file)
    try:
        launch_core.heartbeat(min_interval=0.0)  # arm file + throttle state
        t0 = time.perf_counter()
        for _ in range(throttled_calls):
            launch_core.heartbeat()  # default throttle: fast path
        throttled_ns = (time.perf_counter() - t0) / throttled_calls * 1e9
        t0 = time.perf_counter()
        for _ in range(beats):
            launch_core.heartbeat(min_interval=0.0)  # every call touches
        beat_ns = (time.perf_counter() - t0) / beats * 1e9
    finally:
        if saved_env is None:
            os.environ.pop(launch_core.HEARTBEAT_ENV, None)
        else:
            os.environ[launch_core.HEARTBEAT_ENV] = saved_env

    # -- (b) restart-to-first-step latency ---------------------------------
    restart = _restart_latency(tmp, train_steps=train_steps,
                               kill_step=kill_step, save_freq=save_freq)
    return {
        "metric": "resilience_restart_to_first_step_seconds",
        "value": restart["latency"],
        "unit": "s",
        "ok": restart["ok"],
        "attempts": restart["attempts"],
        "restarts_used": restart["restarts_used"],
        "heartbeat_throttled_ns_per_call": round(throttled_ns, 1),
        "heartbeat_beat_ns_per_call": round(beat_ns, 1),
        "note": "latency includes process spawn, imports, checkpoint "
                "restore and jit recompile on XLA:CPU (backoff ~0)",
    }


def _restart_latency(tmp, *, train_steps=8, kill_step=3, save_freq=2,
                     extra_env=None, fault=True):
    """One supervised kill-and-restart run; returns the wall-clock seconds
    from failure detection to the restarted worker's first optimizer step
    (the `bench.py resilience` part-(b) measurement, shared with
    `bench.py compile_cache` which runs it cold-vs-warm). ``extra_env``
    augments the worker environment — e.g. JAX_COMPILATION_CACHE_DIR to
    point the worker at a persistent compile cache. ``fault=False`` runs
    the same workload straight through with NO kill (latency None) —
    `compile_cache` uses it to populate the cache safely: jax's cache
    writes are not atomic, so a kill mid-write would leave a corrupt
    entry that crashes later readers (see utils/compile_cache.py)."""
    import os
    import textwrap
    from pathlib import Path

    from distributed_tpu.resilience import RestartPolicy, Supervisor
    from distributed_tpu.utils.events import EventLog

    tmp = Path(tmp)
    tmp.mkdir(parents=True, exist_ok=True)
    worker = tmp / "worker.py"
    worker.write_text(textwrap.dedent(
        """
        import os, sys
        sys.path.insert(0, os.environ["BENCH_REPO"])
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import distributed_tpu as dtpu
        from distributed_tpu.resilience import FaultInjector
        from distributed_tpu.training.callbacks import (
            LambdaCallback, ModelCheckpoint)
        from distributed_tpu.utils import events

        attempt = int(os.environ.get("DTPU_ATTEMPT", "1"))
        x, y = dtpu.data.synthetic_images(256, (28, 28), 10, 0)
        x = x[..., None].astype(np.float32) / 255.0
        m = dtpu.Model(dtpu.models.mnist_cnn())
        m.compile(optimizer=dtpu.optim.SGD(0.05), metrics=["accuracy"])
        seen = []
        def first_step(model, step, logs):
            if not seen:
                seen.append(step)
                events.emit("first_step", attempt=attempt, step=int(step))
        cbs = [ModelCheckpoint(os.environ["BENCH_CKPT"],
                               save_freq=int(os.environ["BENCH_SAVE_FREQ"]),
                               restore=True),
               LambdaCallback(on_batch_end=first_step)]
        fault = FaultInjector.from_env()
        if fault is not None:
            cbs.append(fault)
        m.fit(x, y.astype(np.int32), batch_size=32, epochs=1,
              steps_per_epoch=int(os.environ["BENCH_STEPS"]), verbose=0,
              seed=0, callbacks=cbs)
        """
    ))
    log = EventLog(tmp / "events.jsonl")
    env_extra = {
        "BENCH_REPO": os.path.dirname(os.path.abspath(__file__)),
        "BENCH_CKPT": str(tmp / "ckpt"),
        "BENCH_STEPS": str(train_steps),
        "BENCH_SAVE_FREQ": str(save_freq),
    }
    if fault:
        env_extra["DTPU_FAULT"] = f"kill:at_step={kill_step}"
        env_extra["DTPU_FAULT_MARKER"] = str(tmp / "fault_once")
    if extra_env:
        env_extra.update(extra_env)
    # max_restarts=4 (not the minimal 2): on XLA:CPU a worker running
    # executables DESERIALIZED from a warm persistent cache can
    # intermittently die of heap corruption AFTER its first step (jaxlib
    # deserialize bug, observed as SIGSEGV/SIGABRT around the step-4
    # checkpoint write while building `compile_cache`); the
    # restart-to-first-step measurement below reads the FIRST restarted
    # attempt's first_step event, which precedes any such crash, so extra
    # restarts only keep the supervised run itself finishing ok.
    sup = Supervisor(
        [sys.executable, str(worker)], 1,
        policy=RestartPolicy(max_restarts=4, backoff=0.01, backoff_max=0.01),
        checkpoint_dir=tmp / "ckpt",
        event_log=log,
        env_extra=env_extra,
    )
    result = sup.run(timeout=600.0)
    events = log.read()

    def first(kind, **match):
        for e in events:
            if e["event"] == kind and all(e.get(k) == v
                                          for k, v in match.items()):
                return e
        return None

    fail_end = first("attempt_end", attempt=1)
    resumed = first("first_step", attempt=2)
    latency = (round(resumed["ts"] - fail_end["ts"], 3)
               if (fail_end and resumed) else None)
    return {
        "latency": latency,
        "ok": result.ok,
        "attempts": result.attempts,
        "restarts_used": result.restarts_used,
    }


def bench_compile_cache(train_steps=8, kill_step=3, save_freq=2,
                        repeats=3):
    """Persistent-compile-cache payoff on the production restart path
    (ROADMAP item 0): the supervised kill-and-restart run from
    ``bench.py resilience``, measured (a) COLD — no persistent cache,
    today's default restart: the restarted worker recompiles every jit
    program from scratch — and (b) WARM — JAX_COMPILATION_CACHE_DIR
    pointed at a cache dir pre-populated by one untimed supervised run,
    so the restarted worker deserializes its executables from disk. The
    cold-vs-warm restart-to-first-step delta is the latency a warm cache
    removes from every real restart; the same cache-dir machinery
    (utils/compile_cache.py, exported by scripts/tier1.sh) is what keeps
    tier-1 under its 870s kill. Median of ``repeats`` runs each (each run
    spawns supervised worker subprocesses). Artifact:
    BENCH_compile_cache.json."""
    import tempfile
    from pathlib import Path

    tmp = Path(tempfile.mkdtemp(prefix="dtpu_bench_cc_"))
    cache_dir = tmp / "jax_cache"
    cache_dir.mkdir()
    # Workers cache EVERY compile (thresholds dropped): the mnist worker's
    # per-program compiles sit near the 1s default threshold, so the
    # default-threshold cache would capture almost nothing and the bench
    # would measure noise. The aggressive settings are exactly what
    # utils/compile_cache.enable() refuses to do for tier-1 — XLA:CPU
    # executable serialization can corrupt the heap — which is fine HERE:
    # workers are disposable (the supervisor's restart budget absorbs an
    # intermittent post-measurement crash, see _restart_latency), and the
    # latency is read from the restarted attempt's first_step event,
    # which precedes any such crash.
    env = {
        "JAX_COMPILATION_CACHE_DIR": str(cache_dir),
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "-1",
    }
    # Populate the cache once with a FAULT-FREE run (untimed): after
    # this, every program the worker compiles — on first start AND on
    # restart — is on disk. The populate run must not be kill-injected:
    # jax's cache writes are not atomic, and a kill mid-write corrupts
    # the entry for every later reader (utils/compile_cache.py); the
    # timed warm runs below only ever READ (their programs are already
    # cached), so their kills are safe.
    _restart_latency(tmp / "populate", train_steps=train_steps,
                     kill_step=kill_step, save_freq=save_freq,
                     extra_env=env, fault=False)
    colds, warms, ok = [], [], True
    for i in range(max(1, repeats)):
        cold = _restart_latency(tmp / f"cold{i}", train_steps=train_steps,
                                kill_step=kill_step, save_freq=save_freq)
        warm = _restart_latency(tmp / f"warm{i}", train_steps=train_steps,
                                kill_step=kill_step, save_freq=save_freq,
                                extra_env=env)
        ok = ok and cold["ok"] and warm["ok"]
        colds.append(cold["latency"])
        warms.append(warm["latency"])
    cold_s = float(np.median([c for c in colds if c is not None]))
    warm_s = float(np.median([w for w in warms if w is not None]))
    return {
        "metric": "supervisor_restart_to_first_step_seconds_warm_cache",
        "value": round(warm_s, 3),
        "unit": "s",
        "cold_seconds": round(cold_s, 3),
        "warm_seconds": round(warm_s, 3),
        "cold_over_warm": round(cold_s / warm_s, 2),
        "saved_seconds_per_restart": round(cold_s - warm_s, 3),
        "cache_files": len(list(cache_dir.iterdir())),
        "ok": bool(ok),
        "window_cold_seconds": colds,
        "window_warm_seconds": warms,
        "note": "same supervised kill->restart run as `bench.py "
                "resilience`: cold = no persistent compile cache (the "
                "pre-PR default, full jit recompile on restart); warm = "
                "JAX_COMPILATION_CACHE_DIR pre-populated, executables "
                "deserialized from disk",
    }


# --------------------------------------------------------------- elastic ----
_ELASTIC_WORKER = """
import os, sys, time
sys.path.insert(0, os.environ["BENCH_REPO"])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import distributed_tpu as dtpu
from distributed_tpu.data.pipeline import Pipeline
from distributed_tpu.launch import report_result
from distributed_tpu.resilience import FaultInjector
from distributed_tpu.training.callbacks import LambdaCallback, ModelCheckpoint
from distributed_tpu.utils import events

spec = dtpu.cluster.initialize()
world = spec.num_processes
attempt = int(os.environ.get("DTPU_ATTEMPT", "1"))
GB = int(os.environ["BENCH_GB"])
STEPS = int(os.environ["BENCH_STEPS"])
record_loss = os.environ.get("BENCH_RECORD_LOSS") == "1"

x, y = dtpu.data.synthetic_images(256, (8, 8), 10, 0)
strategy = dtpu.DataParallel() if world > 1 else dtpu.SingleDevice()
with strategy.scope():
    m = dtpu.Model(dtpu.nn.Sequential([
        dtpu.nn.Flatten(),
        dtpu.nn.Dense(32, activation="relu"),
        dtpu.nn.Dense(10),
    ]))
    m.compile(optimizer=dtpu.optim.SGD(0.05),
              loss="sparse_categorical_crossentropy")
m.build((8, 8))

seen_first = []
def on_step(model, step, logs):
    if not seen_first:
        seen_first.append(step)
        events.emit("first_step", attempt=attempt, step=int(step),
                    world=world)
    if spec.index == 0:
        events.emit("step_mark", attempt=attempt, world=world,
                    step=int(step),
                    loss=(float(logs["loss"]) if record_loss else None))

cbs = [ModelCheckpoint(os.environ["BENCH_CKPT"], sharded=True,
                       save_freq=int(os.environ.get("BENCH_SAVE_FREQ", "2")),
                       restore=True),
       LambdaCallback(on_batch_end=on_step)]

# Capacity-regain trigger (grow direction): rank 0 flips the supervisor's
# capacity-probe file just before the injected transient kill, so the
# restart boundary sees the regained capacity.
cap_file = os.environ.get("BENCH_CAP_FLIP_FILE")
if cap_file and spec.index == 0:
    flip_at = int(os.environ.get("BENCH_CAP_FLIP_AT", "3"))
    def flip(model, step, logs):
        if step >= flip_at:
            with open(cap_file, "w") as f:
                f.write(os.environ.get("BENCH_CAP_FLIP_TO", "4"))
    cbs.append(LambdaCallback(on_batch_end=flip))

# Permanent-loss model: the fault stays armed while the world is ABOVE the
# surviving capacity (BENCH_FAULT_ABOVE) — every relaunch at the doomed
# size dies again, which is exactly what per-rank attribution must see.
# With a once-marker (grow direction) the fault is the usual transient one.
fault = FaultInjector.from_env()
if fault is not None and world > int(os.environ.get("BENCH_FAULT_ABOVE", "0")):
    cbs.append(fault)

with Pipeline(x, y, GB, seed=0, use_native=False,
              shard=(spec.index, world)) as p:
    m.fit(p, epochs=1, steps_per_epoch=STEPS, verbose=0, callbacks=cbs)

report_result({"world": world, "final_step": int(m.step)})
"""


def _elastic_gang(tmp, *, world, min_workers, max_workers=None,
                  global_batch=64, steps=10, fault=None, fault_above=0,
                  probe_file=None, cap_flip_to=None, cap_flip_at=3,
                  record_loss=False, failure_threshold=2, max_restarts=3,
                  save_freq=2, timeout=600.0, grace=5.0):
    """One supervised elastic-gang scenario (shared by ``bench.py elastic``
    and tests/test_elastic.py): N workers train the same tiny LM-free dense
    model from per-host-sharded pipelines with sharded checkpoints; faults
    and the capacity probe come from the arguments. Returns the
    SupervisedResult plus the run's event records."""
    import os
    from pathlib import Path

    from distributed_tpu.resilience import (
        ElasticPolicy, RestartPolicy, Supervisor,
    )
    from distributed_tpu.utils.events import EventLog

    tmp = Path(tmp)
    tmp.mkdir(parents=True, exist_ok=True)
    worker = tmp / "worker.py"
    worker.write_text(_ELASTIC_WORKER)
    log = EventLog(tmp / "events.jsonl")
    env_extra = {
        "BENCH_REPO": os.path.dirname(os.path.abspath(__file__)),
        "BENCH_CKPT": str(tmp / "ckpt"),
        "BENCH_GB": str(global_batch),
        "BENCH_STEPS": str(steps),
        "BENCH_SAVE_FREQ": str(save_freq),
        "BENCH_FAULT_ABOVE": str(fault_above),
    }
    if record_loss:
        env_extra["BENCH_RECORD_LOSS"] = "1"
    if fault:
        env_extra["DTPU_FAULT"] = fault
        if fault_above == 0:
            env_extra["DTPU_FAULT_MARKER"] = str(tmp / "fault_once")
    probe = None
    if probe_file is not None:
        probe_path = Path(probe_file)

        def probe():
            return int(probe_path.read_text().strip())

        if cap_flip_to is not None:
            env_extra["BENCH_CAP_FLIP_FILE"] = str(probe_path)
            env_extra["BENCH_CAP_FLIP_AT"] = str(cap_flip_at)
            env_extra["BENCH_CAP_FLIP_TO"] = str(cap_flip_to)
    sup = Supervisor(
        [sys.executable, str(worker)], world,
        policy=RestartPolicy(max_restarts=max_restarts, backoff=0.01,
                             backoff_max=0.01),
        elastic=ElasticPolicy(
            min_workers=min_workers,
            max_workers=max_workers if max_workers is not None else world,
            failure_threshold=failure_threshold,
            probe=probe,
            divisor_of=global_batch,
        ),
        checkpoint_dir=tmp / "ckpt",
        event_log=log,
        env_extra=env_extra,
    )
    result = sup.run(timeout=timeout, grace=grace)
    return result, log.read()


def _elastic_rate(events, attempt):
    """steps/s within one attempt from its rank-0 step_mark timestamps,
    excluding the attempt's first step (jit compile)."""
    marks = sorted(
        (e["step"], e["ts"]) for e in events
        if e["event"] == "step_mark" and e["attempt"] == attempt
    )
    marks = marks[1:]
    if len(marks) < 2:
        return None
    (s0, t0), (s1, t1) = marks[0], marks[-1]
    return round((s1 - s0) / max(t1 - t0, 1e-9), 3)


def _resize_latency(events, end_attempt, first_attempt):
    """Wall-clock from the doomed attempt's end to the re-formed gang's
    first completed optimizer step — resize-to-first-step, the elastic
    sibling of ``bench.py resilience``'s restart-to-first-step."""
    end = next((e for e in events if e["event"] == "attempt_end"
                and e["attempt"] == end_attempt), None)
    first = next((e for e in events if e["event"] == "first_step"
                  and e["attempt"] == first_attempt), None)
    if end is None or first is None:
        return None
    return round(first["ts"] - end["ts"], 3)


def bench_elastic(steps=10, global_batch=64):
    """Elastic-gang cost on the production resize paths (ROADMAP item 2,
    docs/RESILIENCE.md "Elastic gangs"): a 4->2->4 world-size cycle run as
    two supervised scenarios on XLA:CPU gangs (1 device per process).

    - **shrink**: a 4-worker gang with a PERMANENT rank-1 loss (the fault
      re-fires on every relaunch above capacity). Attribution takes
      ``failure_threshold=2`` attempts, then the supervisor re-forms at
      N'=2 (64 % 3 != 0, so ``divisor_of`` snaps 3 -> 2) and the run
      completes — restoring the 4-process sharded checkpoint into the
      2-process gang through the block index.
    - **grow**: a 2-worker gang under a capacity probe; the worker flips
      the probe file to 4 right before a transient kill, so the restart
      boundary grows the gang back to 4.

    Reported: resize-to-first-step latency for both directions (process
    spawn + jax init + N'-gang formation + sharded N->N' restore + jit
    recompile) and steps/s before/after each resize. Artifact:
    BENCH_elastic.json."""
    import tempfile
    from pathlib import Path

    tmp = Path(tempfile.mkdtemp(prefix="dtpu_bench_elastic_"))

    shrink_res, shrink_ev = _elastic_gang(
        tmp / "shrink", world=4, min_workers=2, global_batch=global_batch,
        steps=steps, fault="kill:at_step=4,rank=1", fault_above=2,
        failure_threshold=2, max_restarts=3,
    )
    shrink_final = shrink_res.attempts
    shrink = {
        "from_world": 4,
        "to_world": shrink_res.world_size,
        "ok": shrink_res.ok,
        "attempts": shrink_res.attempts,
        "restarts_used": shrink_res.restarts_used,
        "resizes": shrink_res.resizes,
        "resize_to_first_step_seconds": _resize_latency(
            shrink_ev, shrink_final - 1, shrink_final),
        "steps_per_s_before": _elastic_rate(shrink_ev, 1),
        "steps_per_s_after": _elastic_rate(shrink_ev, shrink_final),
    }

    cap = tmp / "capacity"
    cap.write_text("2")
    grow_res, grow_ev = _elastic_gang(
        tmp / "grow", world=2, min_workers=2, max_workers=4,
        global_batch=global_batch, steps=steps,
        fault="kill:at_step=3,rank=0", fault_above=0,
        probe_file=cap, cap_flip_to=4, cap_flip_at=3, max_restarts=3,
    )
    grow_final = grow_res.attempts
    grow = {
        "from_world": 2,
        "to_world": grow_res.world_size,
        "ok": grow_res.ok,
        "attempts": grow_res.attempts,
        "restarts_used": grow_res.restarts_used,
        "resizes": grow_res.resizes,
        "resize_to_first_step_seconds": _resize_latency(
            grow_ev, grow_final - 1, grow_final),
        "steps_per_s_before": _elastic_rate(grow_ev, 1),
        "steps_per_s_after": _elastic_rate(grow_ev, grow_final),
    }

    return {
        "metric": "elastic_shrink_resize_to_first_step_seconds",
        "value": shrink["resize_to_first_step_seconds"],
        "unit": "s",
        "ok": bool(shrink_res.ok and grow_res.ok
                   and shrink_res.world_size == 2
                   and grow_res.world_size == 4),
        "shrink": shrink,
        "grow": grow,
        "note": "supervised XLA:CPU gangs (1 device/process) on a 1-core "
                "box; latency spans process spawn, jax init, N'-gang "
                "formation, sharded N->N' checkpoint restore through the "
                "block index, and jit recompile. steps/s are rank-0 "
                "dispatch rates excluding each attempt's compile step — "
                "on this box all workers share one core, so the per-world "
                "rates measure dispatch overhead, not chip throughput",
    }


# -------------------------------------------------------------- recovery ----
_RECOVERY_WORKER = """
import os, sys, time
sys.path.insert(0, os.environ["BENCH_REPO"])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import distributed_tpu as dtpu
from distributed_tpu.data.pipeline import Pipeline
from distributed_tpu.launch import report_result
from distributed_tpu.resilience import FaultInjector
from distributed_tpu.training.callbacks import LambdaCallback, ModelCheckpoint
from distributed_tpu.utils import events

spec = dtpu.cluster.initialize()
world = spec.num_processes
attempt = int(os.environ.get("DTPU_ATTEMPT", "1"))
GB = int(os.environ["BENCH_GB"])
STEPS = int(os.environ["BENCH_STEPS"])
WIDTH = int(os.environ["BENCH_WIDTH"])
refresh = int(os.environ.get("BENCH_REFRESH_EVERY", "1"))
record_loss = os.environ.get("BENCH_RECORD_LOSS") == "1"

x, y = dtpu.data.synthetic_images(256, (8, 8), 10, 0)
# FSDP so each worker's state shard is genuinely 1/N-sized (the (1+1/N)x
# redundancy story); single-process falls back to the whole tree.
strategy = (dtpu.FullyShardedDataParallel() if world > 1
            else dtpu.SingleDevice())
with strategy.scope():
    m = dtpu.Model(dtpu.nn.Sequential([
        dtpu.nn.Flatten(),
        dtpu.nn.Dense(WIDTH, activation="relu"),
        dtpu.nn.Dense(WIDTH, activation="relu"),
        dtpu.nn.Dense(10),
    ]))
    m.compile(optimizer=dtpu.optim.SGD(0.05, momentum=0.9),
              loss="sparse_categorical_crossentropy")
m.build((8, 8))

seen_first = []
def on_step(model, step, logs):
    if not seen_first:
        seen_first.append(step)
        events.emit("first_step", attempt=attempt, step=int(step),
                    world=world)
    if spec.index == 0 and record_loss:
        events.emit("step_mark", attempt=attempt, world=world,
                    step=int(step), loss=float(logs["loss"]))

# buddy=True arms the diskless tier from the supervisor-exported
# DTPU_BUDDY_STORE; refresh cadence 10**9 leaves the tier armed for
# restore-tier SELECTION (and its telemetry events) but never refreshed —
# the disk-tier baseline runs through the identical code path.
cbs = [ModelCheckpoint(os.environ["BENCH_CKPT"], sharded=True,
                       save_freq=int(os.environ.get("BENCH_SAVE_FREQ", "2")),
                       restore=True,
                       async_save=os.environ.get("BENCH_SYNC_SAVE") != "1",
                       buddy=True,
                       buddy_refresh_every=(refresh if refresh > 0
                                            else 10**9)),
       LambdaCallback(on_batch_end=on_step)]
fault = FaultInjector.from_env()
if fault is not None:
    cbs.append(fault)

with Pipeline(x, y, GB, seed=0, use_native=False,
              shard=(spec.index, world)) as p:
    m.fit(p, epochs=1, steps_per_epoch=STEPS, verbose=0, callbacks=cbs)

red = (m.last_fit_telemetry or {}).get("redundancy")
report_result({"world": world, "final_step": int(m.step),
               "redundancy": red})
"""


def _recovery_gang(tmp, *, world=2, width=2560, steps=8,
                   fault="kill:at_step=5,rank=1", once=True,
                   refresh_every=1, save_freq=2, global_batch=32,
                   record_loss=False, sync_save=False, max_restarts=3,
                   timeout=600.0, grace=5.0):
    """One supervised diskless-recovery scenario (shared by ``bench.py
    recovery`` and the tests/test_redundancy.py fault matrix): a
    fixed-size FSDP gang with sharded async checkpoints AND the buddy
    tier armed (``refresh_every=0`` arms selection but never refreshes —
    the disk-tier baseline), fault-injected per ``fault``. The supervisor
    owns a tmpfs buddy store and invalidates failed ranks' segments, so
    the relaunch's restore-tier selection sees exactly what a host loss
    leaves behind. Returns (SupervisedResult, events, store_root) — the
    caller removes ``store_root``."""
    import os
    from pathlib import Path

    from distributed_tpu.resilience import (
        RestartPolicy, Supervisor, ram_dir,
    )
    from distributed_tpu.utils.events import EventLog

    tmp = Path(tmp)
    tmp.mkdir(parents=True, exist_ok=True)
    worker = tmp / "worker.py"
    worker.write_text(_RECOVERY_WORKER)
    log = EventLog(tmp / "events.jsonl")
    store_root = ram_dir()
    env_extra = {
        "BENCH_REPO": os.path.dirname(os.path.abspath(__file__)),
        "BENCH_CKPT": str(tmp / "ckpt"),
        "BENCH_GB": str(global_batch),
        "BENCH_STEPS": str(steps),
        "BENCH_WIDTH": str(width),
        "BENCH_SAVE_FREQ": str(save_freq),
        "BENCH_REFRESH_EVERY": str(refresh_every),
    }
    if record_loss:
        env_extra["BENCH_RECORD_LOSS"] = "1"
    if sync_save:
        env_extra["BENCH_SYNC_SAVE"] = "1"
    if fault:
        env_extra["DTPU_FAULT"] = fault
        if once:
            env_extra["DTPU_FAULT_MARKER"] = str(tmp / "fault_once")
    sup = Supervisor(
        [sys.executable, str(worker)], world,
        policy=RestartPolicy(max_restarts=max_restarts, backoff=0.01,
                             backoff_max=0.01),
        checkpoint_dir=tmp / "ckpt",
        buddy_store_dir=store_root,
        event_log=log,
        env_extra=env_extra,
    )
    result = sup.run(timeout=timeout, grace=grace)
    return result, log.read(), store_root


def _recovery_row(events):
    """The first recovery's MTTR breakdown row from a run's events."""
    return next((e for e in events if e["event"] == "recovery"), None)


def _median(values):
    vals = [v for v in values if v is not None]
    return round(float(np.median(vals)), 4) if vals else None


def bench_recovery(width=2560, steps=8, kill_step=5, repeats=3):
    """Diskless-recovery payoff (ROADMAP item 5, docs/RESILIENCE.md
    "Recovery tiers"): the SAME supervised kill-and-restart gang protocol
    as ``bench.py resilience``/``elastic`` — 2 FSDP workers, rank 1
    killed once mid-run — recovered through (a) the BUDDY tier (per-step
    in-RAM mirror refresh; the relaunch restores the gang's state from
    tmpfs mirrors, zero disk-block reads, asserted from the
    ``restore_end`` event counters) and (b) the DISK tier (identical run
    with refreshes disabled: the sharded checkpoint restores). Reported
    per tier, median of ``repeats`` supervised runs: the restore seconds
    (the component the tier changes), the full
    detect/gang-reform/restore/recompile MTTR breakdown from the
    supervisor's ``recovery`` events, and restore-to-first-step for
    comparison with BENCH_elastic.json's 4.0s disk-path row. Artifact:
    BENCH_recovery.json."""
    import shutil
    import tempfile
    from pathlib import Path

    tmp = Path(tempfile.mkdtemp(prefix="dtpu_bench_recovery_"))
    fault = f"kill:at_step={kill_step},rank=1"

    def run_tier(name, refresh_every, i):
        res, events, store = _recovery_gang(
            tmp / f"{name}{i}", width=width, steps=steps, fault=fault,
            refresh_every=refresh_every,
        )
        row = _recovery_row(events)
        shutil.rmtree(store, ignore_errors=True)
        return res, row

    tiers = {}
    ok = True
    for name, refresh_every in (("buddy", 1), ("disk", 0)):
        rows, oks = [], []
        for i in range(max(1, repeats)):
            res, row = run_tier(name, refresh_every, i)
            oks.append(res.ok and row is not None)
            if row is not None:
                rows.append(row)
        ok = ok and all(oks)
        tiers[name] = {
            "ok": all(oks),
            "rows": rows,
            "restore_s_median": _median([r["restore_s"] for r in rows]),
            "restore_to_first_step_s_median": _median(
                [r["total_to_first_step_s"] for r in rows]),
            "gang_reform_s_median": _median(
                [r["gang_reform_s"] for r in rows]),
            "recompile_s_median": _median([r["recompile_s"] for r in rows]),
            "tiers_used": sorted({r["restore_tier"] for r in rows}),
            "disk_block_reads": [r["disk_block_reads"] for r in rows],
        }

    buddy, disk = tiers["buddy"], tiers["disk"]
    zero_disk = all(n == 0 for n in buddy["disk_block_reads"])
    restore_speedup = (
        round(disk["restore_s_median"] / buddy["restore_s_median"], 2)
        if buddy["restore_s_median"] and disk["restore_s_median"] else None
    )
    ok = bool(
        ok
        and buddy["tiers_used"] == ["buddy"]
        and disk["tiers_used"] == ["disk"]
        and zero_disk
        and buddy["restore_s_median"] < disk["restore_s_median"]
    )
    return {
        "metric": "recovery_buddy_restore_to_first_step_seconds",
        "value": buddy["restore_to_first_step_s_median"],
        "unit": "s",
        "ok": ok,
        "buddy": buddy,
        "disk": disk,
        "restore_speedup_buddy_over_disk": restore_speedup,
        "zero_disk_block_reads_on_buddy_path": zero_disk,
        "disk_baseline_elastic_json": 4.0,
        "model": f"dense {width}x{width} MLP, FSDP over 2 procs, "
                 "SGD+momentum",
        "note": "same supervised XLA:CPU 2-worker gang protocol as "
                "bench.py resilience/elastic (1-core box: latencies span "
                "process spawn, jax init, gang formation, restore, jit "
                "recompile; CPU-transport caveat per docs/PERF.md). The "
                "tier changes the RESTORE component: buddy restores the "
                "whole gang state from committed tmpfs mirrors (mmap'd "
                "raw blocks, zero disk-block reads, counters asserted), "
                "disk restores the sharded npz checkpoint. MTTR rows "
                "from the supervisor's recovery events (median of "
                f"{repeats} supervised runs per tier).",
    }


# ------------------------------------------------------------------- obs ----
_OBS_WORKER = """
import os, sys
sys.path.insert(0, os.environ["BENCH_REPO"])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import distributed_tpu as dtpu
from distributed_tpu.data.pipeline import Pipeline
from distributed_tpu.launch import report_result
from distributed_tpu.resilience import FaultInjector

spec = dtpu.cluster.initialize()
world = spec.num_processes
GB = int(os.environ["BENCH_GB"])
STEPS = int(os.environ["BENCH_STEPS"])

x, y = dtpu.data.synthetic_images(256, (8, 8), 10, 0)
strategy = dtpu.DataParallel() if world > 1 else dtpu.SingleDevice()
with strategy.scope():
    m = dtpu.Model(dtpu.nn.Sequential([
        dtpu.nn.Flatten(),
        dtpu.nn.Dense(64, activation="relu"),
        dtpu.nn.Dense(10),
    ]))
    m.compile(optimizer=dtpu.optim.SGD(0.05),
              loss="sparse_categorical_crossentropy")
m.build((8, 8))
cbs = list(filter(None, [FaultInjector.from_env()]))
with Pipeline(x, y, GB, seed=0, use_native=False,
              shard=(spec.index, world)) as p:
    m.fit(p, epochs=1, steps_per_epoch=STEPS, verbose=0, callbacks=cbs)
report_result({"world": world, "final_step": int(m.step)})
"""


def _obs_gang(tmp, *, world=2, steps=12, global_batch=32, at_step=3,
              slow_seconds=0.25, threshold=1.5, timeout=600.0, grace=5.0):
    """One supervised gang with a PERSISTENT slowdown injected on rank 1
    (``FaultInjector`` mode ``slow_steps``: every step from ``at_step``
    sleeps ``slow_seconds`` — degraded, not dead) and per-step obs
    snapshot flushes (``DTPU_OBS_FLUSH_EVERY=1``). The run completes;
    the supervisor's end-of-run skew aggregation must name rank 1 in a
    ``straggler`` event. Returns (SupervisedResult, events)."""
    import os
    from pathlib import Path

    from distributed_tpu.resilience import RestartPolicy, Supervisor
    from distributed_tpu.utils.events import EventLog

    tmp = Path(tmp)
    tmp.mkdir(parents=True, exist_ok=True)
    worker = tmp / "worker.py"
    worker.write_text(_OBS_WORKER)
    log = EventLog(tmp / "events.jsonl")
    sup = Supervisor(
        [sys.executable, str(worker)], world,
        policy=RestartPolicy(max_restarts=1, backoff=0.01, backoff_max=0.01),
        event_log=log,
        straggler_threshold=threshold,
        env_extra={
            "BENCH_REPO": os.path.dirname(os.path.abspath(__file__)),
            "BENCH_GB": str(global_batch),
            "BENCH_STEPS": str(steps),
            "DTPU_OBS_FLUSH_EVERY": "1",
            "DTPU_FAULT": (
                f"slow_steps:at_step={at_step},rank=1,"
                f"slow_seconds={slow_seconds}"
            ),
        },
    )
    result = sup.run(timeout=timeout, grace=grace)
    return result, log.read()


def _obs_overhead(global_batch=256, steps=40, windows=5):
    """Instrumented-vs-bare fit steps/s: the SAME model/data/loop, with
    the obs runtime on (default) vs ``obs.set_enabled(False)`` (spans
    degrade to plain timed blocks, registry/flight no-op — the
    pre-obs loop). Windows are interleaved bare/instrumented so clock
    drift and cache effects land on both sides; median of ``windows``
    per side. Positive ``overhead_pct`` = instrumentation cost."""
    from distributed_tpu import obs

    strategy = _strategy()
    with strategy.scope():
        model = dtpu.Model(dtpu.models.mnist_cnn())
        model.compile(
            optimizer=dtpu.optim.SGD(0.001),
            loss="sparse_categorical_crossentropy",
            metrics=["accuracy"],
        )
    model.build((28, 28, 1))
    n = max(global_batch * 4, 256)
    x, y = dtpu.data.synthetic_images(n, (28, 28), 10, 0)
    x = x[..., None].astype(np.float32) / 255.0
    y = y.astype(np.int32)

    def one_fit():
        t0 = time.perf_counter()
        model.fit(x, y, batch_size=global_batch, epochs=1,
                  steps_per_epoch=steps, verbose=0, shuffle=False)
        return steps / (time.perf_counter() - t0)

    one_fit()  # compile + warm; excluded from both sides
    bare, inst = [], []
    try:
        for _ in range(max(1, windows)):
            obs.set_enabled(False)
            bare.append(one_fit())
            obs.set_enabled(True)
            inst.append(one_fit())
    finally:
        obs.set_enabled(True)
    bare_sps = float(np.median(bare))
    inst_sps = float(np.median(inst))
    return {
        "bare_steps_per_sec": round(bare_sps, 3),
        "instrumented_steps_per_sec": round(inst_sps, 3),
        "window_bare": [round(r, 3) for r in bare],
        "window_instrumented": [round(r, 3) for r in inst],
        "overhead_pct": round((bare_sps - inst_sps) / bare_sps * 100.0, 3),
        "steps_per_window": steps,
        "windows": len(bare),
    }


def bench_obs(global_batch=256, steps=40, windows=5, gang_steps=12,
              slow_seconds=0.25, threshold=1.5):
    """Observability runtime cost + straggler attribution (``python
    bench.py obs``, artifact BENCH_obs.json; docs/OBSERVABILITY.md):

    (a) the overhead gate — mnist_cnn fit through the REAL instrumented
    hot path (spans, registry, flight records, snapshot windows) vs the
    identical loop with obs disabled, interleaved windows, ASSERTED
    <= 3% steps/s; and (b) the attribution gate — a supervised 2-worker
    gang with a ``slow_steps`` fault on rank 1, whose end-of-run skew
    aggregation must emit a ``straggler`` event naming rank 1 (keyed on
    host SELF time: collectives equalize wall across a synchronous gang,
    so the victim's wait shows in its dispatch bucket while the
    straggler's slowdown shows in its self time)."""
    import shutil
    import tempfile
    from pathlib import Path

    overhead = _obs_overhead(global_batch=global_batch, steps=steps,
                             windows=windows)
    tmp = Path(tempfile.mkdtemp(prefix="dtpu_bench_obs_"))
    try:
        result, events = _obs_gang(tmp, steps=gang_steps,
                                   slow_seconds=slow_seconds,
                                   threshold=threshold)
        stragglers = [e for e in events if e["event"] == "straggler"]
        skews = [e for e in events if e["event"] == "rank_skew"]
        dumps = [e for e in events if e["event"] == "flight_dump"]
        straggler_row = stragglers[-1] if stragglers else None
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    ok_overhead = overhead["overhead_pct"] <= 3.0
    ok_straggler = bool(
        result.ok and straggler_row is not None
        and straggler_row.get("rank") == 1
    )
    return {
        "metric": "obs_instrumentation_overhead_pct",
        "value": overhead["overhead_pct"],
        "unit": "%",
        "ok": bool(ok_overhead and ok_straggler),
        "overhead": overhead,
        "overhead_gate_pct": 3.0,
        "straggler": {
            "ok": ok_straggler,
            "injected_rank": 1,
            "detected_rank": (straggler_row or {}).get("rank"),
            "skew": (straggler_row or {}).get("skew"),
            "threshold": threshold,
            "slow_seconds": slow_seconds,
            "row": straggler_row,
            "rank_skew": skews[-1] if skews else None,
            "flight_dumps": len(dumps),
        },
        "note": "overhead pair: interleaved bare/instrumented fit windows "
                "on the mnist_cnn hot path (median of "
                f"{overhead['windows']}; 1-core box — dispatch jitter per "
                "docs/PERF.md). straggler row: supervised XLA:CPU "
                "2-worker DP gang, rank 1 degraded by slow_steps "
                f"({slow_seconds}s/step); skew computed on per-step host "
                "self time from per-step metrics_snapshot flushes over "
                "DTPU_EVENT_LOG.",
    }


# ------------------------------------------------------------ long context --
def bench_longctx(configs=((2, 4096, False), (2, 4096, True),
                           (1, 8192, True), (1, 16384, True),
                           (1, 32768, True), (1, 65536, True, 8)),
                  vocab=32768, num_layers=12, d_model=768, num_heads=12,
                  warmup=3, measure=20):
    """Single-chip long-context rows (docs/PERF.md table): the 136M LM at
    (batch, seq, remat[, head_chunks]) configs — flash attention keeps
    attention O(T), remat + dots_with_no_batch_dims_saveable bounds block
    residuals, and the T=65,536 row adds compile(head_chunks=8): the
    (T, vocab) logits (4.3 GB bf16, twice that with the cotangent) never
    materialize, which is what makes 64k context fit one 16 GB chip.
    Opt-in mode (``python bench.py longctx``): ~6 large compiles.
    """
    rows = []
    for cfg in configs:
        batch, seq_len, remat = cfg[0], cfg[1], cfg[2]
        head_chunks = cfg[3] if len(cfg) > 3 else None
        kw = {}
        if remat:
            kw = dict(
                remat=True,
                remat_policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            )
        if head_chunks:
            kw["head_chunks"] = head_chunks
        model, sps, win = _lm_bench_run(batch, seq_len, vocab, num_layers,
                                        d_model, num_heads, warmup, measure,
                                        metrics=(), **kw)
        tokens = batch * seq_len
        fwd_per_token = _lm_fwd_flops_per_token(num_layers, d_model,
                                                seq_len, vocab)
        tflops = sps * 3.0 * fwd_per_token * tokens / 1e12
        rows.append({
            "metric": f"lm_longctx_b{batch}_t{seq_len}"
                      f"{'_remat' if remat else ''}"
                      f"{f'_hc{head_chunks}' if head_chunks else ''}",
            "value": round(sps * tokens, 1),
            "unit": "tokens/s",
            "steps_per_sec": round(sps, 3),
            "tflops": round(tflops, 4),
            "mfu": _mfu(tflops),
            "window_steps_per_sec": win,
        })
        del model
    out = rows[0]
    if len(rows) > 1:
        # "rows", not "extra": main() uses "extra" for the flat top-level
        # list, and a nested "extra" would hide rows from consumers that
        # flatten one level.
        out = dict(out)
        out["rows"] = rows[1:]
    return out


# ---------------------------------------------------------------- serving --
def bench_serve(num_requests=32, max_slots=8, block_size=16, vocab=512,
                num_layers=4, d_model=256, num_heads=8, max_len=128,
                prompt_range=(8, 64), new_range=(8, 64), seed=0,
                repeats=3):
    """Continuous batching + paged KV cache (serving.Engine) vs the
    static-batch ``generate()`` baseline on a heterogeneous-length
    workload (prompt and response lengths drawn uniformly from
    ``prompt_range`` / ``new_range``). The static baseline does what a
    static-batch server does: take requests in arrival order, ``max_slots``
    at a time, pad every prompt in the batch to the batch's longest, and
    decode until the batch's LONGEST response is done — early finishers
    burn their slot as padding, and nothing new starts until the whole
    batch drains. Throughput counts only the USEFUL tokens (each
    request's own max_new_tokens); a request's first token is available
    when its batch returns (generate() is all-or-nothing), which is what
    continuous batching's per-request TTFT is up against. Both paths are
    fully warmed (one dry run) before timing; median of ``repeats`` runs.
    Artifact: BENCH_serve.json (docs/SERVING.md, docs/PERF.md)."""
    import distributed_tpu.serving as serving

    model = dtpu.Model(dtpu.models.transformer_lm(
        vocab, num_layers=num_layers, d_model=d_model, num_heads=num_heads,
        max_len=max_len,
    ))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((32,))

    rng = np.random.default_rng(seed)
    prompts = [
        rng.integers(0, vocab, (int(n),)).astype(np.int32)
        for n in rng.integers(prompt_range[0], prompt_range[1] + 1,
                              num_requests)
    ]
    max_news = rng.integers(new_range[0], new_range[1] + 1,
                            num_requests).astype(int)
    useful_tokens = int(np.sum(max_news))

    # One engine reused across repeats: pools allocate once, and the
    # first-dispatch warmup (compiles + buffer-layout settling) happens in
    # the dry run below, exactly as a long-lived serving process amortizes
    # it. run() resets all scheduling state; released block tables point
    # back at the trash block, so a previous run's pool contents are dead.
    engine = serving.Engine(model, max_slots, block_size, max_len=max_len)

    def run_engine():
        outs = engine.run([
            serving.Request(p, int(m)) for p, m in zip(prompts, max_news)
        ])
        return outs, engine.last_run_telemetry

    def run_static():
        """ceil(N/S) static batches; per batch: prompts right-padded to
        the batch max, decoded for the batch-max response length."""
        t0 = time.perf_counter()
        ttfts = []
        for start in range(0, num_requests, max_slots):
            ps = prompts[start:start + max_slots]
            ms = max_news[start:start + max_slots]
            t_max = max(p.size for p in ps)
            batch = np.zeros((len(ps), t_max), np.int32)
            for i, p in enumerate(ps):
                batch[i, :p.size] = p
            model.generate(batch, int(max(ms)), temperature=0.0)
            ttfts += [time.perf_counter() - t0] * len(ps)
        wall = time.perf_counter() - t0
        return wall, float(np.mean(ttfts))

    # Warm both paths: all engine buckets + every static (batch, bucket)
    # compile happen here, so the timed runs measure serving, not XLA.
    run_engine()
    run_static()

    serve_rates, serve_ttfts, last_t = [], [], None
    static_rates, static_ttfts = [], []
    for _ in range(max(1, repeats)):
        _, t = run_engine()
        last_t = t
        serve_rates.append(useful_tokens / t["total_seconds"])
        serve_ttfts.append(t["time_to_first_token"]["mean"])
        wall, ttft = run_static()
        static_rates.append(useful_tokens / wall)
        static_ttfts.append(ttft)
    serve_rate = float(np.median(serve_rates))
    static_rate = float(np.median(static_rates))
    serve_ttft = float(np.median(serve_ttfts))
    static_ttft = float(np.median(static_ttfts))
    return {
        "metric": f"serve_continuous_batching_tokens_per_sec_s{max_slots}",
        "value": round(serve_rate, 2),
        "unit": "tokens/s",
        "static_batch_tokens_per_sec": round(static_rate, 2),
        "speedup_vs_static": round(serve_rate / static_rate, 2),
        "ttft_mean_s": round(serve_ttft, 4),
        "static_ttft_mean_s": round(static_ttft, 4),
        "ttft_ratio_static_over_cb": round(static_ttft / serve_ttft, 2),
        "kv_utilization": last_t["kv_utilization"],
        "decode_steps": last_t["decode_steps"],
        "prefill_dispatches": last_t["prefill_dispatches"],
        "preemptions": last_t["preemptions"],
        "queue_wait_s": last_t["queue_wait"],
        "window_tokens_per_sec": [round(r, 2) for r in serve_rates],
        "workload": {
            "num_requests": num_requests,
            "max_slots": max_slots,
            "block_size": block_size,
            "prompt_range": list(prompt_range),
            "new_range": list(new_range),
            "useful_tokens": useful_tokens,
            "model": f"lm_l{num_layers}_d{d_model}_v{vocab}",
        },
    }


# ----------------------------------------------------------------- prefix --
def bench_prefix(num_requests=32, max_slots=8, block_size=16, vocab=512,
                 num_layers=4, d_model=256, num_heads=8, max_len=128,
                 shared_len=48, tail_range=(4, 24), new_range=(8, 32),
                 spec_k=4, seed=0, repeats=3, strict=True):
    """Serving memory economy (``python bench.py prefix``, artifact
    BENCH_prefix.json; docs/SERVING.md "Prefix caching & speculative
    decoding"): one shared-prefix + mixed-length workload on the
    lm_l4_d256 serving-bench family, four engine rows plus a fleet row.

    - baseline: the plain continuous-batching engine (the BENCH_serve
      path, re-measured here so every comparison is same-process);
    - prefix: ``Engine(prefix_cache=True)`` — ASSERTED: prefix hit rate
      > 0 and shared-prefix TTFT strictly better than the baseline's;
    - int8 KV: ``Engine(kv_dtype="int8")`` — ASSERTED: >= 1.8x
      concurrent decode slots per pool byte vs f32; greedy agreement is
      RECORDED, not asserted exact (fidelity-gated storage);
    - speculative: a truncated-depth draft (the target's first half of
      the blocks plus its embedding/head, weight-copied by layer name)
      — ASSERTED token-exact vs the vanilla engine; acceptance rate and
      tokens/dispatch RECORDED with NO speedup claim: on this 1-core
      host draft+verify walls do not transfer (the PERF.md
      measured-mechanism precedent);
    - fleet: prefix-affinity routing + suffix-only handoff — ASSERTED:
      bytes shipped strictly below full-payload bytes.

    ``strict=False`` (the tier-1 schema smoke) drops only the TTFT
    comparison gate: at smoke shapes every prefill is one
    overhead-dominated dispatch either way, so the wall-clock ordering
    is noise. Every correctness gate (parity, token-exactness, hit
    rate, slot ratio, bytes shipped) holds at every shape."""
    import distributed_tpu.serving as serving
    from distributed_tpu.fleet import ServingFleet

    model = dtpu.Model(dtpu.models.transformer_lm(
        vocab, num_layers=num_layers, d_model=d_model, num_heads=num_heads,
        max_len=max_len,
    ))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((32,))

    # Truncated-depth draft: first half of the target's residual blocks,
    # plus its embedding / positional table / final norm / head, copied
    # by layer name — the standard free-draft construction when no
    # separately-trained small model exists.
    draft = dtpu.Model(dtpu.models.transformer_lm(
        vocab, num_layers=max(1, num_layers // 2), d_model=d_model,
        num_heads=num_heads, max_len=max_len,
    ))
    draft.build((32,))
    for name in list(draft.params):
        if name in model.params:
            draft.params[name] = model.params[name]

    # Workload: two "system prompt" groups of shared_len tokens plus a
    # distinct-prompt minority, mixed-length tails and responses.
    rng = np.random.default_rng(seed)
    groups = [rng.integers(0, vocab, (shared_len,)).astype(np.int32)
              for _ in range(2)]
    prompts, shared_mask = [], []
    for i in range(num_requests):
        tail = rng.integers(
            0, vocab, (int(rng.integers(*tail_range)),)).astype(np.int32)
        if i % 4 == 3:  # every 4th prompt shares nothing
            prompts.append(tail if tail.size else np.array([1], np.int32))
            shared_mask.append(False)
        else:
            prompts.append(np.concatenate([groups[i % 2], tail]))
            shared_mask.append(True)
    max_news = rng.integers(new_range[0], new_range[1] + 1,
                            num_requests).astype(int)
    cap = max_len - (spec_k - 1)
    assert all(p.size + m <= cap for p, m in zip(prompts, max_news))
    useful_tokens = int(np.sum(max_news))

    def reqs():
        return [serving.Request(p, int(m))
                for p, m in zip(prompts, max_news)]

    def timed(engine, n=repeats):
        rates, ttfts, outs, tel = [], [], None, None
        engine.run(reqs())  # warm: compiles + (prefix) store population
        for _ in range(max(1, n)):
            outs = engine.run(reqs())
            tel = engine.last_run_telemetry
            rates.append(useful_tokens / tel["total_seconds"])
            ttfts.append(tel["time_to_first_token"]["mean"])
        return float(np.median(rates)), float(np.median(ttfts)), outs, tel

    base = serving.Engine(model, max_slots, block_size, max_len=max_len)
    base_rate, base_ttft, base_outs, base_tel = timed(base)

    pfx = serving.Engine(model, max_slots, block_size, max_len=max_len,
                         prefix_cache=True)
    pfx_rate, pfx_ttft, pfx_outs, pfx_tel = timed(pfx)
    for i, (w, g) in enumerate(zip(base_outs, pfx_outs)):
        np.testing.assert_array_equal(w, g, err_msg=f"prefix request {i}")
    pc = pfx_tel["prefix_cache"]
    assert pc["hit_rate"] > 0, pc
    if strict:
        assert pfx_ttft < base_ttft, (
            f"shared-prefix TTFT {pfx_ttft:.4f}s not better than baseline "
            f"{base_ttft:.4f}s"
        )

    q8 = serving.Engine(model, max_slots, block_size, max_len=max_len,
                        kv_dtype="int8")
    q8.run(reqs())
    q8_outs = q8.run(reqs())
    q8_tel = q8.last_run_telemetry
    slot_ratio = base.kv.bytes_per_block() / q8.kv.bytes_per_block()
    assert slot_ratio >= 1.8, (
        f"int8 KV slots-per-byte ratio {slot_ratio:.2f} < 1.8"
    )
    agree = total = 0
    for w, g, p in zip(base_outs, q8_outs, prompts):
        gw, gg = w[p.size:], g[p.size:]
        agree += int(np.sum(gw == gg))
        total += len(gw)

    spec = serving.Engine(model, max_slots, block_size, max_len=max_len,
                          draft_model=draft, spec_k=spec_k)
    spec.run(reqs())
    spec_outs = spec.run(reqs())
    spec_tel = spec.last_run_telemetry["speculative"]
    for i, (w, g) in enumerate(zip(base_outs, spec_outs)):
        np.testing.assert_array_equal(w, g, err_msg=f"spec request {i}")

    fleet = ServingFleet(model, decode_replicas=2, prefill_replicas=1,
                         max_slots=4, block_size=block_size,
                         max_len=max_len, prefix_cache=True)
    fleet.run(reqs())
    h = fleet.last_run_telemetry["handoffs"]
    assert h["suffix_trims"] > 0 and \
        0 < h["bytes_shipped"] < h["bytes_full"], h

    return {
        "metric": f"serve_prefix_cache_tokens_per_sec_s{max_slots}",
        "value": round(pfx_rate, 2),
        "unit": "tokens/s",
        "baseline_tokens_per_sec": round(base_rate, 2),
        "ttft_mean_s": round(pfx_ttft, 4),
        "baseline_ttft_mean_s": round(base_ttft, 4),
        "ttft_ratio_baseline_over_prefix": round(base_ttft / pfx_ttft, 2),
        "prefix_cache": {
            "hit_rate": pc["hit_rate"],
            "hit_tokens": pc["hit_tokens"],
            "kv_bytes_saved": pc["kv_bytes_saved"],
            "cow_copies": pc["cow_copies"],
            "evictions": pc["evictions"],
        },
        "kv_utilization": pfx_tel["kv_utilization"],
        "baseline_kv_utilization": base_tel["kv_utilization"],
        "int8_kv": {
            "concurrent_slot_ratio_vs_f32": round(slot_ratio, 2),
            "greedy_agreement": round(agree / total, 4),
            "note": "fidelity-gated storage, NOT bit-exact "
                    "(docs/PERF.md); agreement recorded, not asserted",
            "kv_utilization": q8_tel["kv_utilization"],
        },
        "speculative": {
            "k": spec_tel["k"],
            "accept_rate": spec_tel["accept_rate"],
            "tokens_per_dispatch": spec_tel["tokens_per_dispatch"],
            "token_exact_vs_vanilla": True,
            "note": "NO speedup claim: 1-core draft+verify walls do not "
                    "transfer (PERF.md measured-mechanism precedent)",
        },
        "fleet": {
            "handoff_bytes_full": h["bytes_full"],
            "handoff_bytes_shipped": h["bytes_shipped"],
            "handoff_bytes_saved": h["bytes_saved"],
            "suffix_trims": h["suffix_trims"],
            "installed": h["installed"],
        },
        "workload": {
            "num_requests": num_requests,
            "shared_prefix_requests": int(np.sum(shared_mask)),
            "shared_len": shared_len,
            "max_slots": max_slots,
            "block_size": block_size,
            "tail_range": list(tail_range),
            "new_range": list(new_range),
            "useful_tokens": useful_tokens,
            "model": f"lm_l{num_layers}_d{d_model}_v{vocab}",
            "draft": f"lm_l{max(1, num_layers // 2)}_d{d_model}_v{vocab}",
        },
    }


# ------------------------------------------------------------------- spec --
def bench_spec(vocab=512, num_layers=4, d_model=256, num_heads=8,
               max_len=128, max_slots=4, block_size=16, num_prompts=8,
               prompt_range=(6, 14), max_new=24, train_epochs=12,
               distill_lr=1e-2, distill_epochs=40, distill_rounds=3,
               spec_k=4, seed=0, repeats=3, strict=True):
    """Speculation that PAYS (``python bench.py spec``, artifact
    BENCH_spec.json; docs/SERVING.md "Draft models & gossip",
    docs/PERF.md "When speculation pays"): the three levers that turn
    speculative decoding from a loss into a win, each gated.

    - **distillation**: a layer-truncated draft accepts almost never
      (recorded baseline, ~0.02 at the real shape);
      ``rl.distill.DraftDistiller`` rounds of collect → distill → sync
      lift greedy accept_rate to an ASSERTED >= 0.5, and the token
      stream stays exactly the vanilla engine's under greedy AND
      pinned-seed sampling (both ASSERTED);
    - **virtual-timeline throughput**: tokens/s vs vanilla decode is
      asserted better at accept >= 0.5 by DISPATCH-COUNT arithmetic (a
      draft dispatch costs layers_draft/layers_target of a target
      dispatch; vanilla earns 1 token per unit) — wall-clock rates are
      RECORDED with no speedup claim, the PERF.md measured-mechanism
      precedent on this 1-core host;
    - **prefix gossip**: a gossiping 2-replica fleet adopts the warm
      replica's shared-prefix blocks onto the cold one — ASSERTED: zero
      full re-prefills in the wave, zero stale adoptions, and worst-case
      TTFT strictly better than the gossip-off fleet (which pins the
      wave behind the one warm replica) on the virtual-clock timeline;
    - **adaptive spec_k**: per-tenant rung adaptation across tenant
      churn is ASSERTED recompile-free (``_verify_jit`` trace count is
      pinned across a second run with a different tenant mix).

    The TARGET is briefly trained first (sharp logits): acceptance
    measurement on an untrained model is noise — near-tied logits flip
    argmax between dispatch shapes. ``strict=False`` (the tier-1 schema
    smoke) drops only the TTFT-ordering and virtual-speedup gates (one
    overhead-dominated dispatch either way at smoke shapes); every
    correctness gate (accept lift, token-exactness, zero re-prefills,
    stamp hygiene, trace pinning) holds at every shape."""
    import distributed_tpu.serving as serving
    from distributed_tpu.fleet import EnginePrograms, ServingFleet
    from distributed_tpu.rl.distill import DraftDistiller
    from distributed_tpu.serving.engine import SPEC_K_LADDER

    rng = np.random.default_rng(seed)
    model = dtpu.Model(dtpu.models.transformer_lm(
        vocab, num_layers=num_layers, d_model=d_model, num_heads=num_heads,
        max_len=max_len,
    ))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((32,))
    xs = rng.integers(0, vocab, size=(64, 32)).astype(np.int32)
    model.fit(xs, np.roll(xs, -1, axis=1), batch_size=32,
              epochs=train_epochs, verbose=0)

    # The baseline draft: the target's leading quarter of the residual
    # blocks plus its embedding / positional table / final norm / head,
    # copied by layer name (the bench_prefix free-draft construction,
    # shallower — the virtual-timeline arithmetic charges each draft
    # dispatch at layers_draft/layers_target of a target dispatch).
    draft_layers = max(1, num_layers // 4)
    draft = dtpu.Model(dtpu.models.transformer_lm(
        vocab, num_layers=draft_layers, d_model=d_model,
        num_heads=num_heads, max_len=max_len,
    ))
    draft.build((32,))
    for name in list(draft.params):
        if name in model.params:
            # COPIES, not references: distillation trains the draft
            # through the donating fit path — aliased buffers would let
            # the draft's train step delete the target's own params.
            draft.params[name] = jax.tree_util.tree_map(
                lambda x: jax.numpy.array(x, copy=True),
                model.params[name])

    cap = max_len - (max(spec_k, max(SPEC_K_LADDER)) - 1)
    prompts = [
        rng.integers(0, vocab, size=int(s)).astype(np.int32)
        for s in rng.integers(prompt_range[0], prompt_range[1], num_prompts)
    ]
    assert all(p.size + max_new <= cap for p in prompts)
    useful_tokens = num_prompts * max_new

    def reqs(seed0=None):
        return [serving.Request(p, int(max_new),
                                seed=None if seed0 is None else seed0 + i)
                for i, p in enumerate(prompts)]

    def timed(engine, n=repeats):
        rates, outs, tel = [], None, None
        engine.run(reqs())  # warm: compiles
        for _ in range(max(1, n)):
            outs = engine.run(reqs())
            tel = engine.last_run_telemetry
            rates.append(useful_tokens / tel["total_seconds"])
        return float(np.median(rates)), outs, tel

    # ------------------------------------------------- distillation gate
    eng = serving.Engine(model, max_slots, block_size, max_len=max_len,
                         draft_model=draft, spec_k=spec_k)
    _, _, cold_tel = timed(eng, n=1)
    cold = cold_tel["speculative"]
    dist = DraftDistiller(eng, draft, learning_rate=float(distill_lr))
    rows = dist.fit(prompts, max_new_tokens=max_new, epochs=distill_epochs,
                    rounds=distill_rounds)
    spec_rate, spec_outs, warm_tel = timed(eng)
    warm = warm_tel["speculative"]
    assert warm["accept_rate"] >= 0.5, (
        f"distilled accept_rate {warm['accept_rate']} < 0.5 "
        f"(baseline {cold['accept_rate']})"
    )
    assert warm["accept_rate"] > cold["accept_rate"]
    assert rows[0]["loss_last"] < rows[0]["loss_first"]

    vanilla = serving.Engine(model, max_slots, block_size, max_len=max_len)
    vanilla_rate, vanilla_outs, _ = timed(vanilla)
    for i, (w, g) in enumerate(zip(vanilla_outs, spec_outs)):
        np.testing.assert_array_equal(w, g, err_msg=f"greedy request {i}")

    # Pinned-seed sampling: the verify path reuses the engine's
    # per-token key derivation, so the sampled stream is bit-identical.
    sv = serving.Engine(model, max_slots, block_size, max_len=max_len,
                        temperature=1.0, top_k=8)
    ss = serving.Engine(model, max_slots, block_size, max_len=max_len,
                        temperature=1.0, top_k=8, draft_model=draft,
                        spec_k=spec_k)
    a = sv.run(reqs(seed0=1000))
    b = ss.run(reqs(seed0=1000))
    for i, (w, g) in enumerate(zip(a, b)):
        np.testing.assert_array_equal(w, g, err_msg=f"sampled request {i}")

    # ------------------------------------- virtual-timeline throughput
    draft_cost = draft_layers / num_layers
    units_per_round = 1.0 + spec_k * draft_cost
    tpd = warm["tokens_per_dispatch"]
    virtual_speedup = tpd / units_per_round
    if strict and warm["accept_rate"] >= 0.5:
        assert virtual_speedup > 1.0, (
            f"{tpd} tokens per {units_per_round} target-dispatch units "
            f"does not beat vanilla's 1/unit at accept "
            f"{warm['accept_rate']}"
        )

    # ------------------------------------------------ prefix gossip gate
    programs = EnginePrograms(model)
    shared = rng.integers(0, vocab, size=2 * block_size).astype(np.int32)

    def gossip_wave(gossip, seed0):
        g = np.random.default_rng(seed0)
        fl = ServingFleet(model, decode_replicas=2, prefill_replicas=0,
                          max_slots=2, block_size=block_size,
                          max_len=max_len, prefix_cache=True,
                          prefix_gossip=gossip, programs=programs)

        def mk(n, s0):
            return [serving.Request(np.concatenate([
                shared, g.integers(0, vocab, size=3 + i).astype(np.int32),
            ]), 16, seed=s0 + i) for i in range(n)]

        fl.run(mk(1, 100))  # warms one replica's store + advertisement
        outs = fl.run(mk(3, 0))  # same-instant shared-prefix wave
        return fl, outs

    gossip_wave(True, 5)  # throwaway: traces the adoption gather/scatter
    fl_on, out_on = gossip_wave(True, 7)
    fl_off, out_off = gossip_wave(False, 7)
    tel_on = fl_on.last_run_telemetry
    gsp = tel_on["gossip"]
    assert gsp["adoptions"] >= 1 and gsp["stale_rejected"] == 0, gsp
    full_prefills = sum(
        r["prefills_full"]
        for r in tel_on["decode_pool"]["replicas"].values()
    )
    # the only full prefill ever is the warm-up request's first-compute:
    # every wave request admitted from cached or adopted blocks
    assert full_prefills == 1, full_prefills
    ttft_on = tel_on["time_to_first_token"]["max"]
    ttft_off = fl_off.last_run_telemetry["time_to_first_token"]["max"]
    if strict:
        assert ttft_on < ttft_off, (ttft_on, ttft_off)
    for w, g in zip(out_on, out_off):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(g))

    # ------------------------------------------------- adaptive spec_k
    ad = serving.Engine(model, max_slots, block_size, max_len=max_len,
                        draft_model=draft, spec_k="adaptive")
    ad.run(reqs()[:4], tenants=["a", "a", "b", "b"])
    traces = ad._verify_jit._cache_size()
    ad.run(reqs(seed0=50)[:4], tenants=["b", "c", "c", "a"])
    assert ad._verify_jit._cache_size() == traces, "adaptive-k recompiled"
    assert traces <= sum(1 for k in SPEC_K_LADDER if k >= 2)
    ad_tel = ad.last_run_telemetry["speculative"]

    return {
        "metric": "spec_decode_distilled_accept_rate",
        "value": warm["accept_rate"],
        "unit": "accept_rate",
        "draft": {
            "construction": "layer-truncated, then distilled "
                            "(rl.distill.DraftDistiller)",
            "layers": draft_layers,
            "target_layers": num_layers,
            "baseline_accept_rate": cold["accept_rate"],
            "distilled_accept_rate": warm["accept_rate"],
            "distill_rounds": distill_rounds,
            "distill_epochs": distill_epochs,
            "distill_lr": distill_lr,
            "distill_loss_first": round(rows[0]["loss_first"], 4),
            "distill_loss_last": round(rows[-1]["loss_last"], 4),
            "draft_staleness": warm["draft_staleness"],
        },
        "virtual_timeline": {
            "tokens_per_dispatch": tpd,
            "draft_cost_per_dispatch": round(draft_cost, 4),
            "units_per_round": round(units_per_round, 4),
            "speedup_vs_vanilla": round(virtual_speedup, 3),
            "vanilla_tokens_per_unit": 1.0,
            "note": "dispatch-count arithmetic: a draft dispatch costs "
                    "layers_draft/layers_target of a target dispatch "
                    "(docs/PERF.md 'When speculation pays')",
        },
        "wall_clock": {
            "spec_tokens_per_sec": round(spec_rate, 2),
            "vanilla_tokens_per_sec": round(vanilla_rate, 2),
            "note": "NO wall-clock speedup claim: 1-core draft+verify "
                    "walls do not transfer (PERF.md measured-mechanism "
                    "precedent)",
        },
        "token_exact": {
            "greedy": True,
            "pinned_seed": True,
            "sampling": "temperature=1.0 top_k=8 pinned request seeds",
        },
        "gossip": {
            "ttft_max_on_s": round(ttft_on, 4),
            "ttft_max_off_s": round(ttft_off, 4),
            "adoptions": gsp["adoptions"],
            "adopted_blocks": gsp["adopted_blocks"],
            "stale_rejected": gsp["stale_rejected"],
            "wave_full_reprefills": full_prefills - 1,
            "note": "virtual-clock fleet timeline (docs/SERVING.md "
                    "'Fleet'): real dispatch walls, virtual arrivals",
        },
        "adaptive_k": {
            "ladder": list(SPEC_K_LADDER),
            "tenant_k": ad_tel["tenant_k"],
            "k_adjustments": ad_tel["k_adjustments"],
            "verify_traces": traces,
            "recompile_free_across_tenant_churn": True,
        },
        "workload": {
            "num_prompts": num_prompts,
            "prompt_range": list(prompt_range),
            "max_new_tokens": max_new,
            "max_slots": max_slots,
            "block_size": block_size,
            "spec_k": spec_k,
            "useful_tokens": useful_tokens,
            "model": f"lm_l{num_layers}_d{d_model}_v{vocab}",
            "draft_model": f"lm_l{draft_layers}_d{d_model}_v{vocab}",
        },
    }


# ------------------------------------------------------------------ fleet --
def bench_fleet(num_requests=64, replica_counts=(1, 2, 4), max_slots=4,
                block_size=16, vocab=512, num_layers=4, d_model=256,
                num_heads=8, max_len=128, prompt_range=(8, 32),
                new_range=(32, 96), burst_size=16, burst_gap_s=0.15,
                kill_replicas=2, kill_at_step=8, seed=0, strict=True):
    """Disaggregated serving fleet (``python bench.py fleet``, artifact
    BENCH_fleet.json; docs/SERVING.md "Fleet"). Three pinned facts:

    1. **Scaling** — aggregate useful tokens/s vs decode-replica count
       under the SAME bursty open-loop arrival process (bursts of
       ``burst_size`` requests every ``burst_gap_s`` fleet-seconds).
       Asserted strictly increasing across ``replica_counts``: with the
       queue deeper than one replica's slots, added replicas drain real
       decode work in parallel. The prefill pool scales as ceil(R/2) so
       prompt caching does not become the artificial bottleneck.
    2. **Tail latency** — per-request TTFT p50/p99 from the fleet's
       lifecycle rows. R=1 saturates (the queue builds across bursts, so
       p99 >> p50); the same workload at the largest R shows what the
       added replicas buy at the tail.
    3. **Kill-a-replica** — re-runs the ``kill_replicas`` row with
       ``FaultInjector(mode="replica_kill")`` tearing one decode replica
       down mid-decode. Gate: ZERO lost requests and per-request outputs
       token-exact vs the unfaulted run of the same shape (greedy
       decode; the router requeues, survivors re-prefill).

    Clock honesty (the PERF.md measured-mechanism precedent): replicas
    are cooperative objects on one host — every dispatch is real JAX
    compute timed for real, but each replica accrues its own VIRTUAL
    timeline and fleet makespan is their parallel composition, which is
    what a process-per-replica deployment computes and a 1-core box
    cannot run for real. The artifact records the clock model; the
    MECHANISMS (routing, handoff, requeue, autoscaling) are identical on
    real fleets.
    """
    import distributed_tpu.fleet as fleet_lib
    import distributed_tpu.serving as serving
    from distributed_tpu.resilience import FaultInjector

    model = dtpu.Model(dtpu.models.transformer_lm(
        vocab, num_layers=num_layers, d_model=d_model,
        num_heads=num_heads, max_len=max_len,
    ))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((32,))

    rng = np.random.default_rng(seed)
    prompts = [
        rng.integers(0, vocab, (int(n),)).astype(np.int32)
        for n in rng.integers(prompt_range[0], prompt_range[1] + 1,
                              num_requests)
    ]
    max_news = rng.integers(new_range[0], new_range[1] + 1,
                            num_requests).astype(int)
    useful_tokens = int(np.sum(max_news))
    arrivals = [
        (i // burst_size) * burst_gap_s for i in range(num_requests)
    ]

    def requests():
        return [serving.Request(p, int(m))
                for p, m in zip(prompts, max_news)]

    def build(r, *, fault=None, programs=None):
        return fleet_lib.ServingFleet(
            model, decode_replicas=r,
            prefill_replicas=max(1, r // 2), max_slots=max_slots,
            block_size=block_size, max_len=max_len, fault=fault,
            programs=programs,
        )

    # Warm every program the sweep will hit (prefill buckets for fresh
    # prompts AND for requeue-path re-prefills of prompt+generated
    # contexts, plus the decode shape) so virtual timelines measure
    # serving, not XLA. Long-context re-prefill is exercised by a
    # max-length request.
    warm = build(1)
    long_p = rng.integers(0, vocab, (max_len - 8,)).astype(np.int32)
    warm.run(requests()[:4] + [serving.Request(long_p, 4)])
    programs = warm.programs
    del warm

    rows = []
    outputs_by_r = {}
    for r in replica_counts:
        fl = build(int(r), programs=programs)
        outs = fl.run(requests(), arrival_times=arrivals)
        t = fl.last_run_telemetry
        assert t["lost_requests"] == 0, t["lost_requests"]
        outputs_by_r[int(r)] = [np.asarray(o) for o in outs]
        rows.append({
            "decode_replicas": int(r),
            "prefill_replicas": max(1, int(r) // 2),
            "tokens_per_sec": t["tokens_per_sec"],
            "makespan_s": t["makespan_s"],
            "ttft_mean_s": t["time_to_first_token"]["mean"],
            "ttft_p50_s": t["time_to_first_token"]["p50"],
            "ttft_p99_s": t["time_to_first_token"]["p99"],
            "queue_depth_peak": t["queue_depth_peak"],
            "handoffs_installed": t["handoffs"]["installed"],
            "decode_steps": t["decode_steps"],
            "preemptions": t["preemptions"],
        })
    # ``strict=False`` (the smoke, mirroring bench_prefix) drops only
    # this scaling gate: the virtual timelines are built from MEASURED
    # per-dispatch costs, so on a loaded 1-core box a tiny-shape R=2 row
    # can time slower than R=1 by noise alone. Every mechanism gate
    # (zero lost, token-exact kill recovery) still asserts.
    if strict:
        for prev, cur in zip(rows, rows[1:]):
            assert cur["tokens_per_sec"] > prev["tokens_per_sec"], (
                f"aggregate tokens/s must increase with decode replicas: "
                f"{[r['tokens_per_sec'] for r in rows]}"
            )
    base = rows[0]["tokens_per_sec"]
    for row in rows:
        row["speedup_vs_r1"] = round(row["tokens_per_sec"] / base, 2)

    # Kill-a-replica: same workload/shape as the kill_replicas row,
    # one decode replica torn down mid-decode; the reconcile loop
    # respawns capacity and the router requeues the dead replica's
    # in-flight work.
    fault = FaultInjector("replica_kill", replica="decode-1",
                          at_step=kill_at_step)
    fk = build(int(kill_replicas), fault=fault, programs=programs)
    kouts = fk.run(requests(), arrival_times=arrivals)
    kt = fk.last_run_telemetry
    ref = outputs_by_r[int(kill_replicas)]
    token_exact = all(
        np.array_equal(a, b) for a, b in zip(ref, kouts)
    )
    assert kt["lost_requests"] == 0, kt["lost_requests"]
    assert len(kt["decode_pool"]["kills"]) == 1, kt["decode_pool"]["kills"]
    assert token_exact, "kill-recovery outputs diverged from unfaulted run"
    kill_row = {
        "decode_replicas": int(kill_replicas),
        "killed_replica": kt["decode_pool"]["kills"][0]["replica"],
        "kill_at_decode_step": kill_at_step,
        "requeued_requests": kt["decode_pool"]["kills"][0]["requeued"],
        "lost_requests": kt["lost_requests"],
        "token_exact_vs_unfaulted": bool(token_exact),
        "tokens_per_sec": kt["tokens_per_sec"],
        "ttft_p99_s": kt["time_to_first_token"]["p99"],
        "fallback_reprefills": kt["handoffs"]["fallback_reprefill"],
        "respawned": any(
            e["event"] == "spawn" for e in kt["decode_pool"]["events"]
        ),
    }

    top = rows[-1]
    return {
        "metric": f"fleet_aggregate_tokens_per_sec_r{top['decode_replicas']}",
        "value": top["tokens_per_sec"],
        "unit": "tokens/s",
        "speedup_vs_one_replica": top["speedup_vs_r1"],
        "ttft_p50_s": top["ttft_p50_s"],
        "ttft_p99_s": top["ttft_p99_s"],
        "scaling": rows,
        "kill": kill_row,
        "arrivals": {
            "process": "bursty open-loop",
            "num_requests": num_requests,
            "burst_size": burst_size,
            "burst_gap_s": burst_gap_s,
            "useful_tokens": useful_tokens,
        },
        "clock": "virtual: per-replica timelines over real dispatch "
                 "walls (single-host harness; docs/SERVING.md 'Fleet')",
        "spinup_alloc_s": kt["decode_pool"]["spinup_alloc_s"],
        "workload": {
            "max_slots": max_slots,
            "block_size": block_size,
            "prompt_range": list(prompt_range),
            "new_range": list(new_range),
            "model": f"lm_l{num_layers}_d{d_model}_v{vocab}",
        },
    }


# ---------------------------------------------------------------- service --
def bench_service(num_requests=18, replica_counts=(1, 2, 4), max_slots=2,
                  block_size=4, vocab=64, num_layers=2, d_model=32,
                  num_heads=2, max_len=64, build_len=64,
                  prompt_range=(4, 10), new_range=(8, 16), burst_size=6,
                  burst_gap_s=1.0, kill_replicas=2, kill_after_tokens=8,
                  flood_requests=8, paying_requests=4, quota_rate=2.0,
                  quota_burst=40.0, ttft_bound_s=30.0, deadline_s=240.0,
                  seed=0, sections=("scaling", "kill", "quota")):
    """The serving fleet as REAL processes on WALL time (``python
    bench.py fleet --clock wall``, artifact BENCH_service.json;
    docs/SERVING.md "Running as a service"). This is the measured
    answer to BENCH_fleet.json's virtual-clock caveat: every number
    here is wall-clock across worker processes spawned with
    ``python -m distributed_tpu.serve_service.worker``. Four pinned
    facts:

    1. **Scaling** — wall tokens/s and TTFT p50/p99 at R decode
       processes under the same bursty open-loop arrivals, KV handoff
       riding /dev/shm. The strictly-increasing gate is HONEST about
       the host: R CPU-bound decode processes only speed up wall time
       when the box has >= R cores, so on smaller hosts the gate
       degrades to the mechanism facts (every replica decodes, zero
       lost, token-exact) and the artifact records which gate ran —
       the PERF.md measured-mechanism precedent.
    2. **Streaming byte-identity** — every output is assembled from
       the per-decode-step token frames a client would stream, and is
       asserted byte-identical to the non-streaming in-process
       ``Engine.run`` of the same requests (``Model.build`` is
       seed-deterministic, so worker processes hold identical params).
    3. **Kill-a-replica** — a decode WORKER PROCESS is killed
       mid-decode (after ``kill_after_tokens`` streamed tokens). Gate:
       zero lost requests, outputs token-exact, a respawned process
       absorbs the requeue, and the dead worker leaves a readable
       flight-recorder postmortem referenced from the event log
       (rendered by ``dtpu-events``).
    4. **Quotas** — a flooding tenant behind a token bucket cannot
       starve the weight-2 paying tenant: the flood is rejected at
       the front door (reason ``"quota"``) while every paying request
       finishes with TTFT p99 under ``ttft_bound_s``.

    ``sections`` picks which rows run: the scaling rows (and their
    streaming byte-identity gate) always do; ``"kill"`` and ``"quota"``
    each spawn another worker fleet (~3 s spin-up per process), so the
    tier-1 schema smoke runs scaling only — kill recovery and quota
    starvation are separately pinned by the @slow multi-process matrix
    in tests/test_serve_service.py, and the checked-in
    BENCH_service.json carries every section.
    """
    import os
    import tempfile

    from distributed_tpu.fleet import Router
    from distributed_tpu.obs.cli import summarize
    from distributed_tpu.serve_service import (
        ServeService, ServeSpec, TenantQuotas,
    )
    from distributed_tpu.serving import Engine, Request
    from distributed_tpu.utils.events import read_events

    model_cfg = dict(vocab_size=vocab, num_layers=num_layers,
                     d_model=d_model, num_heads=num_heads, max_len=max_len)
    spec = ServeSpec(model=model_cfg, build_len=build_len,
                     max_slots=max_slots, block_size=block_size,
                     max_len=max_len)

    rng = np.random.default_rng(seed)
    prompts = [
        rng.integers(0, vocab, (int(n),)).astype(np.int32)
        for n in rng.integers(prompt_range[0], prompt_range[1] + 1,
                              num_requests)
    ]
    news = [int(m) for m in rng.integers(new_range[0], new_range[1] + 1,
                                         num_requests)]
    useful_tokens = int(sum(news))
    arrivals = [(i // burst_size) * burst_gap_s
                for i in range(num_requests)]

    def requests():
        return [Request(p, m, seed=0) for p, m in zip(prompts, news)]

    # Non-streaming reference IN THIS process: the byte-identity bar
    # every service output (assembled from streamed token frames) must
    # clear. Same params as the workers — Model.build is
    # seed-deterministic.
    model = dtpu.Model(dtpu.models.transformer_lm(**model_cfg))
    model.compile(optimizer=spec.optimizer, loss=spec.loss)
    model.build((build_len,))
    reference = [np.asarray(o) for o in Engine(
        model, max_slots=max_slots, block_size=block_size, max_len=max_len
    ).run(requests())]
    del model

    def token_exact(outs):
        return all(o is not None and np.array_equal(r, o)
                   for r, o in zip(reference, outs))

    # ------------------------------------------------------- scaling rows
    rows = []
    checked = 0
    for r in replica_counts:
        svc = ServeService(spec, decode_replicas=int(r),
                           prefill_replicas=1, transport="shm")
        with svc:
            res = svc.run(requests(), arrival_times=arrivals,
                          deadline_s=deadline_s)
            stats = svc.collect_stats()
        t = res.telemetry
        assert t["lost_requests"] == 0, t["lost_requests"]
        assert token_exact(res), (
            f"R={r}: streamed outputs diverged from Engine.run"
        )
        checked += num_requests
        decode = sorted((s for s in stats.values()
                         if s.get("role") == "decode"),
                        key=lambda s: s["pid"])
        rows.append({
            "decode_replicas": int(r),
            "prefill_replicas": 1,
            "tokens_per_sec": t["tokens_per_sec"],
            "wall_s": t["wall_s"],
            "ttft_p50_s": t["time_to_first_token"]["p50_s"],
            "ttft_p99_s": t["time_to_first_token"]["p99_s"],
            "queue_depth_peak": t["queue_depth_peak"],
            "spinup_s": t["decode_pool"]["spinup_s"],
            "handoffs_installed": sum(s["handoffs_installed"]
                                      for s in decode),
            "handoffs_fallback": sum(s["handoffs_fallback"]
                                     for s in decode),
            "decode_steps_per_replica": [s["decode_steps"]
                                         for s in decode],
            "streamed_token_exact": True,
        })

    cores = os.cpu_count() or 1
    strict_scaling = cores >= max(replica_counts)
    if strict_scaling:
        for prev, cur in zip(rows, rows[1:]):
            assert cur["tokens_per_sec"] > prev["tokens_per_sec"], (
                f"wall tokens/s must increase with decode processes on a "
                f"{cores}-core host: "
                f"{[row['tokens_per_sec'] for row in rows]}"
            )
        scaling_gate = (f"strict: wall tokens/s strictly increasing "
                        f"across R={list(replica_counts)} ({cores} cores)")
    else:
        top = rows[-1]
        assert all(s > 0 for s in top["decode_steps_per_replica"]), (
            f"every decode process must do real work: "
            f"{top['decode_steps_per_replica']}"
        )
        scaling_gate = (
            f"mechanism-only: this {cores}-core host time-slices R "
            f"CPU-bound decode processes, so wall tokens/s cannot scale "
            f"with R; asserted instead: every replica decodes real work, "
            f"zero lost requests, outputs token-exact (the PERF.md "
            f"measured-mechanism precedent). Re-run on an >= "
            f"{max(replica_counts)}-core host for the strict gate."
        )
    base = rows[0]["tokens_per_sec"]
    for row in rows:
        row["speedup_vs_r1"] = round(row["tokens_per_sec"] / base, 2)

    # ---------------------------------------------------------- kill row
    kill_row = None
    if "kill" in sections:
        tmp = tempfile.mkdtemp(prefix="dtpu-bench-service-")
        prev_log = os.environ.get("DTPU_EVENT_LOG")
        os.environ["DTPU_EVENT_LOG"] = os.path.join(tmp, "events.jsonl")
        try:
            svc = ServeService(spec, decode_replicas=int(kill_replicas),
                               prefill_replicas=1, transport="shm")
            killed = []
            victim = f"decode-{int(kill_replicas) - 1}"

            def chaos(s):
                if not killed and s.streamed_tokens >= kill_after_tokens:
                    s.kill_replica(victim)
                    killed.append(victim)

            with svc:
                kres = svc.run(requests(), arrival_times=arrivals,
                               deadline_s=deadline_s, on_pump=chaos)
            kt = kres.telemetry
            assert killed and kt["decode_pool"]["kills"] == 1
            assert kt["lost_requests"] == 0, kt["lost_requests"]
            assert token_exact(kres), (
                "kill-recovery outputs diverged from Engine.run"
            )
            checked += num_requests
            initial_spawns = int(kill_replicas) + 1  # decode pool + prefill
            respawned = kt["decode_pool"]["spawns"] > initial_spawns
            assert respawned, "the service must respawn killed capacity"
            post = summarize(read_events(os.environ["DTPU_EVENT_LOG"]))
            dumps = [d for d in post["flight_dumps"]
                     if d["readable"] and d["reason"] == "replica_kill"]
            assert dumps, (
                "a killed worker must leave a readable flight-recorder "
                "postmortem referenced from the event log"
            )
            kill_row = {
                "decode_replicas": int(kill_replicas),
                "killed_replica": killed[0],
                "killed_after_streamed_tokens": kill_after_tokens,
                "lost_requests": kt["lost_requests"],
                "token_exact_vs_engine_run": True,
                "respawned": bool(respawned),
                "requeues": kt["router"]["requeues"],
                "tokens_per_sec": kt["tokens_per_sec"],
                "ttft_p99_s": kt["time_to_first_token"]["p99_s"],
                "postmortem": {
                    "flight_dump": dumps[0]["path"],
                    "records": len(dumps[0]["records"]),
                    "renderer": "dtpu-events " + os.environ["DTPU_EVENT_LOG"],
                },
            }
        finally:
            if prev_log is None:
                del os.environ["DTPU_EVENT_LOG"]
            else:
                os.environ["DTPU_EVENT_LOG"] = prev_log

    # --------------------------------------------------------- quota row
    quota_row = None
    if "quota" in sections:
        fprompts = [rng.integers(0, vocab, (8,)).astype(np.int32)
                    for _ in range(flood_requests + paying_requests)]
        fnews = [12] * len(fprompts)
        freqs = [Request(p, m, seed=0) for p, m in zip(fprompts, fnews)]
        tenants = (["flood"] * flood_requests
                   + ["paying"] * paying_requests)
        farrivals = ([0.0] * flood_requests
                     + [0.5 * i for i in range(paying_requests)])
        svc = ServeService(
            spec, decode_replicas=1, transport="none",
            router=Router(tenant_weights={"paying": 2.0}),
            quotas=TenantQuotas({"flood": (quota_rate, quota_burst)}),
        )
        with svc:
            qres = svc.run(freqs, arrival_times=farrivals, tenants=tenants,
                           deadline_s=deadline_s)
        qt = qres.telemetry
        paying = qt["tenants"].get("paying", {"finished": 0})
        assert qt["quotas"]["rejected"] > 0, "the flood must hit the bucket"
        assert paying["finished"] == paying_requests, (
            f"every paying request must finish: {paying}"
        )
        assert paying["ttft_p99_s"] <= ttft_bound_s, (
            f"paying-tenant p99 TTFT {paying['ttft_p99_s']}s exceeds the "
            f"{ttft_bound_s}s bound behind a flooding tenant"
        )
        quota_row = {
            "flood_requests": flood_requests,
            "flood_rejected": qt["quotas"]["rejected_by_tenant"]["flood"],
            "flood_limit": {"rate_tokens_per_s": quota_rate,
                            "burst_tokens": quota_burst},
            "paying_requests": paying_requests,
            "paying_finished": paying["finished"],
            "paying_weight": 2.0,
            "paying_ttft_p50_s": paying["ttft_p50_s"],
            "paying_ttft_p99_s": paying["ttft_p99_s"],
            "ttft_bound_s": ttft_bound_s,
            "lost_requests": qt["lost_requests"],
        }

    top = rows[-1]
    return {
        "metric":
            f"service_wall_tokens_per_sec_r{top['decode_replicas']}",
        "value": top["tokens_per_sec"],
        "unit": "tokens/s",
        "clock": "wall",
        "scaling": rows,
        "scaling_gate": scaling_gate,
        "kill": kill_row,
        "quota": quota_row,
        "streaming": {
            "byte_identical_to_engine_run": True,
            "requests_checked": checked,
        },
        "transport": "shm",
        "arrivals": {
            "process": "bursty open-loop",
            "num_requests": num_requests,
            "burst_size": burst_size,
            "burst_gap_s": burst_gap_s,
            "useful_tokens": useful_tokens,
        },
        "workload": {
            "max_slots": max_slots,
            "block_size": block_size,
            "prompt_range": list(prompt_range),
            "new_range": list(new_range),
            "model": f"lm_l{num_layers}_d{d_model}_v{vocab}",
        },
    }


# --------------------------------------------------------------------- rl --
def bench_rl(vocab=512, num_layers=4, d_model=256, num_heads=8,
             max_len=128, max_slots=8, block_size=16, num_prompts=8,
             prompt_len=8, num_samples=4, max_new_tokens=32, iterations=4,
             learning_rate=1e-3, kl_coef=0.01, length_coef=0.0,
             train_epochs=1, restart_probe_tokens=4, seed=0):
    """Online post-training closed loop (``python bench.py rl``, artifact
    BENCH_rl.json; docs/RL.md). One process group runs trainer AND
    server: each iteration samples ``num_prompts x num_samples`` rollouts
    on the serving engine (per-token logprobs captured in the fixed-shape
    dispatches), scores them with the length-penalized-logprob reward,
    takes one REINFORCE+KL policy-gradient step through the existing fit
    path, and hot-swaps the new weights into the live engine with
    ``Engine.update_weights``. Pinned facts:

    1. **Learning** — mean reward strictly increases across iterations
       (asserted): the loop is closed for real, rollouts -> update ->
       better rollouts, on the ``lm_l4_d256`` serving-bench family.
    2. **Loop couplings** — rollout tokens/s, train steps/s, and
       weight-sync latency per iteration (iteration 1 pays every compile;
       summary rows are medians over the warm iterations).
    3. **Hot-swap vs restart** — the same weight delivery done the old
       way: checkpoint the trained weights, restore them into the model,
       build a fresh engine, decode a first token (what a restarted
       serving process must do before serving; on this CPU box that
       includes the re-jit a real fleet bounds with the persistent
       compile cache). Asserted: the in-place swap is faster.

    1-core caveat (the PERF.md precedent): rollout and train phases
    share one CPU, so their rates here measure dispatch overhead, not
    accelerator throughput, and the swap-vs-restart gap narrows on warm
    compile caches — the artifact records the mechanisms (logprob
    capture, version boundaries, no-restart swap), the chips record the
    speed."""
    import distributed_tpu.serving as serving
    import distributed_tpu.rl as rl

    model = dtpu.Model(dtpu.models.transformer_lm(
        vocab, num_layers=num_layers, d_model=d_model,
        num_heads=num_heads, max_len=max_len,
    ))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((32,))
    engine = serving.Engine(
        model, max_slots, block_size, max_len=max_len, temperature=1.0,
        seed=seed,
    )
    pt = rl.PostTrainer(
        model, engine,
        reward_fn=rl.length_penalized_logprob(length_coef),
        learning_rate=learning_rate, kl_coef=kl_coef, seed=seed,
    )
    rng = np.random.default_rng(seed)
    prompts = [
        rng.integers(0, vocab, (prompt_len,)).astype(np.int32)
        for _ in range(num_prompts)
    ]
    rows = pt.train(
        prompts, iterations=iterations, num_samples=num_samples,
        max_new_tokens=max_new_tokens, train_epochs=train_epochs,
    )
    rewards = [r["reward_mean"] for r in rows]
    for prev, cur in zip(rewards, rewards[1:]):
        assert cur > prev, (
            f"reward must improve every iteration: {rewards}"
        )
    warm = rows[1:] if len(rows) > 1 else rows
    swap_s = float(np.median([r["weight_sync_s"] for r in warm]))

    # Restart comparison: deliver the SAME trained weights by
    # checkpoint-save -> restore -> fresh engine -> first served token.
    import os
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        t0 = time.perf_counter()
        path = os.path.join(tmp, "weights.npz")
        model.save_weights(path)
        model.load_weights(path)
        restarted = serving.Engine(
            model, max_slots, block_size, max_len=max_len,
            temperature=1.0, seed=seed,
        )
        restarted.run([serving.Request(prompts[0],
                                       int(restart_probe_tokens))])
        restart_s = time.perf_counter() - t0
    assert swap_s < restart_s, (
        f"hot-swap ({swap_s:.4f}s) must beat save+restore restart "
        f"({restart_s:.4f}s)"
    )

    return {
        "metric": (
            f"rl_loop_rollout_tokens_per_sec_lm_l{num_layers}_d{d_model}"
        ),
        "value": round(
            float(np.median([r["rollout_tokens_per_sec"] for r in warm])), 2
        ),
        "unit": "tokens/s",
        "train_steps_per_sec": round(
            float(np.median([r["train_steps_per_sec"] for r in warm])), 3
        ),
        "weight_sync_latency_s": round(swap_s, 4),
        "hot_swap_vs_restart": {
            "hot_swap_s": round(swap_s, 4),
            "save_restore_restart_s": round(restart_s, 4),
            "speedup": round(restart_s / swap_s, 1),
            "restart_includes": "save_weights + load_weights + fresh "
                                "Engine (pool alloc + re-jit) + first "
                                f"{restart_probe_tokens} tokens",
        },
        "reward_by_iteration": [round(r, 4) for r in rewards],
        "reward_monotonic": True,
        "kl_by_iteration": [
            None if r["kl"] is None else round(r["kl"], 4) for r in rows
        ],
        "weights_version_final": rows[-1]["weights_version"],
        "iterations": [
            {k: r[k] for k in (
                "iteration", "reward_mean", "loss", "kl", "kl_coef",
                "rollout_tokens_per_sec", "train_steps_per_sec",
                "weight_sync_s", "weights_version",
            )}
            for r in rows
        ],
        "clock": "iteration 1 includes all XLA compiles (engine "
                 "dispatches, train step, KL probe); summary medians use "
                 "warm iterations only; 1-core box — see docs/RL.md",
        "workload": {
            "num_prompts": num_prompts,
            "num_samples": num_samples,
            "prompt_len": prompt_len,
            "max_new_tokens": max_new_tokens,
            "iterations": iterations,
            "max_slots": max_slots,
            "block_size": block_size,
            "learning_rate": learning_rate,
            "kl_coef": kl_coef,
            "reward": f"length_penalized_logprob({length_coef})",
            "model": f"lm_l{num_layers}_d{d_model}_v{vocab}",
        },
    }


# ------------------------------------------------------------------ quant --
def bench_quant(vocab=512, num_layers=4, d_model=256, num_heads=8,
                max_len=128, probe_batch=8, probe_len=32, seed=0):
    """Int8 weight-only quantization (``python bench.py quant``, artifact
    BENCH_quant.json; docs/PERF.md "Quantization & fused updates").

    Three pinned facts on the serving LM shape (l4 d256):

    1. **Param bytes** — the serving-HBM roofline of the memory-bound
       decode path: measured per-device resident bytes
       (tree_bytes_per_device) of the f32 weights vs the int8+scales tree.
       Per-channel scales and the f32-kept 1-D leaves (biases, norms) cost
       ~1% of the tree, so the ratio lands just under the ideal 4x.
    2. **Decode fidelity** — teacher-forced logits of the quantized model
       vs f32 on the same tokens (max abs error, top-1 agreement fraction)
       plus greedy-token agreement of generate(). Weight rounding is
       bounded by scale/2 per element; this records what that does
       end-to-end.
    3. **Collective bytes** — FSDP per-layer gathers priced by
       Strategy.comm_bytes_estimate: int8 weights gather at 1 byte/elem
       vs bf16's 2 (exactly 2x on the weight leaves; slightly less on the
       whole tree because scales/biases stay f32). Multi-device mesh only
       (run under XLA_FLAGS=--xla_force_host_platform_device_count=8 on
       CPU); on one device the comm rows are null.

    Honest CPU caveat (the PR 5 precedent): XLA:CPU has no HBM roofline —
    dequantize-in-trace ADDS compute there, so this bench pins bytes and
    fidelity (backend-independent mechanisms), not tokens/s; the
    throughput win exists where decode is memory-bound (real chips).
    """
    from distributed_tpu import quant
    from distributed_tpu.utils.profiler import tree_bytes_per_device

    def build():
        model = dtpu.Model(dtpu.models.transformer_lm(
            vocab, num_layers=num_layers, d_model=d_model,
            num_heads=num_heads, max_len=max_len,
        ))
        model.compile(optimizer="adam",
                      loss="sparse_categorical_crossentropy")
        model.build((probe_len,), seed=seed)
        return model

    f32 = build()
    q = build()  # same seed -> identical weights; quantized in place
    quant.quantize_model(q)

    bytes_f32 = tree_bytes_per_device(f32.params)["max_bytes_per_device"]
    bytes_q = tree_bytes_per_device(q.params)["max_bytes_per_device"]
    ratio = bytes_f32 / bytes_q

    rng = np.random.default_rng(seed)
    toks = rng.integers(0, vocab, (probe_batch, probe_len)).astype(np.int32)
    ref = f32.predict(toks, batch_size=probe_batch)
    out = q.predict(toks, batch_size=probe_batch)
    logit_err = float(np.max(np.abs(out - ref)))
    top1 = float(np.mean(np.argmax(out, -1) == np.argmax(ref, -1)))
    g_ref = f32.generate(toks[:, :8], 16, temperature=0.0)
    g_q = q.generate(toks[:, :8], 16, temperature=0.0)
    greedy_agree = float(np.mean(g_ref == g_q))

    out_row = {
        "metric": f"quant_int8_param_bytes_ratio_vs_f32_l{num_layers}"
                  f"_d{d_model}",
        "value": round(ratio, 3),
        "unit": "x_fewer_param_bytes_per_device",
        "param_bytes_per_device": {"f32": bytes_f32, "int8": bytes_q},
        "meets_3p5x": bool(ratio >= 3.5),
        "decode_fidelity": {
            "max_abs_logit_err": round(logit_err, 5),
            "top1_agreement": round(top1, 4),
            "greedy_token_agreement": round(greedy_agree, 4),
            "probe": f"teacher-forced ({probe_batch}, {probe_len}) + "
                     "greedy generate 16 new tokens",
        },
        "model": f"lm_l{num_layers}_d{d_model}_v{vocab}",
    }
    del f32, q

    # ---- FSDP gathered-bytes accounting (multi-device mesh only) ----
    if len(jax.devices()) > 1:
        strategy = dtpu.FSDP()
        with strategy.scope():
            model = build()
        host = jax.tree_util.tree_map(
            lambda a: np.asarray(jax.device_get(a)), model.params)
        qtree = quant.quantize_tree(host)

        def weights_only(tree):
            # Keep only the quantizable weight leaves (ndim >= 2); None
            # leaves vanish in tree_leaves, so comm_bytes_estimate prices
            # just the weights.
            def walk(t):
                if quant.is_quantized_leaf(t):
                    return {"q": t["q"]}
                if isinstance(t, dict):
                    return {k: walk(v) for k, v in t.items()}
                return t if getattr(t, "ndim", 0) >= 2 else None
            return walk(tree)

        est = {
            "f32": strategy.comm_bytes_estimate(host),
            "bf16": strategy.comm_bytes_estimate(
                host, compute_dtype=jnp.bfloat16),
            "int8": strategy.comm_bytes_estimate(
                qtree, compute_dtype=jnp.bfloat16),
        }
        west = {
            "bf16": strategy.comm_bytes_estimate(
                weights_only(host), compute_dtype=jnp.bfloat16),
            "int8": strategy.comm_bytes_estimate(
                weights_only(qtree), compute_dtype=jnp.bfloat16),
        }
        gk = "gathered_param_bytes_per_device"
        out_row["fsdp_gathered_bytes_per_device"] = {
            k: v[gk] for k, v in est.items()
        }
        out_row["fsdp_gather_ratio_bf16_over_int8"] = {
            # Whole tree: scales + the f32-kept biases dilute the ideal 2x
            # by ~1%; the weight leaves themselves gather at exactly half
            # of bf16 (1 byte vs 2). Both recorded, neither rounded up.
            "full_tree": round(est["bf16"][gk] / est["int8"][gk], 3),
            "weight_leaves": round(west["bf16"][gk] / west["int8"][gk], 3),
        }
        out_row["fsdp_gather_ratio_f32_over_int8"] = round(
            est["f32"][gk] / est["int8"][gk], 3)
        del model
    return out_row


def bench_fused_update(vocab=512, num_layers=4, d_model=256, num_heads=8,
                       max_len=128, updates=20, windows=3, seed=0):
    """Fused optimizer-update kernel (``python bench.py fused_update``,
    rides in BENCH_quant.json's extra rows): times the jitted
    update+apply phase — ``tx.update`` + ``optax.apply_updates`` on the
    l4 d256 LM master tree — for stock ``optim.Adam`` vs the Pallas
    ``optim.fused_adam``, median of ``windows`` windows of ``updates``
    updates each. Forward/backward is deliberately excluded: the kernel
    only changes the update phase, and measuring it alone is what makes
    the number attributable.

    Backend honesty (the PR 5 precedent): the speedup claim is only
    asserted on an accelerator backend, where the fused pass replaces the
    per-leaf kernel walk with one kernel per dtype segment. On XLA:CPU
    the kernel runs in Pallas INTERPRET mode — each grid block dispatches
    through the interpreter, so the fused path is typically SLOWER there
    and ``speedup_asserted`` is false; the artifact instead pins the
    mechanism by assertion: bit/1e-6-level parity with stock optax over
    ``updates`` steps and the leaf->segment consolidation (hundreds of
    per-leaf update chains collapsed into kernel launches counted by
    ``n_segments``)."""
    import optax

    model = dtpu.Model(dtpu.models.transformer_lm(
        vocab, num_layers=num_layers, d_model=d_model, num_heads=num_heads,
        max_len=max_len,
    ))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((32,), seed=seed)
    params = model.params
    n_leaves = len(jax.tree_util.tree_leaves(params))
    key = jax.random.PRNGKey(seed)
    grads = jax.tree_util.tree_map(
        lambda p: jax.random.normal(key, p.shape, p.dtype) * 0.01, params)

    def phase(tx):
        opt_state = tx.init(params)

        @jax.jit
        def one(p, s, g):
            u, s = tx.update(g, s, p)
            return optax.apply_updates(p, u), s

        p, s = one(params, opt_state, grads)  # compile + warm
        _sync(jax.tree_util.tree_leaves(p)[0])
        rates = []
        for _ in range(max(1, windows)):
            t0 = time.perf_counter()
            for _ in range(updates):
                p, s = one(p, s, grads)
            _sync(jax.tree_util.tree_leaves(p)[0])
            rates.append((time.perf_counter() - t0) / updates)
        return float(np.median(rates)), [round(r * 1e3, 3) for r in rates], (
            p, s)

    stock_s, stock_win, (p_stock, _) = phase(dtpu.optim.Adam(1e-3))
    fused_s, fused_win, (p_fused, _) = phase(dtpu.optim.fused_adam(1e-3))
    parity = max(
        float(np.max(np.abs(
            np.asarray(a, np.float64) - np.asarray(b, np.float64))))
        for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(p_stock)),
                        jax.tree_util.tree_leaves(jax.device_get(p_fused)))
    )
    on_accel = jax.default_backend() == "tpu"
    speedup = stock_s / fused_s
    return {
        "metric": f"fused_adam_update_phase_speedup_l{num_layers}"
                  f"_d{d_model}",
        "value": round(speedup, 3),
        "unit": "x_vs_stock_optax_update_phase",
        "update_phase_ms": {
            "stock_adam": round(stock_s * 1e3, 3),
            "fused_adam": round(fused_s * 1e3, 3),
        },
        "window_update_ms": {"stock": stock_win, "fused": fused_win},
        "backend": jax.default_backend(),
        "speedup_asserted": bool(on_accel and speedup >= 1.0),
        "mechanism": {
            "parity_max_abs_diff_after_updates": parity,
            "updates_compared": (1 + windows * updates),
            "n_param_leaves": n_leaves,
            "n_segments": 1,  # one f32 segment = one kernel launch/update
            "note": "XLA:CPU runs the kernel in Pallas interpret mode "
                    "(per-block interpreter dispatch), so the CPU number "
                    "measures the interpreter, not the fused-HBM-pass "
                    "win; parity + segment consolidation are the "
                    "portable claims (PR 5 honesty precedent)",
        },
        "model": f"lm_l{num_layers}_d{d_model}_v{vocab}",
    }


# ---------------------------------------------------------------- overlap2 --
def bench_overlap2(vocab=512, num_layers=4, d_model=32, num_heads=2,
                   seq_len=32, batch=8, steps=6, gather_reps=10, windows=3):
    """FSDP comm/compute overlap inside the scanned transformer stack
    (``python bench.py overlap2``, artifact BENCH_overlap2.json): trains
    the same scanned LM under FSDP with ``scan_overlap='off'`` (every
    per-layer parameter all-gather serial with compute) and ``'auto'``
    (layer i+1's gather issued while layer i computes — the
    ``Strategy.overlap_spec`` x ``nn.ScannedBlocks`` seam), asserting the
    loss trajectories match at rtol 2e-5 and that the telemetry-reported
    exposed-comm fraction drops strictly (1.0 -> 1/L: only the layer-0
    warm gather stays on the critical path).

    Span attribution: the per-step comm and compute volumes are measured
    as REAL timed dispatches under nested obs spans, so the seconds land
    in the registry as ``span_seconds/fit/dispatch/gather_prefetch`` vs
    ``span_seconds/fit/dispatch/compute`` — the exposed-comm seconds per
    mode are those measured gather seconds scaled by each mode's exposed
    fraction, not a model.

    Backend honesty (the PR 5 precedent): on a single-host CPU mesh the
    gather dispatches share one execution stream with compute, so no
    wall-clock hiding is claimable and ``speedup_asserted`` is false; the
    artifact pins the mechanism (trajectory parity + structural exposed
    fraction + measured comm seconds). Opt-in like ``zero``: needs a
    multi-device mesh (XLA_FLAGS=--xla_force_host_platform_device_count=8
    on CPU)."""
    from distributed_tpu.obs import registry as obs_registry
    from distributed_tpu.obs import spans as obs_spans

    n_dev = len(jax.devices())
    rng = np.random.default_rng(0)
    tok = rng.integers(0, vocab, (batch, seq_len + 1), dtype=np.int64)
    xb, yb = tok[:, :-1].astype(np.int32), tok[:, 1:].astype(np.int32)

    losses, telems, keep = {}, {}, {}
    for mode in ("off", "auto"):
        strategy = dtpu.FSDP() if n_dev > 1 else dtpu.SingleDevice()
        with strategy.scope():
            model = dtpu.Model(dtpu.models.transformer_lm(
                vocab, num_layers=num_layers, d_model=d_model,
                num_heads=num_heads, max_len=seq_len, scan=True,
                scan_overlap=mode,
            ))
            model.compile(optimizer=dtpu.optim.Adam(1e-3),
                          loss="sparse_categorical_crossentropy")
        model.build((seq_len,), seed=0)
        hist = model.fit(xb, yb, batch_size=batch, epochs=steps,
                         steps_per_epoch=1, verbose=0, seed=0)
        losses[mode] = [float(l) for l in hist.history["loss"]]
        telems[mode] = dict(model.last_fit_telemetry.get("overlap") or {})
        keep[mode] = (strategy, model)

    ref = np.asarray(losses["off"], np.float64)
    got = np.asarray(losses["auto"], np.float64)
    max_rel = float(np.max(np.abs(got - ref) / np.maximum(np.abs(ref), 1e-12)))
    parity_ok = bool(np.allclose(got, ref, rtol=2e-5, atol=0))
    assert parity_ok, (
        f"overlap changed the loss trajectory: max rel diff {max_rel:.3e}"
    )

    frac_off = float(telems["off"].get("exposed_comm_fraction", 1.0))
    frac_on = float(telems["auto"].get("exposed_comm_fraction", 1.0))
    overlap_active = bool(telems["auto"].get("overlap"))
    if overlap_active:
        assert frac_on < frac_off, (
            f"exposed-comm fraction did not drop: {frac_on} !< {frac_off}"
        )

    # Span-attributed comm/compute seconds: time the real all-gather of
    # the scan-stacked block params (the per-step comm volume the overlap
    # hides) and the compiled train step, each under its own nested span.
    gather_s = compute_s = None
    strategy, model = keep["auto"]
    gather = strategy.overlap_spec()
    if gather is not None:
        stacked = [
            l for l in jax.tree_util.tree_leaves(model.params)
            if getattr(l, "ndim", 0) >= 2 and l.shape[0] == num_layers
        ]
        # step_fn donates the param buffers: gather timing needs its own
        # copies (same sharding) or the warm step deletes them.
        stacked = [l + 0 for l in stacked]
        # Replicated out_shardings force the all-gathers to materialize:
        # GSPMD cancels an unconsumed gather whose output reshards back,
        # and here (unlike the scan body) nothing consumes the gathered
        # value — the output layout is the consumer.
        from jax.sharding import NamedSharding, PartitionSpec
        rep = NamedSharding(strategy.mesh, PartitionSpec())
        gather_jit = jax.jit(lambda ps: [gather(p) for p in ps],
                             out_shardings=[rep] * len(stacked))
        n_all_gathers = gather_jit.lower(stacked).compile().as_text().count(
            "all-gather")
        step_fn = model._get_train_step()
        dev_batch = model.strategy.put_batch({"x": xb, "y": yb})
        rngk = jax.random.PRNGKey(0)
        _sync(gather_jit(stacked)[0])
        # step_fn donates its buffers: chain params/state/opt locally and
        # never touch model.params after the warm call.
        p, s, o = model.params, model.state, model.opt_state
        p, s, o, loss, _ = step_fn(p, s, o, dev_batch["x"],
                                   dev_batch["y"], rngk)
        _sync(loss)
        g_win, c_win = [], []
        for _ in range(max(1, windows)):
            with obs_spans.span("fit"):
                with obs_spans.span("dispatch"):
                    with obs_spans.span("gather_prefetch") as sp_g:
                        for _ in range(gather_reps):
                            out = gather_jit(stacked)
                        _sync(out[0])
                    with obs_spans.span("compute") as sp_c:
                        for _ in range(gather_reps):
                            p, s, o, loss, _ = step_fn(
                                p, s, o, dev_batch["x"], dev_batch["y"],
                                rngk)
                        _sync(loss)
            g_win.append(sp_g.seconds / gather_reps)
            c_win.append(sp_c.seconds / gather_reps)
        gather_s = float(np.median(g_win))
        compute_s = float(np.median(c_win))

    out = {
        "metric": f"fsdp_scan_overlap2_exposed_comm_fraction_l{num_layers}",
        "value": round(frac_on, 4),
        "unit": "exposed_comm_fraction",
        "baseline_off_fraction": round(frac_off, 4),
        "overlap_active": overlap_active,
        "layers": num_layers,
        "n_devices": n_dev,
        "loss_parity": {
            "max_rel_diff": max_rel,
            "rtol": 2e-5,
            "allclose": parity_ok,
            "steps_compared": steps,
        },
        "telemetry": {"off": telems["off"], "auto": telems["auto"]},
        "backend": jax.default_backend(),
        "speedup_asserted": False,
        "note": "single-host mesh shares one execution stream, so the "
                "wall-clock hiding is an accelerator claim; this artifact "
                "pins trajectory parity, the structural exposed-comm drop "
                "(all L gathers serial -> only the layer-0 warm gather), "
                "and the span-measured comm volume the overlap prefetches",
        "model": f"lm_l{num_layers}_d{d_model}_v{vocab}_scan",
    }
    if gather_s is not None:
        out["span_seconds"] = {
            "gather_prefetch_per_dispatch": round(gather_s, 6),
            "compute_per_step": round(compute_s, 6),
            "all_gathers_in_timed_program": n_all_gathers,
            "paths": ["span_seconds/fit/dispatch/gather_prefetch",
                      "span_seconds/fit/dispatch/compute"],
            "obs_registry_enabled": bool(obs_registry.enabled()),
        }
        out["exposed_comm_seconds_per_step"] = {
            "off": round(gather_s * frac_off, 6),
            "auto": round(gather_s * frac_on, 6),
        }
    else:
        out["multi_device"] = False
    return out


# ------------------------------------------------------------ decode kernel --
def bench_decode_kernel(num_requests=12, max_slots=4, block_size=16,
                        vocab=512, num_layers=2, d_model=64, num_heads=2,
                        max_len=128, prompt_range=(4, 24), new_range=(8, 24),
                        seed=0, repeats=3):
    """Fused paged-attention decode kernel vs the reference gather+dense
    path (``python bench.py decode_kernel``, artifact
    BENCH_decode_kernel.json): the same Engine workload is served twice —
    ``decode_kernel='reference'`` and ``'fused'`` — across the serving
    configurations the kernel must survive (batch churn, pool-pressure
    preemption, prefix-cache admission, int8 KV pools, speculative
    verify, pinned-seed sampling), asserting token-exact outputs per
    request and reporting tokens/s for both paths.

    Backend honesty (the PR 5 precedent): on XLA:CPU the fused kernel
    runs in Pallas INTERPRET mode — per-grid-block interpreter dispatch —
    so the fused path is typically slower there and ``speedup_asserted``
    is false; token-exactness across every configuration is the portable
    claim, and the throughput win (one kernel replacing the block-table
    gather + masked dense attention chain) is measured on an accelerator
    backend."""
    import distributed_tpu.serving as serving

    model = dtpu.Model(dtpu.models.transformer_lm(
        vocab, num_layers=num_layers, d_model=d_model, num_heads=num_heads,
        max_len=max_len,
    ))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((32,))
    draft = dtpu.Model(dtpu.models.transformer_lm(
        vocab, num_layers=1, d_model=32, num_heads=2, max_len=max_len,
    ))
    draft.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    draft.build((32,))

    rng = np.random.default_rng(seed)
    prompts = [
        rng.integers(0, vocab, (int(n),)).astype(np.int32)
        for n in rng.integers(prompt_range[0], prompt_range[1] + 1,
                              num_requests)
    ]
    max_news = rng.integers(new_range[0], new_range[1] + 1,
                            num_requests).astype(int)
    useful_tokens = int(np.sum(max_news))
    # Prefix-cache config: every request shares a 2-block leading span.
    common = rng.integers(0, vocab, (2 * block_size,)).astype(np.int32)
    prefix_prompts = [np.concatenate([common, p]) for p in prompts]

    # Preemption pool: contexts cap at prompt_range[1] + new_range[1]
    # tokens, i.e. ceil(48/16) = 3 blocks per sequence. Give the pool
    # one block MORE than that single-sequence worst case (plus the
    # trash block): any one context always fits (forward progress), but
    # two concurrently growing slots can't both be backed, so a running
    # slot's mid-decode ``reserve`` fails and evicts the youngest —
    # asserted below so the config can't silently degrade into a
    # no-pressure run (sizing against max_len instead of real context
    # lengths is exactly the mistake that made an earlier pool toothless).
    preempt_blocks = 2 + (
        -(-(prompt_range[1] + new_range[1]) // block_size))
    configs = [
        ("greedy_churn", {}, prompts),
        ("sampled_seeded", {"temperature": 0.8, "seed": 7}, prompts),
        ("preemption", {"num_blocks": preempt_blocks}, prompts),
        ("prefix_cache", {"prefix_cache": True}, prefix_prompts),
        ("int8_kv", {"kv_dtype": "int8"}, prompts),
        ("spec_verify", {"draft_model": draft, "spec_k": 3}, prompts),
    ]

    rows = []
    for name, kwargs, ps in configs:
        reqs = [serving.Request(p, int(m)) for p, m in zip(ps, max_news)]
        engines = {
            kind: serving.Engine(model, max_slots, block_size,
                                 max_len=max_len, decode_kernel=kind,
                                 **kwargs)
            for kind in ("reference", "fused")
        }
        outs, rates, telem = {}, {"reference": [], "fused": []}, {}
        for kind, eng in engines.items():
            outs[kind] = eng.run(list(reqs))  # warm: compiles outside timing
            for _ in range(max(1, repeats)):
                outs[kind] = eng.run(list(reqs))
                t = eng.last_run_telemetry
                rates[kind].append(useful_tokens / t["total_seconds"])
            telem[kind] = eng.last_run_telemetry
        exact = bool(all(
            np.array_equal(a, b)
            for a, b in zip(outs["reference"], outs["fused"])
        ))
        assert exact, f"decode_kernel parity broke on config {name!r}"
        if name == "preemption":
            for kind in ("reference", "fused"):
                assert telem[kind]["preemptions"] > 0, (
                    f"{kind}: preemption config never hit pool pressure "
                    f"(num_blocks={preempt_blocks}) — shrink the pool")
        rows.append({
            "config": name,
            "token_exact": exact,
            "reference_tokens_per_sec": round(
                float(np.median(rates["reference"])), 2),
            "fused_tokens_per_sec": round(
                float(np.median(rates["fused"])), 2),
            "preemptions": telem["fused"]["preemptions"],
            "decode_steps": telem["fused"]["decode_steps"],
        })
        del engines

    base = rows[0]
    out = {
        "metric": f"serve_decode_kernel_fused_tokens_per_sec_s{max_slots}",
        "value": base["fused_tokens_per_sec"],
        "unit": "tokens/s",
        "reference_tokens_per_sec": base["reference_tokens_per_sec"],
        "token_exact_all_configs": bool(all(r["token_exact"] for r in rows)),
        "configs": rows,
        "backend": jax.default_backend(),
        "speedup_asserted": False,
        "note": "XLA:CPU runs the fused kernel in Pallas interpret mode "
                "(per-block interpreter dispatch), so the CPU tokens/s "
                "measures the interpreter, not the fused gather+attention "
                "win; token-exactness across churn/preemption/prefix/int8/"
                "spec-verify/sampling is the portable claim",
        "workload": {
            "num_requests": num_requests,
            "max_slots": max_slots,
            "block_size": block_size,
            "prompt_range": list(prompt_range),
            "new_range": list(new_range),
            "useful_tokens": useful_tokens,
            "model": f"lm_l{num_layers}_d{d_model}_v{vocab}",
        },
    }
    return out


# --------------------------------------------------------------- autoshard --
def bench_autoshard(vocab=512, num_layers=2, d_model=256, num_heads=4,
                    seq_len=64, batch=32,
                    big_vocab=2048, big_layers=4, big_d_model=768,
                    hbm_cap_mb=256, big_batch=None,
                    warmup=2, measure=10, windows=3, match_tol=0.10):
    """The auto-shard planner re-picking the known-best configs
    (``python bench.py autoshard``, artifact BENCH_autoshard.json;
    docs/PERF.md "Autotuned sharding"). Two rows, both through the REAL
    user path — ``model.compile(strategy="auto")`` — on the shapes
    BENCH_zero already measured:

    1. **Uncapped small LM** (the BENCH_zero part-1 shape): the planner
       must pick plain DP (replication is free when everything fits, and
       ZeRO/FSDP only add gather traffic). The pick is then VALIDATED by
       measuring dp/zero1/fsdp with the standard ``_time_steps``
       median-of-3 protocol: ``pick_matches_measured_best`` is exact,
       ``pick_within_tol_of_best`` allows the transport's documented
       dispatch jitter (BENCH_zero measured the three within 2% of each
       other — well inside the +/-10-30% window noise).
    2. **Capped big LM** (the BENCH_zero hbm_cap_row shape under the same
       256MB cap): replicated DP needs ~378MB/device and must be PRUNED
       (rationale recorded in the plan), FSDP's ~47MB share must be
       chosen, and the committed model proves it by training real steps.

    ``hbm_cap_mb="midpoint"`` derives a cap between the replicated and
    FSDP footprints from an estimate-only pre-pass (the smoke test's
    path, where tiny shapes make any fixed cap meaningless).

    Planner knobs are pinned to K=1 / accum=1 so the strategy dimension —
    the one BENCH_zero measured — is what's compared."""
    from distributed_tpu.parallel import plan_sharding

    rng = np.random.default_rng(0)
    n_dev = len(jax.devices())
    if n_dev < 2:
        raise SystemExit("bench autoshard needs a multi-device mesh (run "
                         "under XLA_FLAGS=--xla_force_host_platform_"
                         "device_count=8 on CPU)")
    pin = dict(grad_accums=(1,), steps_per_execution=(1,))

    # ---- row 1: uncapped small LM -> DP --------------------------------
    def small_module():
        return dtpu.models.transformer_lm(
            vocab, num_layers=num_layers, d_model=d_model,
            num_heads=num_heads, max_len=seq_len)

    auto = dtpu.Model(small_module())
    auto.compile(optimizer=dtpu.optim.Adam(1e-3),
                 loss="sparse_categorical_crossentropy",
                 strategy="auto",
                 auto_options=dict(batch_size=batch, **pin))
    auto.build((seq_len,))
    plan = auto.last_plan
    picked = plan.chosen["config"]["strategy"]
    del auto

    tok = rng.integers(0, vocab, (batch, seq_len + 1), dtype=np.int64)
    xb, yb = tok[:, :-1].astype(np.int32), tok[:, 1:].astype(np.int32)
    alternatives = {"dp": dtpu.DataParallel, "zero1": dtpu.ZeroDataParallel,
                    "fsdp": dtpu.FSDP}
    rates = {}
    for name, cls in alternatives.items():
        with cls().scope():
            m = dtpu.Model(small_module())
            m.compile(optimizer=dtpu.optim.Adam(1e-3),
                      loss="sparse_categorical_crossentropy")
        m.build((seq_len,))
        dev_batch = m.strategy.put_batch({"x": xb, "y": yb})
        sps, _ = _time_steps(m, dev_batch, warmup, measure, windows=windows)
        rates[name] = round(sps, 3)
        del m, dev_batch
    measured_best = max(rates, key=rates.get)
    picked_rate = rates.get(picked)
    within = (
        picked_rate is not None
        and picked_rate >= rates[measured_best] * (1.0 - match_tol)
    )

    def trim(p):
        return {
            "chosen": {k: p.chosen[k] for k in
                       ("label", "config", "state_bytes_per_device",
                        "comm_bytes_per_step_per_device",
                        "est_step_seconds")},
            "tie_break": p.tie_break,
            "n_feasible": len(p.candidates),
            "n_pruned": len(p.pruned),
            "pruned": [
                {"label": r["label"], "reason": r["reason"]}
                for r in p.pruned[:8]
            ],
        }

    out = {
        "metric": f"autoshard_uncapped_lm_pick_steps_per_sec_gb{batch}",
        "value": picked_rate,
        "unit": "steps/s",
        "picked": picked,
        "measured_best": measured_best,
        "pick_matches_measured_best": picked == measured_best,
        "pick_within_tol_of_best": bool(within),
        "match_tol": match_tol,
        "measured_steps_per_sec": rates,
        "plan": trim(plan),
        "note": "on this shape the three data-parallel strategies do "
                "IDENTICAL compute and differ only in collective layout, "
                "so their measured rates sit within the transport's "
                "dispatch jitter (BENCH_zero measured them within 2%; "
                "window spread is +/-10-30% on dispatch-bound models) — "
                "the asserted claim is pick_within_tol_of_best, with the "
                "exact-match bool recorded for the runs where the "
                "ordering is stable",
    }

    # ---- row 2: capped big LM -> FSDP ----------------------------------
    def big_module():
        return dtpu.models.transformer_lm(
            big_vocab, num_layers=big_layers, d_model=big_d_model,
            num_heads=num_heads, max_len=seq_len)

    bb = int(big_batch) if big_batch is not None else n_dev
    if hbm_cap_mb == "midpoint":
        pre = plan_sharding(big_module(), (seq_len,), optimizer="adam",
                            batch_size=bb, **pin)
        by = {r["config"]["strategy"]: r for r in pre.candidates}
        cap = (by["dp"]["state_bytes_per_device"]
               + by["fsdp"]["state_bytes_per_device"]) // 2
    else:
        cap = int(hbm_cap_mb) * 1024 * 1024
    big = dtpu.Model(big_module())
    big.compile(optimizer=dtpu.optim.Adam(1e-3),
                loss="sparse_categorical_crossentropy",
                strategy="auto", hbm_cap_bytes=cap,
                auto_options=dict(batch_size=bb, **pin))
    big.build((seq_len,))
    big_plan = big.last_plan
    big_tok = rng.integers(0, big_vocab, (bb, seq_len + 1), dtype=np.int64)
    hist = big.fit(big_tok[:, :-1].astype(np.int32),
                   big_tok[:, 1:].astype(np.int32),
                   batch_size=bb, epochs=1, steps_per_epoch=2, verbose=0,
                   seed=0)
    dp_pruned = next(
        (r for r in big_plan.pruned if r.get("config", {}).get("strategy")
         == "dp"), None)
    out["rows"] = [{
        "metric": "autoshard_capped_lm_pick",
        "value": big_plan.chosen["config"]["strategy"],
        "unit": "strategy",
        "hbm_cap_bytes": cap,
        "picked_state_bytes_per_device":
            big_plan.chosen["state_bytes_per_device"],
        "replicated_pruned": dp_pruned is not None,
        "replicated_prune_reason":
            dp_pruned["reason"] if dp_pruned else None,
        "replicated_state_bytes_per_device":
            dp_pruned.get("state_bytes_per_device") if dp_pruned else None,
        "trained_steps": 2,
        "final_loss": round(float(hist.history["loss"][-1]), 4),
        "plan": trim(big_plan),
        "telemetry_plan_recorded":
            "plan" in (big.last_fit_telemetry or {}),
    }]
    del big
    return out


# ---------------------------------------------------------------- pipeline --
def bench_pipeline(vocab=331, num_layers=4, d_model=36, num_heads=2, d_ff=84,
                   seq_len=16, batch=16, max_len=33,
                   il_vocab=64, il_d_model=32, il_seq=8, il_batch=16,
                   warmup=2, measure=10, windows=3, match_tol=0.10,
                   num_requests=8, max_slots=4, block_size=8,
                   prompt_range=(4, 12), new_range=(6, 12), seed=0):
    """Third-axis speed (``python bench.py pipeline``, artifact
    BENCH_pipeline.json; docs/PERF.md "Pipeline round 2"). Three rows:

    1. **Capped pick**: an LM whose dims are all indivisible by the 8-way
       mesh, so ``_largest_divisible_spec`` degrades every flat sharder
       (DP/ZeRO-1/FSDP) to replication while the 4-deep stage stack still
       splits over 'pipe'. Under a midpoint HBM cap the planner must
       prune the flat layouts (rationale recorded) and commit a 2-stage
       pipeline through the real ``compile(strategy="auto")`` path; the
       committed model proves it by training real steps, and the pick is
       validated against ``_time_steps`` measurements of the feasible
       schedule points (``pick_within_tol_of_best`` at the PR 9 10%).
    2. **GPipe vs interleaved**: the same pipelined LM fit under both
       schedules plus the single-device baseline. On one CPU core all
       ranks timeshare, so the MECHANISM is what's asserted — telemetry
       tick/bubble arithmetic (gpipe (n-1)/(M+n-1), interleaved
       (n-1)/(vM+n-1), strictly smaller at fixed M) and loss-trajectory
       parity at rtol 2e-5 — while wall steps/s is recorded honestly
       without claiming a 1-core speedup (the PR 5/13 precedent).
    3. **Paged serving of stacked blocks**: a ``scan=True`` LM served
       through the Engine's paged pools (ScannedBlocks' stacked per-layer
       pools under the ``nn.scan.STACKED_POOL_KEY`` contract), token-exact
       vs per-request dense ``generate()`` under greedy, for the reference
       AND fused decode kernels and composed with the prefix cache."""
    import distributed_tpu.serving as serving
    from distributed_tpu.parallel import plan_sharding

    rng = np.random.default_rng(seed)
    n_dev = len(jax.devices())
    if n_dev < 2:
        raise SystemExit("bench pipeline needs a multi-device mesh (run "
                         "under XLA_FLAGS=--xla_force_host_platform_"
                         "device_count=8 on CPU)")
    pin = dict(grad_accums=(1,), steps_per_execution=(1,))

    # ---- row 1: capped awkward-dims LM -> 2-stage pipeline -------------
    def awkward_module():
        return dtpu.models.transformer_lm(
            vocab, num_layers=num_layers, d_model=d_model,
            num_heads=num_heads, d_ff=d_ff, max_len=max_len, pipeline=True)

    pre = plan_sharding(awkward_module(), (seq_len,), optimizer="adam",
                        batch_size=batch, **pin)
    need = {
        r["label"]: (r["state_bytes_per_device"]
                     + r["activation_bytes_per_device"])
        for r in pre.candidates + [p for p in pre.pruned
                                   if "state_bytes_per_device" in p]
    }
    pp2_need = min(v for k, v in need.items() if k.startswith("pp2"))
    other_need = min(v for k, v in need.items() if not k.startswith("pp2"))
    assert pp2_need < other_need, (
        f"awkward-dims shape lost its point: pp2 needs {pp2_need} vs "
        f"next-best {other_need}")
    cap = (pp2_need + other_need) // 2

    capped = dtpu.Model(awkward_module())
    capped.compile(optimizer=dtpu.optim.Adam(1e-3),
                   loss="sparse_categorical_crossentropy",
                   strategy="auto", hbm_cap_bytes=cap,
                   auto_options=dict(batch_size=batch, **pin))
    capped.build((seq_len,))
    cplan = capped.last_plan
    ccfg = cplan.chosen["config"]
    assert ccfg["strategy"] == "pp" and ccfg["pipeline_parallel"] == 2, (
        f"capped planner picked {cplan.chosen['label']}, wanted a 2-stage "
        f"pipeline")
    for lbl in ("dp", "zero1", "fsdp"):
        row = next(r for r in cplan.pruned if r["label"] == lbl)
        assert "hbm_cap" in row["reason"], (lbl, row["reason"])
    tok = rng.integers(0, vocab, (2 * batch, seq_len + 1), dtype=np.int64)
    xb, yb = tok[:, :-1].astype(np.int32), tok[:, 1:].astype(np.int32)
    hist = capped.fit(xb, yb, batch_size=batch, epochs=1, verbose=0, seed=0)
    assert np.isfinite(hist.history["loss"][-1])
    picked_label = cplan.chosen["label"]
    del capped

    # Validate the pick against measurement: every pp config the capped
    # plan kept feasible, timed with the standard median-of-3 protocol.
    feas = [r["config"] for r in cplan.candidates
            if r["config"]["strategy"] == "pp"]
    rates = {}
    for cfg in feas:
        strat = dtpu.DataPipelineParallel(
            jax.devices(), pipeline_parallel=cfg["pipeline_parallel"],
            num_microbatches=cfg["num_microbatches"])
        with strat.scope():
            m = dtpu.Model(awkward_module())
            m.compile(optimizer=dtpu.optim.Adam(1e-3),
                      loss="sparse_categorical_crossentropy")
            m.build((seq_len,))
        dev_batch = m.strategy.put_batch({"x": xb[:batch], "y": yb[:batch]})
        sps, _ = _time_steps(m, dev_batch, warmup, measure, windows=windows)
        label = f"pp{cfg['pipeline_parallel']}/m{cfg['num_microbatches']}"
        rates[label] = round(sps, 3)
        del m, dev_batch
    measured_best = max(rates, key=rates.get)
    within = rates[picked_label] >= rates[measured_best] * (1.0 - match_tol)

    def trim(p):
        return {
            "chosen": {k: p.chosen[k] for k in
                       ("label", "config", "state_bytes_per_device",
                        "comm_bytes_per_step_per_device",
                        "est_step_seconds")},
            "tie_break": p.tie_break,
            "n_feasible": len(p.candidates),
            "n_pruned": len(p.pruned),
            "pruned": [
                {"label": r["label"], "reason": r["reason"]}
                for r in p.pruned[:8]
            ],
        }

    row1 = {
        "metric": "pipeline_capped_lm_pick",
        "value": picked_label,
        "unit": "config",
        "hbm_cap_bytes": int(cap),
        "flat_layouts_pruned": True,
        "trained_loss": round(float(hist.history["loss"][-1]), 4),
        "measured_steps_per_sec": rates,
        "measured_best": measured_best,
        "pick_matches_measured_best": picked_label == measured_best,
        "pick_within_tol_of_best": bool(within),
        "match_tol": match_tol,
        "plan": trim(cplan),
        "note": "every dim of this LM is indivisible by the 8-way mesh, "
                "so ZeRO/FSDP's largest-divisible-dim rule degrades to "
                "replication and the HBM cap prunes every flat layout; "
                "only the 2-stage schedule points stay feasible",
    }

    # ---- row 2: gpipe vs interleaved bubble + parity -------------------
    pp_n, pp_m, il_v = 2, 4, 2

    def il_model(schedule, v):
        strat = (dtpu.DataPipelineParallel(
                     jax.devices(), pipeline_parallel=pp_n,
                     num_microbatches=pp_m)
                 if schedule is not None else None)
        with (strat.scope() if strat is not None
              else contextlib.nullcontext()):
            # pipeline=True even for the single-device baseline: the SAME
            # module (identical param tree + init) runs PipelinedBlocks'
            # sequential path off the pipe mesh, so parity compares
            # schedules, not architectures.
            m = dtpu.Model(dtpu.models.transformer_lm(
                il_vocab, num_layers=num_layers, d_model=il_d_model,
                num_heads=num_heads, max_len=32, pipeline=True,
                pipeline_schedule=schedule or "gpipe",
                pipeline_interleave=v))
            m.compile(optimizer=dtpu.optim.Adam(1e-3),
                      loss="sparse_categorical_crossentropy")
        m.build((il_seq,))
        return m

    il_tok = rng.integers(0, il_vocab, (il_batch, il_seq + 1),
                          dtype=np.int64)
    ix, iy = il_tok[:, :-1].astype(np.int32), il_tok[:, 1:].astype(np.int32)
    losses, il_rates, traces = {}, {}, {}
    for name, sched, v in (("single_device", None, 1),
                           ("gpipe", "gpipe", 1),
                           ("interleaved", "interleaved", il_v)):
        m = il_model(sched, v)
        h = m.fit(ix, iy, batch_size=il_batch, epochs=2, verbose=0, seed=0)
        losses[name] = [float(l) for l in h.history["loss"]]
        if sched is not None:
            traces[name] = dict(m.last_fit_telemetry["pipeline"])
        dev_batch = m.strategy.put_batch({"x": ix, "y": iy})
        sps, _ = _time_steps(m, dev_batch, warmup, measure, windows=windows)
        il_rates[name] = round(sps, 3)
        del m, dev_batch
    # The 1-core-assertable claims: schedule arithmetic and numerics.
    tg, ti = traces["gpipe"], traces["interleaved"]
    assert tg["ticks"] == pp_m + pp_n - 1 and ti["ticks"] == (
        il_v * pp_m + pp_n - 1), (tg, ti)
    assert abs(tg["bubble_fraction"] - (pp_n - 1) / tg["ticks"]) < 1e-6
    assert abs(ti["bubble_fraction"] - (pp_n - 1) / ti["ticks"]) < 1e-6
    assert ti["bubble_fraction"] < tg["bubble_fraction"]
    np.testing.assert_allclose(losses["gpipe"], losses["single_device"],
                               rtol=2e-5)
    np.testing.assert_allclose(losses["interleaved"],
                               losses["single_device"], rtol=2e-5)
    row2 = {
        "metric": "pipeline_interleaved_bubble_fraction",
        "value": ti["bubble_fraction"],
        "unit": "idle fraction",
        "gpipe_bubble_fraction": tg["bubble_fraction"],
        "bubble_shrink": round(
            1.0 - ti["bubble_fraction"] / tg["bubble_fraction"], 4),
        "schedule_shape": {"num_stages": pp_n, "num_microbatches": pp_m,
                           "interleave": il_v,
                           "gpipe_ticks": tg["ticks"],
                           "interleaved_ticks": ti["ticks"]},
        "loss_parity_rtol": 2e-5,
        "steps_per_sec": il_rates,
        "wall_speedup_interleaved_vs_gpipe": round(
            il_rates["interleaved"] / il_rates["gpipe"], 3),
        "speedup_asserted": False,
        "note": "all pipe ranks timeshare ONE CPU core here, so the "
                "bubble's idle ticks cost the same wall time as work "
                "ticks and the interleaved schedule's extra laps ADD "
                "per-tick overhead; the asserted claims are the tick/"
                "bubble arithmetic and rtol-2e-5 loss parity — the wall "
                "win needs ranks on separate chips",
    }

    # ---- row 3: paged serving of stacked blocks ------------------------
    lm = dtpu.Model(dtpu.models.transformer_lm(
        il_vocab, num_layers=num_layers, d_model=il_d_model,
        num_heads=num_heads, max_len=64, scan=True))
    lm.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    lm.build((16,))
    prompts = [
        rng.integers(0, il_vocab, (int(n),)).astype(np.int32)
        for n in rng.integers(prompt_range[0], prompt_range[1] + 1,
                              num_requests)
    ]
    news = rng.integers(new_range[0], new_range[1] + 1,
                        num_requests).astype(int)
    useful = int(np.sum(news))
    dense = [lm.generate(p[None], int(m), temperature=0.0)[0]
             for p, m in zip(prompts, news)]
    serve_rows = []
    for name, kwargs in (("reference", {}),
                         ("fused", {"decode_kernel": "fused"}),
                         ("fused_prefix", {"decode_kernel": "fused",
                                           "prefix_cache": True})):
        eng = serving.Engine(lm, max_slots, block_size, max_len=64,
                             **kwargs)
        reqs = [serving.Request(p, int(m)) for p, m in zip(prompts, news)]
        outs = eng.run(list(reqs))  # warm
        outs = eng.run(list(reqs))
        for i, (w, g) in enumerate(zip(dense, outs)):
            assert np.array_equal(w, g), (
                f"stacked paged serving ({name}) diverged from dense "
                f"generate on request {i}")
        t = eng.last_run_telemetry
        serve_rows.append({
            "config": name,
            "token_exact_vs_dense": True,
            "tokens_per_sec": round(useful / t["total_seconds"], 2),
            "decode_steps": t["decode_steps"],
        })
        del eng
    row3 = {
        "metric": "pipeline_stacked_paged_serving_token_exact",
        "value": True,
        "unit": "bool",
        "configs": serve_rows,
        "note": "ScannedBlocks serves through per-layer paged pools "
                "stacked under one reserved 'stacked' key (pool-block "
                "axis 1); the engine, CoW prefix store, and fused kernel "
                "compose unchanged",
    }

    return {
        "metric": row2["metric"],
        "value": row2["value"],
        "unit": row2["unit"],
        "rows": [row1, row2, row3],
        "backend": jax.default_backend(),
    }


def main(modes=("mnist", "multistep", "overlap", "convergence", "cifar",
                "resnet50", "lm")):
    known = {"mnist", "multistep", "overlap", "input", "convergence",
             "cifar", "resnet50", "lm", "longctx", "resilience", "zero",
             "precision", "compile_cache", "serve", "elastic", "quant",
             "fused_update", "autoshard", "fleet", "rl", "recovery", "obs",
             "prefix", "spec", "service", "overlap2", "decode_kernel",
             "pipeline"}
    unknown = set(modes) - known
    if unknown or not modes:
        raise SystemExit(
            f"unknown bench mode(s) {sorted(unknown)}; choose from {sorted(known)}"
        )
    headline = bench_mnist() if "mnist" in modes else None
    extra = []
    if "multistep" in modes:
        extra.append(bench_multi_step())
    if "overlap" in modes:
        extra.append(bench_overlap())
    if "input" in modes:
        # Opt-in: decode-bound record streaming at decode_workers W
        # (BENCH_input.json; docs/PERF.md "Streaming input").
        extra.append(bench_input())
    if "convergence" in modes:
        extra.append(bench_convergence())
    if "cifar" in modes:
        extra.append(bench_cifar())
    if "resnet50" in modes:
        extra.append(bench_resnet50())
    if "lm" in modes:
        extra.append(bench_transformer_lm())
    if "longctx" in modes:
        extra.append(bench_longctx())
    if "zero" in modes:
        # Opt-in: ZeRO-1/FSDP memory + throughput vs replicated DP
        # (BENCH_zero.json; docs/PERF.md "Memory: ZeRO & gradient
        # accumulation").
        extra.append(bench_zero())
    if "precision" in modes:
        # Opt-in: f32 vs mixed_bfloat16 under FSDP (BENCH_precision.json;
        # docs/PERF.md "Mixed precision").
        extra.append(bench_precision())
    if "resilience" in modes:
        # Opt-in (like longctx): spawns supervised worker subprocesses.
        extra.append(bench_resilience())
    if "compile_cache" in modes:
        # Opt-in: cold-vs-warm persistent-compile-cache restart latency
        # (BENCH_compile_cache.json; ROADMAP item 0).
        extra.append(bench_compile_cache())
    if "serve" in modes:
        # Opt-in: continuous batching + paged KV serving vs static-batch
        # generate() (BENCH_serve.json; docs/SERVING.md).
        extra.append(bench_serve())
    if "prefix" in modes:
        # Opt-in: serving memory economy — refcounted prefix KV sharing,
        # int8 KV cache, speculative decoding, suffix-only fleet handoff
        # (BENCH_prefix.json; docs/SERVING.md "Prefix caching &
        # speculative decoding").
        extra.append(bench_prefix())
    if "spec" in modes:
        # Opt-in: speculation that pays — distilled draft accept >= 0.5,
        # virtual-timeline throughput vs vanilla, cross-replica prefix
        # gossip TTFT, adaptive spec_k recompile-free (BENCH_spec.json;
        # docs/SERVING.md "Draft models & gossip", docs/PERF.md "When
        # speculation pays").
        extra.append(bench_spec())
    if "fleet" in modes:
        # Opt-in: disaggregated prefill/decode fleet — tokens/s scaling
        # vs replica count, tail TTFT under bursty arrivals, and the
        # kill-a-replica recovery row (BENCH_fleet.json;
        # docs/SERVING.md "Fleet").
        extra.append(bench_fleet())
    if "service" in modes:
        # Opt-in: the fleet as REAL worker processes on WALL time —
        # shm KV transport, streaming byte-identity, process-kill
        # recovery with postmortem, tenant quotas (BENCH_service.json;
        # docs/SERVING.md "Running as a service"). Canonical spelling:
        # `python bench.py fleet --clock wall`.
        extra.append(bench_service())
    if "rl" in modes:
        # Opt-in: online post-training closed loop — rollout tokens/s,
        # train steps/s, weight-sync latency, reward improvement, and the
        # hot-swap-vs-restart row (BENCH_rl.json; docs/RL.md).
        extra.append(bench_rl())
    if "elastic" in modes:
        # Opt-in: elastic gang 4->2->4 resize-to-first-step latency
        # (BENCH_elastic.json; docs/RESILIENCE.md "Elastic gangs").
        extra.append(bench_elastic())
    if "recovery" in modes:
        # Opt-in: diskless buddy-tier vs disk-tier recovery on the
        # supervised-gang protocol (BENCH_recovery.json;
        # docs/RESILIENCE.md "Recovery tiers").
        extra.append(bench_recovery())
    if "obs" in modes:
        # Opt-in: instrumented-vs-bare fit overhead (<= 3% asserted) +
        # supervised-gang straggler attribution (BENCH_obs.json;
        # docs/OBSERVABILITY.md).
        extra.append(bench_obs())
    if "quant" in modes:
        # Opt-in: int8 weight-only serving bytes + decode fidelity + FSDP
        # gather accounting (BENCH_quant.json; docs/PERF.md "Quantization
        # & fused updates").
        extra.append(bench_quant())
    if "fused_update" in modes:
        # Opt-in: fused Adam Pallas kernel update-phase time vs stock
        # optax (rides in BENCH_quant.json).
        extra.append(bench_fused_update())
    if "overlap2" in modes:
        # Opt-in (multi-device mesh, like zero): FSDP scan gather-prefetch
        # overlap — loss parity + span-attributed exposed-comm drop
        # (BENCH_overlap2.json; docs/PERF.md "Overlap round 2").
        extra.append(bench_overlap2())
    if "decode_kernel" in modes:
        # Opt-in: fused paged-attention decode kernel vs reference path —
        # token-exact across serving configs + tokens/s
        # (BENCH_decode_kernel.json; docs/PERF.md "Fused paged
        # attention").
        extra.append(bench_decode_kernel())
    if "autoshard" in modes:
        # Opt-in: compile(strategy="auto") re-picking the BENCH_zero
        # known-best configs (BENCH_autoshard.json; docs/PERF.md
        # "Autotuned sharding").
        extra.append(bench_autoshard())
    if "pipeline" in modes:
        # Opt-in (multi-device mesh, like zero): interleaved-vs-GPipe
        # bubble + parity, the capped planner picking a 2-stage pipeline,
        # and paged serving of stacked blocks (BENCH_pipeline.json;
        # docs/PERF.md "Pipeline round 2").
        extra.append(bench_pipeline())
    result = headline or extra.pop(0)
    if extra:
        result["extra"] = extra
    result["device"] = jax.devices()[0].device_kind
    # Self-describing measurement protocol: BENCH_r01 predates the host-
    # fetch barrier (jax.block_until_ready is a no-op on the tunneled
    # transport) and records unsynced dispatch rates — cross-round readers
    # must not read the r01->r02 drop as a regression. Stamping the sync
    # method makes each artifact carry its own validity conditions.
    result["protocol"] = {
        "sync": "host-fetch barrier after each timing window "
                "(device_get; block_until_ready is a no-op on this "
                "transport)",
        "windows": "median of 3 independent windows, >=20 steps each, for "
                   "every throughput mode (raw per-window rates persisted "
                   "as window_steps_per_sec); dispatch jitter on this "
                   "transport is +/-10-30% for dispatch-bound models "
                   "(docs/PERF.md)",
        # Same measured quantity as rounds 2-4 (host-fetch-synced steady
        # rate); round 5 only tightened the estimator (1 window -> median
        # of 3 everywhere), so cross-round comparison is still valid.
        "comparable_since_round": 2,
        "median_of_3_since_round": 5,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    argv = list(sys.argv[1:])
    # `bench.py fleet --clock wall` is the canonical spelling of the
    # real-process service mode (the fleet's virtual-clock caveat,
    # measured away): rewrite it to the `service` mode name.
    if "--clock" in argv:
        i = argv.index("--clock")
        clock = argv[i + 1] if i + 1 < len(argv) else None
        if clock != "wall":
            raise SystemExit(
                f"--clock takes 'wall' (real processes, wall time), "
                f"got {clock!r}; the fleet mode's virtual clock is the "
                f"default"
            )
        del argv[i:i + 2]
        argv = ["service" if m == "fleet" else m for m in argv] or [
            "service"]
    main(tuple(argv)
         or ("mnist", "multistep", "overlap", "convergence", "cifar",
             "resnet50", "lm"))
