"""distributed_tpu — a TPU-native distributed training framework.

Capability parity with the reference system (Mrhs121/distributed: TF 2.0
MultiWorkerMirroredStrategy over TF_CONFIG/gRPC, driven from R, Python and
Spark — see SURVEY.md), re-designed for TPU: jit-compiled train steps,
device meshes + NamedSharding for parallelism, XLA collectives over ICI/DCN,
`jax.distributed` for multi-host bootstrap.

Quickstart (the reference's local->distributed 6-line-diff contract):

    import distributed_tpu as dtpu

    x, y = dtpu.data.load_mnist("train")
    model = dtpu.Model(dtpu.models.mnist_cnn())
    model.compile(optimizer=dtpu.optim.SGD(0.001),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, batch_size=64, epochs=3)

    # distributed: wrap construction in a strategy scope
    strategy = dtpu.DataParallel()
    with strategy.scope():
        model = dtpu.Model(dtpu.models.mnist_cnn())
        model.compile(...)
    model.fit(x, y, batch_size=64 * strategy.num_replicas_in_sync, epochs=3)
"""

from . import cluster, data, models, nn, ops, optim, parallel, precision, utils
from . import obs  # jax-free at import; spans resolve jax lazily
from .precision import Policy
from .checkpoint import Checkpointer, ShardedCheckpointer, export_hdf5, import_hdf5
from .training import callbacks
from . import resilience  # after training/checkpoint: builds on both
from . import serving  # after training: Engine builds on Model
from .ops import losses, metrics
from .parallel.mesh import make_mesh
from .parallel.strategy import (
    CompositeParallel,
    DataParallel,
    DataPipelineParallel,
    DataSeqParallel,
    DataExpertParallel,
    DataTensorParallel,
    FSDP,
    FullyShardedDataParallel,
    MultiWorkerMirroredStrategy,
    SingleDevice,
    Strategy,
    ZeroDataParallel,
    current_strategy,
)
from .training.history import History
from .training.model import Model
from .version import __version__


def __getattr__(name):
    # `dtpu.quant` resolves lazily rather than via an eager top-level
    # import: the raw-speed tier (quant, and through optim.fused_adam /
    # ops.fused_update the Pallas optimizer kernel) must never add to the
    # base import cost on CPU boxes. quant itself is light (jnp only) and
    # usually already bound by nn's layer imports; the Pallas machinery
    # stays behind ops.__getattr__ until an API that needs it is called.
    if name in ("quant", "fleet", "rl"):
        # fleet (the multi-replica serving tier) and rl (online
        # post-training) are lazy for the same reason: processes that
        # only train or only serve never pay for them.
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "Model",
    "History",
    "Strategy",
    "SingleDevice",
    "CompositeParallel",
    "DataParallel",
    "DataPipelineParallel",
    "DataSeqParallel",
    "DataExpertParallel",
    "DataTensorParallel",
    "FSDP",
    "FullyShardedDataParallel",
    "MultiWorkerMirroredStrategy",
    "ZeroDataParallel",
    "current_strategy",
    "make_mesh",
    "Checkpointer",
    "ShardedCheckpointer",
    "export_hdf5",
    "import_hdf5",
    "nn",
    "ops",
    "optim",
    "precision",
    "Policy",
    "losses",
    "metrics",
    "models",
    "data",
    "parallel",
    "cluster",
    "utils",
    "callbacks",
    "obs",
    "resilience",
    "serving",
    "fleet",  # lazy: see __getattr__
    "quant",  # lazy: see __getattr__
    "rl",  # lazy: see __getattr__
    "__version__",
]
