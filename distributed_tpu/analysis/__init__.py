"""Repo-aware static analysis (``dtpu-lint``).

Machine-checks the framework's hardest-won cross-cutting invariants on
every PR instead of re-discovering them in production postmortems:

- ``jax-free-import``   — declared jax-free modules stay jax-free
  through their TRANSITIVE module-scope import graph (imports.py);
- ``writer-thread``     — ``dtpu-*writer`` background threads never
  statically reach a collective (threads.py);
- ``trace-purity``      — no host-impure calls inside jit-traced code
  (purity.py);
- ``event-schema``      — every ``emit(...)`` site agrees with the
  declared event schema in ``utils/event_schema.py`` (events.py);
- ``thread-hygiene``    — every ``threading.Thread`` is daemonized and
  ``dtpu-*``-named (threads.py).

Entry points: the ``dtpu-lint`` console script (cli.py, pyproject),
``python -m distributed_tpu.analysis.cli``, and the library surface
below (tests drive rules directly on synthetic trees). Catalog, escape
hatches (``# dtpu-lint: allow[rule]`` comments, the checked-in baseline
file) and the add-a-rule walk: docs/ANALYSIS.md.

jax-free at import — the linter runs on controller and CI boxes and
never imports the code it analyzes.
"""

from .core import (
    Finding,
    SourceTree,
    apply_baseline,
    load_baseline,
    make_rules,
    rule_names,
    run_rules,
    write_baseline,
)
from .imports import JAX_FREE_MODULES

__all__ = [
    "Finding",
    "JAX_FREE_MODULES",
    "SourceTree",
    "apply_baseline",
    "load_baseline",
    "make_rules",
    "rule_names",
    "run_rules",
    "write_baseline",
]
