"""``dtpu-lint``: the framework's repo-aware static analysis gate.

    dtpu-lint                       # lint the installed package tree
    dtpu-lint path/to/pkg           # lint an arbitrary tree
    dtpu-lint --rules event-schema,thread-hygiene
    dtpu-lint --write-baseline      # accept current findings
    dtpu-lint --list-rules

Findings print as ``path:line: RULE-ID message`` and the exit status is
non-zero when any survive the allowlist comments and the baseline file
(default ``<scan-parent>/.dtpu-lint-baseline``). Run by scripts/lint.sh
and as the tier-1 gate in scripts/tier1.sh; rule catalog and the
allowlist/baseline workflow live in docs/ANALYSIS.md.

jax-free: the linter parses source, it never imports the code under
analysis.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path
from typing import List, Optional

from . import core


def _default_paths() -> List[Path]:
    return [Path(__file__).resolve().parents[1]]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="dtpu-lint", description=__doc__)
    ap.add_argument("paths", nargs="*", type=Path,
                    help="directories/files to lint (default: the "
                         "distributed_tpu package itself)")
    ap.add_argument("--rules", type=str, default=None,
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--baseline", type=Path, default=None,
                    help="baseline file of findings deliberately kept "
                         "(default: .dtpu-lint-baseline next to the "
                         "first scanned tree)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings as the baseline "
                         "and exit 0")
    ap.add_argument("--jax-free", action="append", default=[],
                    metavar="MODULE",
                    help="extra module(s) for the jax-free-import "
                         "manifest (repeatable)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as one JSON array")
    args = ap.parse_args(argv)

    if args.list_rules:
        for n in core.rule_names():
            print(n)
        return 0

    paths = [p.resolve() for p in (args.paths or _default_paths())]
    for p in paths:
        if not p.exists():
            print(f"dtpu-lint: no such path: {p}", file=sys.stderr)
            return 2
    baseline_path = args.baseline
    if baseline_path is None:
        baseline_path = paths[0].parent / ".dtpu-lint-baseline"

    t0 = time.perf_counter()
    tree = core.SourceTree(paths)
    if tree.errors:
        for e in tree.errors:
            print(f"dtpu-lint: {e}", file=sys.stderr)
        return 2

    try:
        rules = core.make_rules(
            args.rules.split(",") if args.rules else None,
            **{"jax-free-import": {"extra_manifest": tuple(args.jax_free)}},
        )
    except KeyError as e:
        print(f"dtpu-lint: {e.args[0]}", file=sys.stderr)
        return 2

    findings = core.run_rules(tree, rules)
    if args.write_baseline:
        core.write_baseline(baseline_path, findings)
        print(f"dtpu-lint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}")
        return 0
    kept, suppressed = core.apply_baseline(
        findings, core.load_baseline(baseline_path)
    )
    elapsed = time.perf_counter() - t0

    if args.json:
        print(json.dumps([f.__dict__ for f in kept]))
    else:
        for f in kept:
            print(f.render())
        print(
            f"dtpu-lint: {len(kept)} finding(s) "
            f"({suppressed} baselined) over {len(tree.files)} files "
            f"in {elapsed:.2f}s"
        )
    return 1 if kept else 0


if __name__ == "__main__":
    sys.exit(main())
