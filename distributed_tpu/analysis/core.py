"""dtpu-lint core: source model, findings, allowlist, baseline, runner.

The framework's hardest correctness rules — jax-free-at-import,
writer-thread collective discipline, trace purity, event-schema
agreement, thread hygiene — are repo-specific invariants no generic
linter knows. This package is the standing machine check: an AST-level
analyzer with a pluggable rule registry, run as the ``dtpu-lint``
console script and as the tier-1 lint gate (scripts/tier1.sh invokes it
before pytest).

Vocabulary:

- :class:`SourceFile` / :class:`SourceTree` — parsed ``.py`` files with
  repo-relative paths and dotted module names. Parsing is the only I/O;
  nothing here imports the code under analysis (the linter stays cheap
  and side-effect-free, and can lint a tree that would not even import).
- :class:`Finding` — one violation, rendered ``path:line: RULE-ID
  message``. The baseline identity is ``(rule, path, message)`` — line
  numbers drift with unrelated edits and are deliberately excluded.
- Allowlist — ``# dtpu-lint: allow[rule-id]`` on (or one line above)
  the offending line suppresses that rule there. For findings whose
  anchor is a multi-line statement the comment goes on the statement's
  first line. Allowlists live next to the code they excuse; the
  baseline file is for findings kept at the TREE level (see
  :func:`load_baseline`).
- Baseline — a checked-in text file of findings deliberately kept
  (``<rule> <path> :: <message>`` lines, ``#`` comments). ``dtpu-lint
  --write-baseline`` regenerates it from the current findings.

Rules register via :func:`register` and implement
``check(tree) -> List[Finding]``. See docs/ANALYSIS.md for the catalog
and the how-to-add-a-rule walk.

jax-free at import (the linter runs on controller/CI boxes).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

ALLOW_RE = re.compile(r"dtpu-lint:\s*allow\[([a-z0-9_,-]+)\]")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str  # repo-relative posix path
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    @property
    def baseline_key(self) -> str:
        # Line-number-free: a baselined finding survives unrelated edits
        # above it. Messages therefore must not embed line numbers.
        return f"{self.rule} {self.path} :: {self.message}"


class SourceFile:
    """One parsed module: AST + text + allowlist-comment lookup."""

    def __init__(self, path: Path, root: Path):
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=str(path))
        parts = path.relative_to(root).with_suffix("").parts
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        self.module = ".".join(parts)

    def _line_allows(self, rule: str, lineno: int) -> bool:
        if not (1 <= lineno <= len(self.lines)):
            return False
        m = ALLOW_RE.search(self.lines[lineno - 1])
        return bool(m) and rule in m.group(1).split(",")

    def allows(self, rule: str, lineno: int) -> bool:
        """True when ``# dtpu-lint: allow[rule]`` sits on the line or the
        line immediately above it (the comment-above idiom for lines
        already at width)."""
        return (self._line_allows(rule, lineno)
                or self._line_allows(rule, lineno - 1))


class SourceTree:
    """Every ``.py`` file under the scan roots, parsed once.

    Module names derive from the path relative to each root's PARENT, so
    scanning ``<repo>/distributed_tpu`` yields ``distributed_tpu.x.y``
    names and a synthetic fixture tree ``tmp/pkg`` yields ``pkg.mod`` —
    the import-graph rule works identically on both.
    """

    def __init__(self, paths: Sequence[Path]):
        self.files: List[SourceFile] = []
        self.errors: List[str] = []
        by_module: Dict[str, SourceFile] = {}
        for p in paths:
            p = Path(p).resolve()
            # A package dir (has __init__.py) contributes its own name to
            # module paths (scan distributed_tpu/ -> distributed_tpu.x.y);
            # a plain workspace dir does not (scan tmp/ -> pkg.mod for
            # tmp/pkg/mod.py).
            is_pkg = p.is_dir() and (p / "__init__.py").exists()
            root = p.parent if (is_pkg or p.is_file()) else p
            candidates = (
                sorted(p.rglob("*.py")) if p.is_dir() else [p]
            )
            for f in candidates:
                if "__pycache__" in f.parts:
                    continue
                try:
                    sf = SourceFile(f, root)
                except (OSError, SyntaxError, ValueError) as e:
                    self.errors.append(f"{f}: unparseable ({e})")
                    continue
                self.files.append(sf)
                by_module[sf.module] = sf
        self.by_module = by_module

    def find_file(self, name: str) -> Optional[SourceFile]:
        """The first file whose basename matches ``name`` (e.g. a tree's
        own ``event_schema.py``)."""
        for sf in self.files:
            if sf.path.name == name:
                return sf
        return None


# ------------------------------------------------------------ registry
_RULES: Dict[str, type] = {}


def register(cls):
    """Class decorator: adds the rule to the registry under ``cls.name``."""
    _RULES[cls.name] = cls
    return cls


def rule_names() -> List[str]:
    _load_builtin_rules()
    return sorted(_RULES)


def _load_builtin_rules():
    # Imported here (not at module top) so core stays import-cycle-free:
    # the rule modules import core for Finding/register.
    from . import events as _e  # noqa: F401
    from . import imports as _i  # noqa: F401
    from . import purity as _p  # noqa: F401
    from . import threads as _t  # noqa: F401


def make_rules(names: Optional[Iterable[str]] = None, **overrides):
    """Instantiate rules by name (default: all registered). ``overrides``
    maps rule name -> kwargs dict for that rule's constructor (the CLI
    uses it for --jax-free manifest additions)."""
    _load_builtin_rules()
    selected = list(names) if names is not None else sorted(_RULES)
    out = []
    for n in selected:
        if n not in _RULES:
            raise KeyError(
                f"unknown rule {n!r} (known: {', '.join(sorted(_RULES))})"
            )
        out.append(_RULES[n](**overrides.get(n, {})))
    return out


def run_rules(tree: SourceTree, rules) -> List[Finding]:
    """All findings from ``rules`` over ``tree``, allowlist applied,
    sorted by (path, line, rule)."""
    findings: List[Finding] = []
    for rule in rules:
        for f in rule.check(tree):
            sf = next((s for s in tree.files if s.rel == f.path), None)
            if sf is not None and sf.allows(f.rule, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings


# ------------------------------------------------------------ baseline
def load_baseline(path) -> List[str]:
    """Baseline keys from a checked-in file: one ``<rule> <path> ::
    <message>`` per line, ``#`` comments and blanks ignored. Missing
    file = empty baseline."""
    try:
        text = Path(path).read_text()
    except OSError:
        return []
    keys = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        keys.append(line)
    return keys


def write_baseline(path, findings: Sequence[Finding]) -> None:
    lines = [
        "# dtpu-lint baseline — findings deliberately kept, with rationale.",
        "# One `<rule> <path> :: <message>` per line; regenerate with",
        "#   dtpu-lint --write-baseline",
        "# Prefer a `# dtpu-lint: allow[rule]` comment AT the code site for",
        "# single-line keeps; use this file for tree-level decisions.",
        "",
    ]
    lines += [f.baseline_key for f in findings]
    Path(path).write_text("\n".join(lines) + "\n")


def apply_baseline(findings: Sequence[Finding],
                   keys: Sequence[str]) -> Tuple[List[Finding], int]:
    """(kept findings, suppressed count)."""
    keyset = set(keys)
    kept = [f for f in findings if f.baseline_key not in keyset]
    return kept, len(findings) - len(kept)


# ---------------------------------------------------------- AST helpers
def dotted_name(node) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None. ``self.x`` and
    ``cls.x`` drop the receiver (``x``) so method calls resolve by name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        if node.id not in ("self", "cls"):
            parts.append(node.id)
    elif parts:
        # computed receiver (f(x).attr, d[k].attr): keep the attr chain
        pass
    else:
        return None
    return ".".join(reversed(parts)) if parts else None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def iter_module_scope(tree: ast.Module):
    """Statements that execute at import time: module-level statements,
    recursing into If/Try/With and ClassDef bodies (all run at import)
    but never into function bodies (those run at call time)."""
    stack = list(tree.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.If, ast.Try, ast.With, ast.For,
                             ast.While, ast.ClassDef)):
            for field in ("body", "orelse", "finalbody", "handlers"):
                for child in getattr(node, field, []):
                    if isinstance(child, ast.ExceptHandler):
                        stack.extend(child.body)
                    else:
                        stack.append(child)


def literal_str_prefix(node) -> Optional[str]:
    """The static string prefix of a str constant or f-string (the part
    before the first interpolation); None for non-strings."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        if node.values and isinstance(node.values[0], ast.Constant) \
                and isinstance(node.values[0].value, str):
            return node.values[0].value
        return ""  # f-string starting with an interpolation
    return None
