"""Rule ``event-schema``: emit sites agree with the declared event schema.

``utils/event_schema.py`` declares every event kind on the JSONL stream
with the keys its consumers require (the postmortem CLI, the cross-rank
aggregation, ``recovery_rows``). This rule closes the producer side:
every ``emit(...)`` / ``log.emit(...)`` / ``self._emit(...)`` call site
whose event name is statically resolvable (a string literal or a schema
name constant) is checked —

- the event name must be declared in the schema;
- all required keys must be passed as literal keywords (unless the call
  spreads ``**fields``, which is statically opaque — then only the keys
  that ARE literal are validated);
- no undeclared keys, unless the event is marked ``extra`` (open-payload
  events like a plan summary).

Producer/consumer drift — a renamed key, a consumer growing a new
required field, an emit site typo — becomes a lint error instead of a
postmortem that silently renders half-empty.

The schema is read STATICALLY (AST, never imported): name constants are
plain string assignments and ``EVENTS`` is a dict literal, a shape the
schema module's own docstring pins. A scanned tree containing its own
``event_schema.py`` (fixture trees in tests) is preferred over the
packaged one.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, SourceFile, SourceTree, register

_EMIT_NAMES = frozenset({"emit", "_emit"})


def _schema_ast(tree: SourceTree):
    sf = tree.find_file("event_schema.py")
    if sf is not None:
        return sf.tree
    default = Path(__file__).resolve().parent.parent / "utils" \
        / "event_schema.py"
    return ast.parse(default.read_text(), filename=str(default))


def load_schema(tree: SourceTree) -> Tuple[Dict[str, dict], Dict[str, str]]:
    """(schemas, constants): ``schemas`` maps event name -> {"required",
    "optional", "extra"}; ``constants`` maps CONSTANT identifier -> event
    name, for resolving ``emit(event_schema.RESTORE_BEGIN, ...)``."""
    mod = _schema_ast(tree)
    constants: Dict[str, str] = {}
    events_node = None
    for node in mod.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            tgt = node.targets[0].id
            if isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                constants[tgt] = node.value.value
            elif tgt == "EVENTS" and isinstance(node.value, ast.Dict):
                events_node = node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.target.id == "EVENTS" \
                and isinstance(node.value, ast.Dict):
            events_node = node.value
    schemas: Dict[str, dict] = {}
    if events_node is None:
        return schemas, constants
    for key, val in zip(events_node.keys, events_node.values):
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            name = key.value
        elif isinstance(key, ast.Name) and key.id in constants:
            name = constants[key.id]
        else:
            continue
        if not isinstance(val, ast.Dict):
            continue
        row = {"required": (), "optional": (), "extra": False}
        for k, v in zip(val.keys, val.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            if k.value in ("required", "optional") \
                    and isinstance(v, (ast.Tuple, ast.List)):
                row[k.value] = tuple(
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                )
            elif k.value == "extra" and isinstance(v, ast.Constant):
                row["extra"] = bool(v.value)
        schemas[name] = row
    return schemas, constants


def _emit_event_name(call: ast.Call,
                     constants: Dict[str, str]) -> Optional[str]:
    """The statically-resolved event name of an emit call, or None when
    the first argument is dynamic (wrapper functions forwarding a
    parameter are not checkable — their CALLERS are)."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    ident = None
    if isinstance(arg, ast.Name):
        ident = arg.id
    elif isinstance(arg, ast.Attribute):
        ident = arg.attr
    if ident is not None and ident in constants:
        return constants[ident]
    return None


def _is_emit(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in _EMIT_NAMES
    if isinstance(f, ast.Attribute):
        return f.attr in _EMIT_NAMES
    return False


@register
class EventSchemaRule:
    name = "event-schema"

    def check(self, tree: SourceTree) -> List[Finding]:
        schemas, constants = load_schema(tree)
        findings: List[Finding] = []
        if not schemas:
            return findings
        for sf in tree.files:
            if sf.path.name == "event_schema.py":
                continue
            findings.extend(self._check_file(sf, schemas, constants))
        return findings

    def _check_file(self, sf: SourceFile, schemas, constants):
        out: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and _is_emit(node)):
                continue
            name = _emit_event_name(node, constants)
            if name is None:
                continue
            if name not in schemas:
                out.append(Finding(
                    self.name, sf.rel, node.lineno,
                    f"emit of undeclared event '{name}' (declare it in "
                    f"utils/event_schema.py with its required/optional "
                    f"keys, or fix the typo)",
                ))
                continue
            row = schemas[name]
            explicit: Set[str] = {
                kw.arg for kw in node.keywords if kw.arg is not None
            }
            spread = any(kw.arg is None for kw in node.keywords)
            required = set(row["required"])
            declared = required | set(row["optional"])
            missing = sorted(required - explicit)
            if missing and not spread:
                out.append(Finding(
                    self.name, sf.rel, node.lineno,
                    f"emit('{name}') is missing required key(s) "
                    f"{', '.join(missing)} (consumers index these "
                    f"unconditionally — see utils/event_schema.py)",
                ))
            unknown = sorted(explicit - declared)
            if unknown and not row["extra"]:
                out.append(Finding(
                    self.name, sf.rel, node.lineno,
                    f"emit('{name}') passes undeclared key(s) "
                    f"{', '.join(unknown)} (add them to the event's "
                    f"schema in utils/event_schema.py so consumers know "
                    f"they exist)",
                ))
        return out
