"""Rule ``jax-free-import``: declared jax-free modules must stay jax-free
TRANSITIVELY at module scope.

The framework's controller-side surfaces — the supervisor, the obs
registry/aggregation/CLI, the fleet router, the event transport — carry
"jax-free at import" contracts in their docstrings: they must be cheap
to import on controller/CI processes and unit-testable with jax
monkeypatched out. Before this rule the contract was prose asserted in
~15 docstrings and broken silently: a module three hops down adds one
top-level ``import jax`` and every "jax-free" importer above it now
pays (and requires) the jax world.

Mechanics: every file's MODULE-SCOPE imports (top-level statements,
recursing into if/try/with/class bodies — all execute at import — but
never into function bodies) become graph edges. ``from pkg import sub``
resolves to the submodule when one exists in the scanned tree, else to
``pkg`` (its ``__init__`` defines the symbol, and runs). The rule walks
the closure from each manifest module and reports the full chain to
``jax``/``jaxlib`` when one exists.

Scope note: ancestor-package ``__init__`` execution is deliberately NOT
an edge (importing ``a.b.c`` runs ``a/__init__``). The top-level
``distributed_tpu/__init__`` eagerly builds the training world, so the
file-level graph is the contract these modules can actually keep — it
bounds what the MODULE ITSELF drags in, which is what jax-out
unit tests and import-cost budgets observe.

The manifest below is the declared list; ``dtpu-lint --jax-free mod``
appends entries for one run (fixture trees in tests use this).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding, SourceTree, iter_module_scope, register

POISON = ("jax", "jaxlib")

#: Modules contractually jax-free at import. Grow this list whenever a
#: docstring claims jax-freeness — the claim is only real once it is
#: machine-checked here.
JAX_FREE_MODULES: Tuple[str, ...] = (
    # observability: importable on controller boxes next to the event log
    "distributed_tpu.obs",
    "distributed_tpu.obs.aggregate",
    "distributed_tpu.obs.cli",
    "distributed_tpu.obs.export",
    "distributed_tpu.obs.flight",
    "distributed_tpu.obs.registry",
    "distributed_tpu.obs.spans",
    # event transport + schema + logging
    "distributed_tpu.utils.compile_cache",
    "distributed_tpu.utils.event_schema",
    "distributed_tpu.utils.events",
    "distributed_tpu.utils.logging",
    # resilience controller side (the supervisor runs where jax may not)
    "distributed_tpu.resilience.elastic",
    "distributed_tpu.resilience.markers",
    "distributed_tpu.resilience.policy",
    "distributed_tpu.resilience.supervisor",
    # fleet control plane (pure host arithmetic)
    "distributed_tpu.fleet.autoscale",
    "distributed_tpu.fleet.router",
    # gang launcher + the pieces it stands on
    "distributed_tpu.cluster.config",
    "distributed_tpu.cluster.net",
    "distributed_tpu.launch.core",
    "distributed_tpu.serving.scheduler",
    # serving service router side (the router process never pays a jax
    # import; serve_service.worker is the ONE jax module and is spawned,
    # never imported, by these)
    "distributed_tpu.serve_service",
    "distributed_tpu.serve_service.protocol",
    "distributed_tpu.serve_service.quotas",
    "distributed_tpu.serve_service.service",
    "distributed_tpu.serve_service.transport",
    # the linter itself
    "distributed_tpu.analysis",
    "distributed_tpu.analysis.cli",
    "distributed_tpu.analysis.core",
    "distributed_tpu.analysis.events",
    "distributed_tpu.analysis.imports",
    "distributed_tpu.analysis.purity",
    "distributed_tpu.analysis.threads",
)


def module_scope_imports(sf) -> List[Tuple[str, int]]:
    """``(dotted-target, lineno)`` per module-scope import of ``sf``,
    resolved to absolute dotted names (relative levels applied)."""
    is_init = sf.path.name == "__init__.py"
    pkg_parts = sf.module.split(".") if sf.module else []
    if not is_init:
        pkg_parts = pkg_parts[:-1]  # containing package
    out: List[Tuple[str, int]] = []
    for node in iter_module_scope(sf.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out.append((alias.name, node.lineno))
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                up = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                base = ".".join(up + ([node.module] if node.module else []))
            if not base:
                continue
            for alias in node.names:
                out.append((f"{base}.{alias.name}", node.lineno))
    return out


class ImportGraph:
    """Module-scope import edges over a SourceTree, with resolution:
    ``pkg.sub`` that exists as a scanned module stays itself; ``pkg.sym``
    (a symbol import) falls back to ``pkg``; anything outside the tree
    collapses to its top-level name (``jax.numpy`` -> ``jax``)."""

    def __init__(self, tree: SourceTree):
        self.tree = tree
        self.edges: Dict[str, List[Tuple[str, int]]] = {}
        for sf in tree.files:
            deps: List[Tuple[str, int]] = []
            for target, lineno in module_scope_imports(sf):
                deps.append((self._resolve(target), lineno))
            self.edges[sf.module] = deps

    def _resolve(self, dotted: str) -> str:
        if dotted in self.tree.by_module:
            return dotted
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            cand = ".".join(parts[:cut])
            if cand in self.tree.by_module:
                return cand
        return parts[0]  # external: top-level distribution name

    def chain_to(self, start: str,
                 targets: Sequence[str]) -> Optional[List[str]]:
        """Shortest module chain ``[start, ..., target]`` reaching any of
        ``targets`` through module-scope imports, else None."""
        if start not in self.edges:
            return None
        parent: Dict[str, Optional[str]] = {start: None}
        queue = [start]
        while queue:
            cur = queue.pop(0)
            for dep, _ in self.edges.get(cur, ()):
                if dep in parent:
                    continue
                parent[dep] = cur
                if dep in targets:
                    chain = [dep]
                    at: Optional[str] = cur
                    while at is not None:
                        chain.append(at)
                        at = parent[at]
                    return list(reversed(chain))
                queue.append(dep)
        return None

    def first_hop_line(self, start: str, nxt: str) -> int:
        for dep, lineno in self.edges.get(start, ()):
            if dep == nxt:
                return lineno
        return 1


@register
class JaxFreeImportRule:
    """See module docstring."""

    name = "jax-free-import"

    def __init__(self, manifest: Optional[Sequence[str]] = None,
                 extra_manifest: Sequence[str] = ()):
        base = tuple(manifest) if manifest is not None else JAX_FREE_MODULES
        self.manifest = tuple(base) + tuple(extra_manifest)

    def check(self, tree: SourceTree) -> List[Finding]:
        graph = ImportGraph(tree)
        tops = {m.split(".")[0] for m in tree.by_module if m}
        findings: List[Finding] = []
        for mod in self.manifest:
            sf = tree.by_module.get(mod)
            if sf is None:
                # Only a full scan of the module's package can judge a
                # missing entry (partial/fixture scans skip silently).
                if mod.split(".")[0] in tops:
                    findings.append(Finding(
                        self.name, "<manifest>", 1,
                        f"manifest names unknown module '{mod}' "
                        f"(typo, or the file moved without updating "
                        f"analysis/imports.py)",
                    ))
                continue
            chain = graph.chain_to(mod, POISON)
            if chain is None:
                continue
            line = graph.first_hop_line(mod, chain[1]) if len(chain) > 1 \
                else 1
            findings.append(Finding(
                self.name, sf.rel, line,
                f"module '{mod}' is declared jax-free at import but its "
                f"module-scope imports reach '{chain[-1]}' via "
                + " -> ".join(chain[1:])
                + " (defer the import into the function that needs it, "
                  "or remove the module from the manifest)",
            ))
        return findings
