"""Rule ``trace-purity``: no host-impure calls inside jit-traced code.

A ``jax.jit``-traced function runs ONCE per shape signature; host-side
effects inside it (``time.*``, ``np.random.*``, ``random.*``,
``os.environ`` reads, ``print``, ``.item()``/``float()`` on traced
values) silently bake one trace-time value into the compiled program —
the class of bug that reads as "works in eager, wrong/frozen under jit"
and that cost PR 4 and PR 8 runtime drives to find (GSPMD placement
drift and the DP de-replication were both invisible until a real run).

Traced regions (heuristic, over-approximating):

- functions decorated with ``jax.jit`` / ``functools.partial(jax.jit,
  ...)``;
- the resolved argument of any ``jax.jit(...)`` call — a local function
  name, a ``lambda``, a ``functools.partial(fn, ...)``, or a
  step-factory call like ``self._train_step_body()`` (the repo's
  factory idiom: the factory's body, nested closures included, is
  scanned);
- scan bodies: the first argument of ``lax.scan(...)``;
- any def whose name ends in ``_body`` (the ``_grad_eval_body`` /
  ``*_train_step_body`` naming convention marks trace-scoped code).

Factories legitimately do host work BEFORE building their closure;
that is exactly what the ``# dtpu-lint: allow[trace-purity]`` escape is
for — the comment documents, at the line, why the impurity is outside
the trace or deliberate.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from .core import Finding, SourceTree, call_name, dotted_name, register
from .threads import function_index

_JIT_NAMES = frozenset({"jax.jit", "jit"})
_SCAN_NAMES = frozenset({"lax.scan", "jax.lax.scan"})


def _resolve_traced(node, idx) -> Iterable[ast.AST]:
    """AST regions traced for a jit/scan argument expression."""
    if isinstance(node, ast.Lambda):
        yield node
    elif isinstance(node, ast.Name):
        yield from idx.get(node.id, ())
    elif isinstance(node, ast.Attribute):
        yield from idx.get(node.attr, ())
    elif isinstance(node, ast.Call):
        dotted = call_name(node)
        if dotted in ("functools.partial", "partial") and node.args:
            yield from _resolve_traced(node.args[0], idx)
        elif dotted is not None:
            # factory idiom: jit(self._train_step_body()) — scan the
            # factory's body (closure included)
            yield from idx.get(dotted.split(".")[-1], ())


def traced_regions(sf) -> List[ast.AST]:
    idx = function_index(sf.tree)
    regions: List[ast.AST] = []
    seen: Set[int] = set()

    def add(node):
        if id(node) not in seen:
            seen.add(id(node))
            regions.append(node)

    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name.endswith("_body"):
                add(node)
            for dec in node.decorator_list:
                d = dotted_name(dec)
                if d in _JIT_NAMES:
                    add(node)
                elif isinstance(dec, ast.Call):
                    dc = call_name(dec)
                    if dc in _JIT_NAMES:
                        add(node)
                    elif dc in ("functools.partial", "partial") and dec.args \
                            and dotted_name(dec.args[0]) in _JIT_NAMES:
                        add(node)
        elif isinstance(node, ast.Call):
            dotted = call_name(node)
            if dotted in _JIT_NAMES and node.args:
                for region in _resolve_traced(node.args[0], idx):
                    add(region)
            elif dotted in _SCAN_NAMES and node.args:
                for region in _resolve_traced(node.args[0], idx):
                    add(region)
    return regions


def _param_names(region) -> Set[str]:
    if not isinstance(region, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
        return set()
    a = region.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    for extra in (a.vararg, a.kwarg):
        if extra is not None:
            names.append(extra.arg)
    return set(names)


def _impure_call(dotted: str) -> Optional[str]:
    parts = dotted.split(".")
    if parts[0] == "time" and len(parts) > 1:
        return "wall-clock read"
    if dotted.startswith(("np.random.", "numpy.random.")):
        return "host RNG"
    if parts[0] == "random" and len(parts) > 1:
        return "host RNG"
    if dotted in ("os.getenv",) or dotted.startswith("os.environ."):
        return "environment read"
    if dotted == "print":
        return "host I/O"
    if dotted in ("datetime.now", "datetime.datetime.now",
                  "datetime.utcnow", "datetime.datetime.utcnow"):
        return "wall-clock read"
    return None


@register
class TracePurityRule:
    name = "trace-purity"

    def check(self, tree: SourceTree) -> List[Finding]:
        findings: List[Finding] = []
        for sf in tree.files:
            for region in traced_regions(sf):
                params = _param_names(region)
                reported: Set[Tuple[int, str]] = set()

                def flag(node, what, why):
                    key = (node.lineno, what)
                    if key in reported:
                        return
                    reported.add(key)
                    findings.append(Finding(
                        self.name, sf.rel, node.lineno,
                        f"{why} '{what}' inside jit-traced code (the "
                        f"value is baked at trace time and frozen into "
                        f"the compiled program; hoist it to the host "
                        f"side, or escape with "
                        f"# dtpu-lint: allow[trace-purity])",
                    ))

                for node in ast.walk(region):
                    if isinstance(node, ast.Call):
                        dotted = call_name(node)
                        if dotted is not None:
                            why = _impure_call(dotted)
                            if why is not None:
                                flag(node, dotted, why)
                                continue
                        if isinstance(node.func, ast.Attribute) \
                                and node.func.attr == "item":
                            flag(node, ".item()", "host transfer")
                        elif isinstance(node.func, ast.Name) \
                                and node.func.id in ("float", "int") \
                                and len(node.args) == 1 \
                                and isinstance(node.args[0], ast.Name) \
                                and node.args[0].id in params:
                            flag(node, f"{node.func.id}(...)",
                                 "host transfer of a traced argument")
                    elif isinstance(node, ast.Attribute):
                        if dotted_name(node) == "os.environ":
                            flag(node, "os.environ", "environment read")
        return findings
