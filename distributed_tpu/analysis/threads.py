"""Rules ``thread-hygiene`` and ``writer-thread``.

``thread-hygiene`` — every ``threading.Thread(...)`` in the tree must be
``daemon=True`` and carry a ``dtpu-*`` name. This is the source-side
half of the tests/conftest.py leak-checker contract: the autouse
teardown asserts no live ``dtpu-*`` thread survives a test, which only
polices threads that ARE named — an unnamed background thread is
invisible to it. Deliberately-abandonable threads (a probe that may be
stuck in a resolver) escape with ``# dtpu-lint: allow[thread-hygiene]``
and a rationale; everything else gets a name and the leak check's
protection.

``writer-thread`` — the PR-13 deferred-barrier contract, mechanized:
background checkpoint/mirror writers (``Thread`` whose name matches
``dtpu-*writer``) must never reach a collective. A collective on a
writer thread deadlocks the gang the moment one process's writer runs
ahead of another's main thread (the reason ShardedCheckpointer defers
its commit barrier to the next main-thread save/wait). The rule walks
the writer target's static call graph — same-file function and method
resolution by name — and flags any reachable call into
``multihost_utils``, the ``lax`` collective family, or ``jnp.*``
dispatch. Findings anchor at the ``Thread(...)`` construction site (the
place that decides what runs on the writer), with the call chain in the
message.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Tuple

from .core import (
    Finding,
    SourceTree,
    call_name,
    dotted_name,
    literal_str_prefix,
    register,
)

WRITER_NAME_RE = re.compile(r"dtpu-[\w.-]*writer")

_COLLECTIVE_TERMINALS = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "axis_index",
    "process_allgather", "broadcast_one_to_all", "sync_global_devices",
    "reached_preemption_sync_point",
})


def denied_on_writer(dotted: str) -> Optional[str]:
    """Why a call is forbidden on a dtpu-*writer thread, or None."""
    parts = dotted.split(".")
    if "multihost_utils" in parts:
        return "multihost collective"
    if parts[-1] in _COLLECTIVE_TERMINALS:
        return "collective"
    if parts[0] == "jnp" or dotted.startswith("jax.numpy."):
        return "jax dispatch"
    return None


def function_index(tree: ast.Module) -> Dict[str, List[ast.AST]]:
    """Every function/method def in the file, keyed by bare name (the
    resolution unit for the same-file call-graph walk; same-name defs
    are all visited — an over-approximation that errs toward flagging)."""
    idx: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            idx.setdefault(node.name, []).append(node)
    return idx


def _thread_calls(sf) -> Iterable[ast.Call]:
    bare_ok = any(
        isinstance(n, ast.ImportFrom) and n.module == "threading"
        and any(a.name == "Thread" for a in n.names)
        for n in ast.walk(sf.tree)
    )
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = call_name(node)
        if dotted == "threading.Thread" or (bare_ok and dotted == "Thread"):
            yield node


def _kw(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _has_spread(call: ast.Call) -> bool:
    return any(kw.arg is None for kw in call.keywords)


@register
class ThreadHygieneRule:
    name = "thread-hygiene"

    def check(self, tree: SourceTree) -> List[Finding]:
        findings: List[Finding] = []
        for sf in tree.files:
            for call in _thread_calls(sf):
                if _has_spread(call):
                    continue  # **kwargs: statically opaque
                probs = []
                daemon = _kw(call, "daemon")
                if not (isinstance(daemon, ast.Constant)
                        and daemon.value is True):
                    probs.append("missing daemon=True (a non-daemon "
                                 "background thread blocks interpreter "
                                 "exit on a crash)")
                name_val = _kw(call, "name")
                prefix = literal_str_prefix(name_val) \
                    if name_val is not None else None
                if prefix is None or not prefix.startswith("dtpu-"):
                    probs.append("missing a literal name='dtpu-*' (the "
                                 "conftest leak checker only polices "
                                 "named dtpu-* threads)")
                for p in probs:
                    findings.append(Finding(
                        self.name, sf.rel, call.lineno, f"Thread(...) {p}",
                    ))
        return findings


@register
class WriterThreadRule:
    name = "writer-thread"

    def check(self, tree: SourceTree) -> List[Finding]:
        findings: List[Finding] = []
        for sf in tree.files:
            idx = function_index(sf.tree)
            for call in _thread_calls(sf):
                name_val = _kw(call, "name")
                label = literal_str_prefix(name_val) \
                    if name_val is not None else None
                if label is None or not WRITER_NAME_RE.match(label):
                    continue
                target = _kw(call, "target")
                tname = dotted_name(target) if target is not None else None
                if tname is None:
                    continue
                tname = tname.split(".")[-1]
                for dotted, chain, why in self._walk(tname, idx):
                    findings.append(Finding(
                        self.name, sf.rel, call.lineno,
                        f"writer thread '{label}' statically reaches "
                        f"{why} '{dotted}' via "
                        + " -> ".join(chain)
                        + " (collectives and device dispatch are "
                          "forbidden on dtpu-*writer threads: a writer "
                          "ahead of a peer's main thread deadlocks the "
                          "gang — defer to the next main-thread "
                          "save/wait)",
                    ))
        return findings

    def _walk(self, root: str, idx) -> List[Tuple[str, List[str], str]]:
        """Denied calls reachable from ``root`` through same-file defs:
        ``(denied dotted name, [root, ..., enclosing fn], reason)``."""
        out: List[Tuple[str, List[str], str]] = []
        seen_fn = set()
        seen_bad = set()
        stack: List[Tuple[str, Tuple[str, ...]]] = [(root, (root,))]
        while stack:
            fname, chain = stack.pop()
            if fname in seen_fn:
                continue
            seen_fn.add(fname)
            for node in idx.get(fname, ()):
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Call):
                        continue
                    dotted = call_name(sub)
                    if dotted is None:
                        continue
                    why = denied_on_writer(dotted)
                    if why is not None:
                        if dotted not in seen_bad:
                            seen_bad.add(dotted)
                            out.append((dotted, list(chain), why))
                        continue
                    tail = dotted.split(".")[-1]
                    if tail in idx and tail not in seen_fn:
                        stack.append((tail, chain + (tail,)))
        return out
