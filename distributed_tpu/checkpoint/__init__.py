from .core import (
    Checkpointer,
    artifact_decode,
    artifact_encode,
    export_hdf5,
    import_hdf5,
    load_npz,
    save_npz,
    wait_all_async,
)
from .sharded import ShardCorruptionError, ShardedCheckpointer

__all__ = [
    "Checkpointer",
    "ShardedCheckpointer",
    "ShardCorruptionError",
    "wait_all_async",
    "save_npz",
    "load_npz",
    "export_hdf5",
    "import_hdf5",
    "artifact_encode",
    "artifact_decode",
]
