"""Checkpointing, model export, and artifact transport.

Parity targets and upgrades over the reference:
- rank-0-only write of a trained model + retrieval to the operator
  (/root/reference/README.md:236-247: ``save_model_hdf5`` -> base64 -> Spark
  ``collect()``). Here: ``export_hdf5`` + ``artifact_encode/decode`` keep the
  exact same shape of workflow for launcher result channels.
- the reference explicitly cannot resume ("Workers will need to restart
  training if any fails", /root/reference/README.md:400). ``Checkpointer``
  fixes that gap: periodic step-tagged checkpoints of params/state/opt_state
  plus the step cursor, restartable mid-training.

Format: flattened path->array npz (portable, no framework pin) and HDF5 for
interchange. Writes are chief-only (process 0), matching the reference's
rank-0 gate (README.md:240); under replicated sharding every process holds
the full value so chief-only write is lossless.
"""

from __future__ import annotations

import base64
import json
import os
import re
import tempfile
import threading
import weakref
import zipfile
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

SEP = "/"

# Checkpointers with a live (or ever-started) background writer, so a
# process-wide barrier (wait_all_async) can flush every pending write —
# the PreemptionHandler's pre-exit flush without needing a reference to
# each callback's private Checkpointer.
_ASYNC_CHECKPOINTERS: "weakref.WeakSet[Checkpointer]" = weakref.WeakSet()


def wait_all_async() -> None:
    """Barrier over EVERY Checkpointer that has started a background save:
    blocks until all in-flight writes have fully landed (npz + gc + latest
    pointer). Writer errors propagate. The preemption path calls this
    before its final synchronous save so an older in-flight write can
    never land after — and shadow — the preemption checkpoint."""
    for ck in list(_ASYNC_CHECKPOINTERS):
        ck.wait()

# What a torn/garbage checkpoint file raises out of np.load/json meta decode:
# truncated zips (BadZipFile/EOFError/OSError), non-zip garbage and bad
# array headers (ValueError, which JSONDecodeError subclasses), and a
# missing required key (KeyError). Anything else is a real bug and must
# propagate.
_CORRUPT_ERRORS = (OSError, ValueError, KeyError, EOFError, zipfile.BadZipFile)


def _to_host(val) -> np.ndarray:
    """Full global value on the host. Tensor-parallel leaves whose shards
    live on other processes can't be device_get directly; they are gathered
    collectively — which is why flatten_tree must run on EVERY process of a
    gang before any chief-only gate."""
    if (
        isinstance(val, jax.Array)
        and not val.is_fully_addressable
        and not val.is_fully_replicated
    ):
        from jax.experimental import multihost_utils

        val = multihost_utils.process_allgather(val, tiled=True)
    return np.asarray(jax.device_get(val))


def iter_leaf_paths(tree, prefix=""):
    """(path, leaf) pairs: sorted dict keys, '#i' for tuple/list entries,
    SEP-joined. The single source of truth for checkpoint path naming
    (flatten_tree and the sharded format both build on it)."""
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from iter_leaf_paths(tree[k], f"{prefix}{k}{SEP}")
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from iter_leaf_paths(v, f"{prefix}#{i}{SEP}")
    elif tree is None:
        return
    else:
        yield prefix.rstrip(SEP), tree


def flatten_tree(tree, prefix="") -> Dict[str, np.ndarray]:
    return {p: _to_host(v) for p, v in iter_leaf_paths(tree, prefix)}


def unflatten_tree(flat: Dict[str, np.ndarray]):
    root: Dict[str, Any] = {}
    for path, val in flat.items():
        parts = path.split(SEP)
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = val

    def fix(node):
        if not isinstance(node, dict):
            return node
        if node and all(re.fullmatch(r"#\d+", k) for k in node):
            # Present indices in numeric order, NOT range(len): tuple
            # elements that flatten to nothing (empty dicts — e.g. optax
            # inject_hyperparams' hyperparams_states, EmptyState) leave
            # gaps. Restore grafts leaves onto a freshly-init'd structure
            # by order, so skipping the empties is exactly right.
            idxs = sorted(int(k[1:]) for k in node)
            return tuple(fix(node[f"#{i}"]) for i in idxs)
        return {k: fix(v) for k, v in node.items()}

    return fix(root)


def _is_chief() -> bool:
    return jax.process_index() == 0


def _atomic_write(path: Path, write_fn):
    tmp_fd, tmp_name = tempfile.mkstemp(dir=str(path.parent), suffix=".tmp")
    os.close(tmp_fd)
    try:
        write_fn(tmp_name)
        # fsync BEFORE the rename: os.replace is atomic in the namespace
        # but not durable — a power cut after the rename could otherwise
        # surface a present-but-empty file under the real name, which the
        # corrupt-skip scan would then have to spend a step on.
        fd = os.open(tmp_name, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp_name, path)
    finally:
        if os.path.exists(tmp_name):
            os.unlink(tmp_name)


def _data_state_of(model, step: int) -> Optional[dict]:
    """The active fit data source's iterator cursor, as JSON-able meta —
    None when there is no source, it has no ``state_dict``, or its state
    fails to serialize (a checkpoint must never die for its data cursor;
    resume then falls back to the seek path). ``step`` is the step the
    model trained to: it overrides the source's own position, which a
    prefetch producer may have staged AHEAD of the consumed stream."""
    src = getattr(model, "_fit_source", None)
    if src is None or not hasattr(src, "state_dict"):
        return None
    from ..utils import logging as _dlog

    try:
        try:
            state = src.state_dict(consumed_steps=step)
        except TypeError:  # sources with a plain state_dict() signature
            state = src.state_dict()
        json.dumps(state)  # meta is JSON; reject unserializable state now
        return state
    except Exception as e:
        _dlog.warning(
            f"Checkpointer: data source state_dict failed ({e}); the "
            "checkpoint carries no iterator state (resume will use the "
            "seek path)"
        )
        return None


def _device_snapshot(tree):
    """Donation-safe copy of a pytree for a background writer: jax leaves
    get an on-device copy (enqueued NOW, on the caller's thread, so it is
    ordered before any later dispatch that donates the original buffers),
    numpy leaves a host copy. The background thread then fetches from the
    snapshot at leisure while training keeps donating the originals."""
    import jax.numpy as jnp

    def cp(a):
        if isinstance(a, jax.Array):
            return jnp.copy(a)
        if isinstance(a, np.ndarray):
            return np.array(a, copy=True)
        return a

    return jax.tree_util.tree_map(cp, tree)


# ---------------------------------------------------------------------- npz --
def save_npz(path, tree, meta: Optional[dict] = None):
    """Chief-only atomic save of a pytree (params or {params,state,...}).

    Flattening runs on every process BEFORE the chief gate: gathering a
    tensor-parallel leaf that spans processes is a collective, so all
    processes must participate even though only the chief writes."""
    path = Path(path)
    flat = flatten_tree(tree)
    if not _is_chief():
        return path
    path.parent.mkdir(parents=True, exist_ok=True)
    if meta is not None:
        flat["__meta__"] = np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8
        ).copy()
    _atomic_write(path, lambda tmp: np.savez(open(tmp, "wb"), **flat))
    return path


def load_npz(path) -> Tuple[Any, Optional[dict]]:
    with np.load(path, allow_pickle=False) as z:
        flat = {k: z[k] for k in z.files}
    meta = None
    if "__meta__" in flat:
        meta = json.loads(bytes(flat.pop("__meta__")).decode())
    return unflatten_tree(flat), meta


# --------------------------------------------------------------------- hdf5 --
def export_hdf5(path, params, attrs: Optional[dict] = None):
    """Model weight export in HDF5 (the reference's interchange format,
    /root/reference/README.md:237). Chief-only."""
    import h5py

    path = Path(path)
    flat = flatten_tree(params)  # before the chief gate: may be collective
    if not _is_chief():
        return path
    path.parent.mkdir(parents=True, exist_ok=True)

    def write(tmp):
        with h5py.File(tmp, "w") as f:
            for key, val in flat.items():
                f.create_dataset(key, data=val)
            for k, v in (attrs or {}).items():
                f.attrs[k] = v

    _atomic_write(path, write)
    return path


def import_hdf5(path):
    import h5py

    flat = {}
    attrs = {}
    with h5py.File(path, "r") as f:
        def visit(name, obj):
            if isinstance(obj, h5py.Dataset):
                flat[name] = np.asarray(obj)

        f.visititems(visit)
        attrs = dict(f.attrs)
    return unflatten_tree(flat), attrs


# ----------------------------------------------------------------- artifact --
def artifact_encode(path) -> str:
    """File -> base64 string, for returning a trained model through a text
    result channel (the reference's Spark column trick, README.md:240-246)."""
    return base64.b64encode(Path(path).read_bytes()).decode()


def artifact_decode(b64: str, out_path):
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    out_path.write_bytes(base64.b64decode(b64))
    return out_path


# ------------------------------------------------------------- checkpointer --
class Checkpointer:
    """Step-tagged training checkpoints with resume.

    Layout: ``dir/ckpt-<step>.npz`` holding params/state/opt_state and a meta
    record (step, seed), plus a ``latest`` pointer file (JSON
    ``{"step": N}``) written atomically (tmp + ``os.replace``) after every
    completed save — a crash mid-save can leave a torn ``ckpt-N.npz.tmp``
    at worst, never a truncated pointer or a half-written checkpoint under
    the real name. ``restore_into(model)`` reloads the latest (or a given
    step) and re-places arrays under the model's strategy, so a resumed
    run continues bit-identically on any mesh with the same replica count.
    That re-placement is what makes checkpoints STRATEGY-PORTABLE: the
    optimizer state grafts onto a template from the live strategy's
    ``init_opt_state`` — a run saved under replicated ``DataParallel``
    resumes under ``ZeroDataParallel``/``FSDP`` with the moments coming
    back data-sharded (and vice versa), and ``inject_hyperparams``
    wrappers round-trip their live values (a runtime-set learning rate
    survives the resume; tests/test_zero.py pins both).
    Checkpoints are also PRECISION-PORTABLE: a mixed-precision policy
    (``compile(precision=...)``) keeps params and optimizer state as f32
    master weights — the compute-dtype cast lives inside the jitted step,
    never in ``model.params`` — so what lands on disk is f32 under every
    mixed preset, and saving under ``mixed_bfloat16`` then restoring
    under ``float32`` (or vice versa) is byte-exact. The one structural
    caveat: ``mixed_float16``'s dynamic loss scale is real optimizer
    state (``optim.LossScaleState``, outermost), so its live scale
    survives same-policy round-trips, but crossing between a
    loss-scaling and a non-scaling policy changes the optimizer-state
    leaf count and raises the format error below (keep the weights via
    ``save_weights``/``load_weights`` in that case;
    tests/test_precision.py pins the round-trips).
    When the newest file is corrupt anyway (torn by the filesystem, or a
    fault-injection test), auto-restore skips it and falls back to the
    previous step instead of failing the relaunch.

    Checkpoints also carry ITERATOR STATE: when the model is mid-``fit``
    over a data source exposing ``state_dict()`` (``data.Pipeline``,
    including record-backed streaming pipelines), each save records the
    source's cursor — aligned to the step the model actually TRAINED,
    not the (possibly prefetch-staged-ahead) source position — in the
    checkpoint meta, and a resuming ``fit`` restores it via the source's
    ``load_state()`` (O(1), no replay; identity fields like seed and
    batch_size are validated loudly). The state is PORTABLE across
    worker counts and shardings: it records the GLOBAL stream cursor,
    never the decode-worker count or the per-host shard, so a resumed
    run may use different ``decode_workers`` or a resized gang
    (``Pipeline.reshard("auto")``) and still consume the exact stream
    the interrupted run would have (docs/API.md "Data").

    ``async_save=True`` moves the expensive half of every save — the
    device->host fetch, npz serialization, fsync, gc, and the atomic
    ``latest`` pointer update — onto a background writer thread, so the
    train loop resumes after only a cheap on-device snapshot
    (donation-safe copies, see ``_device_snapshot``). Ordering contract:
    a new ``save`` first ``wait()``s out any in-flight write (a newer
    step can never race an older one for the pointer), and ``wait()`` is
    the explicit barrier — ``ModelCheckpoint`` calls it at train end,
    the preemption path flushes every live writer
    (``wait_all_async``) before exiting 75. Writer errors surface at the
    next ``save``/``wait``, never silently. Multi-process gangs fall
    back to synchronous saves: gathering non-addressable leaves is a
    collective, which must not run on a background thread concurrently
    with training collectives.
    """

    LATEST_NAME = "latest"

    def __init__(self, directory, keep: int = 3, async_save: bool = False):
        self.directory = Path(directory)
        self.keep = int(keep)
        self.async_save = bool(async_save)
        self._writer: Optional[threading.Thread] = None
        self._writer_error: Optional[BaseException] = None
        self._writer_lock = threading.Lock()

    def _path(self, step: int) -> Path:
        return self.directory / f"ckpt-{step}.npz"

    def all_steps(self):
        if not self.directory.is_dir():
            return []
        steps = []
        for p in self.directory.glob("ckpt-*.npz"):
            m = re.fullmatch(r"ckpt-(\d+)\.npz", p.name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    # -------------------------------------------------------- latest pointer
    def _write_latest_pointer(self, step: int):
        payload = json.dumps({"step": int(step)})
        self.directory.mkdir(parents=True, exist_ok=True)
        _atomic_write(
            self.directory / self.LATEST_NAME,
            lambda tmp: Path(tmp).write_text(payload),
        )

    def _read_latest_pointer(self) -> Optional[int]:
        try:
            rec = json.loads((self.directory / self.LATEST_NAME).read_text())
            return int(rec["step"])
        except (OSError, ValueError, KeyError, TypeError):
            return None  # absent/garbage pointer: the glob scan decides

    def latest_step(self) -> Optional[int]:
        """Newest step on disk. The pointer is the fast path; the glob scan
        both backstops a missing/corrupt pointer and wins when it is STALE
        (a crash between the npz save and the pointer write leaves the
        pointer one step behind a complete, atomically-renamed file)."""
        steps = self.all_steps()
        ptr = self._read_latest_pointer()
        if ptr is not None and self._path(ptr).exists():
            return max(ptr, steps[-1]) if steps else ptr
        return steps[-1] if steps else None

    # ------------------------------------------------------------- validity
    def is_valid(self, step: int) -> bool:
        """Cheap structural check: the file opens as a zip and its member
        table reads. Does not decompress arrays (full validation is the
        load itself, which restore retries downward on failure)."""
        try:
            with np.load(self._path(step), allow_pickle=False) as z:
                z.files  # noqa: B018 — forces the zip directory read
            return True
        except _CORRUPT_ERRORS:
            return False

    def latest_valid_step(self) -> Optional[int]:
        for step in reversed(self.all_steps()):
            if self.is_valid(step):
                return step
        return None

    def _load_latest_valid(self) -> Tuple[int, Any, dict]:
        """(step, tree, meta) of the newest LOADABLE checkpoint: corrupt
        files are skipped (warning + 'corrupt_checkpoint_skipped' event)
        and the scan falls back to the previous step — a torn latest file
        must cost one checkpoint interval of progress, not the run."""
        from ..utils import event_schema as evs
        from ..utils import events as events_lib
        from ..utils import logging as dlog

        steps = self.all_steps()
        for step in reversed(steps):
            try:
                tree, meta = load_npz(self._path(step))
                return step, tree, meta
            except _CORRUPT_ERRORS as e:
                dlog.warning(
                    f"Checkpointer: skipping corrupt checkpoint "
                    f"{self._path(step).name} ({type(e).__name__}: {e}); "
                    "falling back to the previous step"
                )
                events_lib.emit(
                    evs.CORRUPT_CHECKPOINT_SKIPPED, step=int(step),
                    path=str(self._path(step)), error=str(e),
                )
        raise FileNotFoundError(
            f"No loadable checkpoints in {self.directory} "
            f"({len(steps)} candidate file(s), all corrupt)"
            if steps else f"No checkpoints in {self.directory}"
        )

    def wait(self) -> None:
        """Barrier: block until the in-flight background save (if any) has
        fully landed — npz on disk (fsynced), old steps gc'd, ``latest``
        pointer updated. Re-raises the writer's exception if it failed.
        No-op for synchronous checkpointers, so callers can always call
        it unconditionally at fit end / before exit."""
        with self._writer_lock:
            writer, self._writer = self._writer, None
        if writer is not None:
            writer.join()
        err, self._writer_error = self._writer_error, None
        if err is not None:
            raise err

    def save(self, model, step: Optional[int] = None) -> Path:
        step = model.step if step is None else step
        tree = {
            "params": model.params,
            "state": model.state if model.state else {},
            "opt_state": model.opt_state,
        }
        meta = {
            "step": int(step),
            "seed": int(model._seed),
            "input_shape": list(model.input_shape or ()),
        }
        dstate = _data_state_of(model, int(step))
        if dstate is not None:
            meta["data_state"] = dstate
        # Serialize the step family: an older in-flight write must land
        # (and any error surface) before a newer save may start.
        self.wait()
        if self.async_save and jax.process_count() == 1:
            return self._save_async(tree, meta, int(step))
        path = save_npz(self._path(step), tree, meta)
        if _is_chief():
            self._gc()
            self._write_latest_pointer(step)
        return path

    def _save_async(self, tree, meta: dict, step: int) -> Path:
        """Background half of an async save: snapshot on the caller's
        thread (cheap, device-side, ordered before future donations),
        then fetch + serialize + fsync + gc + pointer on a writer."""
        snap = _device_snapshot(tree)
        path = self._path(step)

        def write():
            try:
                save_npz(path, snap, meta)
                self._gc()
                self._write_latest_pointer(step)
            except BaseException as e:  # surfaced at the next save/wait
                self._writer_error = e

        # save_npz -> flatten_tree -> _to_host CAN reach a multihost
        # allgather, but never from here: _save_async is only entered
        # under jax.process_count() == 1 (multi-process saves stay sync,
        # see save()), so the snapshot is always fully addressable.
        # dtpu-lint: allow[writer-thread]
        writer = threading.Thread(
            target=write, name="dtpu-ckpt-writer", daemon=True
        )
        with self._writer_lock:
            self._writer = writer
        _ASYNC_CHECKPOINTERS.add(self)
        writer.start()
        return path

    def _gc(self):
        steps = self.all_steps()
        for s in steps[: max(0, len(steps) - self.keep)]:
            try:
                self._path(s).unlink()
            except OSError:
                pass

    def restore_into(self, model, step: Optional[int] = None) -> int:
        """Restore params/state/opt_state + step cursor.

        Multi-host: saves are chief-only, so on a gang whose checkpoint
        directory is NOT a shared filesystem only process 0 has the files.
        The chief therefore decides which step to restore and broadcasts the
        restored values to every process — all processes always make the
        same decision and end with identical state, keeping the SPMD gang's
        collective schedules in lockstep."""
        if jax.process_count() > 1:
            return self._restore_multihost(model, step)
        if step is None:
            # Auto-restore scans down past corrupt files (a crash mid-save,
            # torn storage) to the newest loadable step; an EXPLICIT step
            # must load exactly that step or raise — silent substitution
            # would hide the corruption from a caller who named the step.
            step, tree, meta = self._load_latest_valid()
        else:
            tree, meta = load_npz(self._path(step))
        if not model.built:
            model.build(meta["input_shape"], seed=meta.get("seed", 0))
        model.params = model.strategy.put_params(
            tree["params"], hints=model._param_hints
        )
        model.state = model.strategy.put_params(tree.get("state") or {})
        if model.compiled and tree.get("opt_state") is not None:
            # npz round-trips optax's NamedTuple state as plain tuples/dicts;
            # graft the saved leaves back onto a freshly-init'd structure.
            # Placement via the strategy's (eager) init keeps TP shardings
            # consistent with the already-placed params.
            template = model.strategy.init_opt_state(model.tx, model.params)
            leaves = jax.tree_util.tree_leaves(tree["opt_state"])
            treedef = jax.tree_util.tree_structure(template)
            if len(leaves) != treedef.num_leaves:
                raise ValueError(
                    f"Checkpoint optimizer state has {len(leaves)} leaves "
                    f"but this model's optimizer expects "
                    f"{treedef.num_leaves}. The optimizer-state FORMAT "
                    "changed (named optimizers carry injected "
                    "hyperparameters since round 4; gradient accumulation "
                    "adds a MultiSteps accumulator) — or compile() used a "
                    "different optimizer than the checkpointing run. To "
                    "keep the weights, load params/state only "
                    "(Model.load_weights on an exported file) and let the "
                    "optimizer state reinitialize."
                )
            shardings = jax.tree_util.tree_map(lambda a: a.sharding, template)
            model.opt_state = jax.device_put(
                jax.tree_util.tree_unflatten(treedef, leaves), shardings
            )
        model.step = int(meta["step"])
        model._seed = int(meta.get("seed", model._seed))
        # Iterator-state handoff: fit() reads this on resume and restores
        # the source via load_state() instead of the bare seek, getting
        # loud validation of the stream identity for free.
        model._restored_data_state = meta.get("data_state")
        return model.step

    def _restore_multihost(self, model, step: Optional[int]) -> int:
        from jax.experimental import multihost_utils

        if not (model.built and model.compiled):
            raise RuntimeError(
                "Multi-host restore needs a built+compiled model: the "
                "non-chief processes take array shapes from the live model "
                "(fit() builds before callbacks run, so ModelCheckpoint"
                "(restore=True) satisfies this automatically)"
            )
        chief = jax.process_index() == 0
        opt_template = model.tx.init(model.params)
        n_p = len(jax.tree_util.tree_leaves(model.params))
        n_s = len(jax.tree_util.tree_leaves(model.state or {}))
        n_o = len(jax.tree_util.tree_leaves(opt_template))

        # Header broadcast first so every process agrees on BOTH the step
        # and the value-broadcast *structure* before any array collective —
        # a structure mismatch across processes would hang the gang.
        tree = None
        local = step
        if chief:
            if step is None:
                # Same corrupt-skip scan as the single-host path, run on
                # the chief BEFORE the header broadcast so every process
                # agrees on the (possibly fallen-back) step.
                try:
                    local, tree, meta = self._load_latest_valid()
                except FileNotFoundError:
                    local = None
            else:
                tree, meta = load_npz(self._path(step))
        if chief and local is not None:
            ck_p = len(jax.tree_util.tree_leaves(tree["params"]))
            ck_s = len(jax.tree_util.tree_leaves(tree.get("state") or {}))
            ck_o = len(jax.tree_util.tree_leaves(tree.get("opt_state")))
            header = np.array(
                [local, int(meta.get("seed", model._seed)), ck_p, ck_s, ck_o],
                np.int64,
            )
        else:
            tree = None
            header = np.array([-1, 0, 0, 0, 0], np.int64)
        header = multihost_utils.broadcast_one_to_all(header)
        agreed, seed, ck_p, ck_s, ck_o = (int(v) for v in header)
        if agreed < 0:
            raise FileNotFoundError(f"No checkpoints in {self.directory}")
        if ck_p != n_p:
            raise RuntimeError(
                f"Checkpoint step {agreed} has {ck_p} param tensors but the "
                f"model has {n_p} — wrong model for this checkpoint"
            )
        if ck_s not in (0, n_s):
            raise RuntimeError(
                f"Checkpoint step {agreed} has {ck_s} state tensors but the "
                f"model has {n_s}"
            )
        # ck_o == 0 (saved uncompiled) keeps the fresh optimizer init, like
        # the single-host path; any other mismatch is a different optimizer.
        if ck_o not in (0, n_o):
            raise RuntimeError(
                f"Checkpoint step {agreed} has {ck_o} optimizer tensors but "
                f"the model's optimizer has {n_o}"
            )

        def zeros_of(tree_):
            return [
                np.zeros(l.shape, l.dtype)
                for l in jax.tree_util.tree_leaves(tree_)
            ]

        if chief:
            p_leaves = [
                np.asarray(l)
                for l in jax.tree_util.tree_leaves(tree["params"])
            ]
            s_leaves = (
                [np.asarray(l)
                 for l in jax.tree_util.tree_leaves(tree.get("state") or {})]
                if ck_s else []
            )
            o_leaves = (
                [np.asarray(l)
                 for l in jax.tree_util.tree_leaves(tree.get("opt_state"))]
                if ck_o else []
            )
        else:
            p_leaves = zeros_of(model.params)
            s_leaves = zeros_of(model.state or {}) if ck_s else []
            o_leaves = zeros_of(opt_template) if ck_o else []
        p_leaves, s_leaves, o_leaves = multihost_utils.broadcast_one_to_all(
            (p_leaves, s_leaves, o_leaves)
        )

        def graft(template, leaves):
            treedef = jax.tree_util.tree_structure(template)
            return jax.tree_util.tree_unflatten(treedef, list(leaves))

        model.params = model.strategy.put_params(
            graft(model.params, p_leaves),
            hints=model._param_hints,
        )
        if ck_s:
            model.state = model.strategy.put_params(
                graft(model.state, s_leaves)
            )
        if ck_o:
            # Same template-sharding placement as the single-host path, so a
            # TP gang's optimizer state comes back sharded, not replicated.
            placed_template = model.strategy.init_opt_state(
                model.tx, model.params
            )
            shardings = jax.tree_util.tree_map(
                lambda a: a.sharding, placed_template
            )
            model.opt_state = jax.device_put(
                graft(opt_template, o_leaves), shardings
            )
        model.step = agreed
        model._seed = seed
        # Meta lives only on the chief here; no process restores iterator
        # state (fit's seek path realigns the stream from the agreed step,
        # which is exact for (seed, step)-deterministic sources).
        model._restored_data_state = None
        return model.step
