"""Sharded (per-process) checkpointing for models larger than one host.

The npz ``Checkpointer`` gathers every leaf to full size on the host
(fine at the reference's 347k-param scale, /root/reference/README.md:236-247,
wrong for the FSDP-scale models this framework trains): per-host RAM is
O(total params) and one process writes everything. This module is the
scale-out design:

- **Save**: every process writes exactly the shard blocks it owns (its
  addressable shards with ``replica_id == 0``, so each unique block of the
  global array is written once cluster-wide) into its own
  ``proc-<i>.npz``. No host ever materializes a full leaf.
- **Commit**: ``manifest.json`` is written by the chief *after* a cross-host
  barrier, so a checkpoint directory without a manifest is an aborted save
  and is ignored by ``all_steps()``.
- **Restore**: arrays are rebuilt with ``jax.make_array_from_callback``
  under the *current* model's shardings; the callback reads only the saved
  blocks overlapping each requested shard. Because blocks carry explicit
  start offsets, the restoring mesh may have a different shape or axis
  layout than the saving one (resharding happens block-by-block on read).
  This covers the STRATEGY as well as the mesh: optimizer state saved
  from a ZeRO-1/FSDP run (data-sharded moments next to replicated
  ``inject_hyperparams`` scalars) restores into whatever the live
  strategy's ``init_opt_state`` template dictates — ZeRO-1 -> FSDP, FSDP
  -> replicated, any direction (tests/test_zero.py).

Restore assumes the checkpoint directory is visible to every process
(shared filesystem / object store) — the standard deployment for sharded
formats; the single-writer npz/HDF5 paths remain for non-shared setups and
interchange.

Layout::

    dir/ckpt-<step>/
        manifest.json   # step, seed, input_shape, leaf shapes/dtypes, nprocs
        proc-0.npz      # this process's blocks: "<leaf-path>@<starts>" -> data
        proc-1.npz
        ...
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from .core import (
    _atomic_write,
    _data_state_of,
    _is_chief,
    iter_leaf_paths as _iter_leaf_paths,
)

__all__ = ["ShardedCheckpointer"]


def _starts_of(index, shape) -> Tuple[int, ...]:
    """Concrete start offsets of a shard's index (slices may have None)."""
    starts = []
    for sl, _dim in zip(index, shape):
        starts.append(0 if sl.start is None else int(sl.start))
    return tuple(starts)


def _block_key(path: str, starts: Tuple[int, ...], shape: Tuple[int, ...]) -> str:
    # Start offsets AND block shape live in the key so restore can decide
    # overlap without reading any data.
    return (
        f"{path}@{','.join(map(str, starts))}@{','.join(map(str, shape))}"
    )


_KEY_RE = re.compile(r"^(?P<path>.*)@(?P<starts>[\d,]*)@(?P<shape>[\d,]*)$")


def _parse_key(key: str) -> Tuple[str, Tuple[int, ...], Tuple[int, ...]]:
    m = _KEY_RE.match(key)
    if not m:
        raise ValueError(f"malformed shard block key: {key!r}")

    def ints(s):
        return tuple(int(v) for v in s.split(",")) if s else ()

    return m.group("path"), ints(m.group("starts")), ints(m.group("shape"))


class _BlockIndex:
    """All saved blocks of one checkpoint: (leaf path) -> [(starts, file,
    key)], with lazily-opened npz handles so restore reads only the blocks
    it needs."""

    def __init__(self, step_dir: Path, nprocs: int):
        self._files = [step_dir / f"proc-{i}.npz" for i in range(nprocs)]
        self._handles: Dict[int, Any] = {}
        self.blocks: Dict[str, list] = {}
        for fi, f in enumerate(self._files):
            if not f.exists():
                raise FileNotFoundError(
                    f"checkpoint shard file missing: {f} (manifest promises "
                    f"{nprocs} processes — is the directory shared?)"
                )
            with np.load(f, allow_pickle=False) as z:
                names = list(z.files)
            for key in names:
                path, starts, shape = _parse_key(key)
                self.blocks.setdefault(path, []).append(
                    (starts, shape, fi, key)
                )

    def _handle(self, fi: int):
        h = self._handles.get(fi)
        if h is None:
            h = np.load(self._files[fi], allow_pickle=False)
            self._handles[fi] = h
        return h

    def read(self, fi: int, key: str) -> np.ndarray:
        return self._handle(fi)[key]

    def close(self):
        for h in self._handles.values():
            h.close()
        self._handles.clear()


class ShardedCheckpointer:
    """Per-process sharded checkpoints with mesh-shape-independent restore.

    Drop-in sibling of ``Checkpointer`` (same ``save(model)`` /
    ``restore_into(model)`` / ``all_steps`` surface), but save cost and
    host memory are O(addressable shards), not O(total params).
    """

    def __init__(self, directory, keep: int = 3):
        self.directory = Path(directory)
        self.keep = int(keep)
        # Diagnostics for tests/ops: the largest single host block touched
        # by the most recent save/restore (must stay << full leaf size for
        # sharded leaves — the whole point of the format).
        self.last_max_block_bytes = 0

    def wait(self) -> None:
        """No-op barrier: sharded saves are synchronous (every process
        writes its own shard blocks inline; the cross-host commit barrier
        makes a background writer collective-unsafe). Present so generic
        callers (ModelCheckpoint train-end, the preemption flush) can call
        ``wait()`` on either checkpointer flavor."""

    # ------------------------------------------------------------- layout --
    def _step_dir(self, step: int) -> Path:
        return self.directory / f"ckpt-{step}"

    def all_steps(self):
        if not self.directory.is_dir():
            return []
        steps = []
        for p in self.directory.glob("ckpt-*"):
            m = re.fullmatch(r"ckpt-(\d+)", p.name)
            # manifest.json is the commit marker: a dir without it is an
            # aborted save.
            if m and (p / "manifest.json").exists():
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # --------------------------------------------------------------- save --
    def save(self, model, step: Optional[int] = None) -> Path:
        step = model.step if step is None else step
        tree = {
            "params": model.params,
            "state": model.state if model.state else {},
            "opt_state": model.opt_state,
        }
        step_dir = self._step_dir(int(step))
        step_dir.mkdir(parents=True, exist_ok=True)

        proc = jax.process_index()
        blocks: Dict[str, np.ndarray] = {}
        leaves_meta: Dict[str, dict] = {}
        max_block = 0
        for path, leaf in _iter_leaf_paths(tree):
            if isinstance(leaf, jax.Array):
                shape, dtype = tuple(leaf.shape), np.dtype(leaf.dtype)
                for shard in leaf.addressable_shards:
                    if shard.replica_id != 0:
                        continue  # an identical copy is written elsewhere
                    data = np.asarray(shard.data)
                    max_block = max(max_block, data.nbytes)
                    starts = _starts_of(shard.index, shape)
                    blocks[_block_key(path, starts, data.shape)] = data
            else:
                # Host-side leaf (plain numpy/python scalar): replicated by
                # construction, chief writes it as one full block.
                data = np.asarray(leaf)
                shape, dtype = tuple(data.shape), data.dtype
                if proc == 0:
                    max_block = max(max_block, data.nbytes)
                    blocks[_block_key(path, (0,) * data.ndim, data.shape)] = data
            leaves_meta[path] = {
                "shape": list(shape),
                "dtype": dtype.name,
            }
        self.last_max_block_bytes = max_block

        _atomic_write(
            step_dir / f"proc-{proc}.npz",
            lambda tmp: np.savez(open(tmp, "wb"), **blocks),
        )

        if jax.process_count() > 1:
            # Every process must finish writing before the chief commits the
            # manifest — otherwise a reader could see a "complete" checkpoint
            # with missing shard files.
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(f"sharded_ckpt_save_{step}")

        if _is_chief():
            manifest = {
                "step": int(step),
                "seed": int(model._seed),
                "input_shape": list(model.input_shape or ()),
                "nprocs": jax.process_count(),
                "leaves": leaves_meta,
            }
            # Iterator cursor of the active fit source (data.Pipeline
            # state_dict), aligned to the trained step — the manifest is
            # read by EVERY process at restore (shared directory), so
            # unlike Checkpointer's chief-only meta it resumes streaming
            # input on whole gangs, including resized (elastic) ones.
            dstate = _data_state_of(model, int(step))
            if dstate is not None:
                manifest["data_state"] = dstate
            _atomic_write(
                step_dir / "manifest.json",
                lambda tmp: Path(tmp).write_text(json.dumps(manifest)),
            )
            self._gc()
        return step_dir

    def _gc(self):
        import shutil

        steps = self.all_steps()
        for s in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    # ------------------------------------------------------------ restore --
    def restore_into(self, model, step: Optional[int] = None) -> int:
        """Restore under the model's *current* strategy/mesh.

        Unlike ``Checkpointer._restore_multihost`` there is no broadcast:
        every process reads the (shared) directory itself and builds only
        its addressable shards. Host memory is therefore O(the target
        sharding's addressable shard sizes) — for a sharded target (FSDP/
        TP) no host ever assembles a full leaf; restoring into a
        *replicated* target necessarily assembles full leaves per host,
        exactly matching what that target keeps in device memory anyway.
        """
        if step is None:
            step = self.latest_step()
            if jax.process_count() > 1:
                # Cross-process agreement: the chief's view of the directory
                # decides (filesystem visibility can lag on some hosts; a
                # per-process latest_step() could silently desynchronize
                # the gang onto different steps).
                from jax.experimental import multihost_utils

                chosen = np.array(
                    [-1 if step is None else int(step)], np.int64
                )
                step = int(multihost_utils.broadcast_one_to_all(chosen)[0])
                if step < 0:
                    step = None
        if step is None:
            raise FileNotFoundError(f"No sharded checkpoints in {self.directory}")
        step_dir = self._step_dir(int(step))
        manifest = json.loads((step_dir / "manifest.json").read_text())

        if not model.built:
            model.build(manifest["input_shape"], seed=manifest.get("seed", 0))

        index = _BlockIndex(step_dir, int(manifest["nprocs"]))
        leaves_meta = manifest["leaves"]
        max_block = 0
        try:
            # Templates define structure AND target shardings. opt_state
            # uses the strategy's eager init so restored optimizer state
            # keeps the same placement as a fresh compile.
            templates = {
                "params": model.params,
                "state": model.state if model.state else {},
            }
            has_opt = any(
                p.startswith("opt_state") for p in leaves_meta
            )
            if model.compiled and has_opt:
                templates["opt_state"] = model.strategy.init_opt_state(
                    model.tx, model.params
                )
            # Saved-before-compile checkpoints have no opt leaves: keep the
            # model's fresh optimizer init (same contract as Checkpointer).

            def rebuild(path, template_leaf):
                meta = leaves_meta.get(path)
                if meta is None:
                    raise KeyError(
                        f"checkpoint step {step} has no leaf {path!r} — "
                        "wrong model or optimizer for this checkpoint"
                    )
                shape = tuple(meta["shape"])
                dtype = np.dtype(meta["dtype"])
                t_shape = tuple(np.shape(template_leaf))
                if t_shape != shape:
                    raise ValueError(
                        f"checkpoint leaf {path!r} has global shape {shape} "
                        f"but the model expects {t_shape}"
                        " — wrong model for this checkpoint"
                    )
                saved = index.blocks.get(path, [])
                if not saved:
                    raise KeyError(
                        f"no saved blocks for leaf {path!r} in step {step}"
                    )
                cache: Dict[Tuple[int, str], np.ndarray] = {}

                def read_block(fi, key):
                    got = cache.get((fi, key))
                    if got is None:
                        got = index.read(fi, key)
                        cache[(fi, key)] = got
                    return got

                def cb(req_index):
                    nonlocal max_block
                    req = [
                        (0 if sl.start is None else int(sl.start),
                         dim if sl.stop is None else int(sl.stop))
                        for sl, dim in zip(req_index, shape)
                    ]
                    out = np.empty(
                        tuple(hi - lo for lo, hi in req), dtype
                    )
                    filled = 0
                    for starts, bshape, fi, key in saved:
                        # Overlap of [bstart, bstop) with [lo, hi) per dim —
                        # decided from the key alone; only overlapping
                        # blocks are read from disk.
                        dst = []
                        ok = True
                        for d, (lo, hi) in enumerate(req):
                            bstart = starts[d] if d < len(starts) else 0
                            bstop = bstart + bshape[d]
                            s, e = max(bstart, lo), min(bstop, hi)
                            if s >= e:
                                ok = False
                                break
                            dst.append((s - lo, e - lo, s - bstart, e - bstart))
                        if not ok:
                            continue
                        block = read_block(fi, key)
                        max_block = max(max_block, block.nbytes)
                        out_sel = tuple(slice(a, b) for a, b, _, _ in dst)
                        blk_sel = tuple(slice(c, d) for _, _, c, d in dst)
                        out[out_sel] = block[blk_sel]
                        filled += int(np.prod(out[out_sel].shape))
                    if filled < int(np.prod(out.shape)):
                        raise ValueError(
                            f"saved blocks for {path!r} do not cover the "
                            f"requested shard {req} (filled {filled} of "
                            f"{int(np.prod(out.shape))} elements)"
                        )
                    return out

                if isinstance(template_leaf, jax.Array):
                    return jax.make_array_from_callback(
                        shape, template_leaf.sharding, cb
                    )
                full = cb(tuple(slice(0, d) for d in shape))
                return np.asarray(full, dtype)

            restored = {}
            for section, template in templates.items():
                paths, leaves = [], []
                for path, leaf in _iter_leaf_paths({section: template}):
                    paths.append(path)
                    leaves.append(leaf)
                new_leaves = [rebuild(p, l) for p, l in zip(paths, leaves)]
                treedef = jax.tree_util.tree_structure(template)
                restored[section] = jax.tree_util.tree_unflatten(
                    treedef, new_leaves
                )
        finally:
            index.close()
        self.last_max_block_bytes = max_block

        model.params = restored["params"]
        if restored.get("state") is not None and model.state:
            model.state = restored["state"]
        if model.compiled and "opt_state" in restored:
            model.opt_state = restored["opt_state"]
        model.step = int(manifest["step"])
        model._seed = int(manifest.get("seed", model._seed))
        # fit() restores the data source from this via load_state() (the
        # state records the GLOBAL stream cursor, so it composes with
        # reshard("auto") after an elastic resize).
        model._restored_data_state = manifest.get("data_state")
        return model.step
