"""Sharded (per-process) checkpointing for models larger than one host.

The npz ``Checkpointer`` gathers every leaf to full size on the host
(fine at the reference's 347k-param scale, /root/reference/README.md:236-247,
wrong for the FSDP-scale models this framework trains): per-host RAM is
O(total params) and one process writes everything. This module is the
scale-out design:

- **Save**: every process writes exactly the shard blocks it owns (its
  addressable shards with ``replica_id == 0``, so each unique block of the
  global array is written once cluster-wide) into its own
  ``proc-<i>.npz``. No host ever materializes a full leaf. Every block
  carries a CRC32 (the ``data/records.py`` corruption-is-loud idiom), so a
  torn or bit-flipped block is caught at read time and named precisely,
  never deserialized into garbage optimizer state.
- **Commit**: ``manifest.json`` is written by the chief *after* a cross-host
  barrier, so a checkpoint directory without a manifest is an aborted save
  and is ignored by ``all_steps()``. ``async_save=True`` moves the
  fetch+serialize half of the save onto a background writer
  ("dtpu-shard-writer") and DEFERS the barrier+commit to the next
  main-thread touchpoint (the following ``save()`` or an explicit
  ``wait()``), where collectives are safe — the cross-host barrier never
  runs concurrently with training collectives.
- **Restore**: arrays are rebuilt with ``jax.make_array_from_callback``
  under the *current* model's shardings; the callback reads only the saved
  blocks overlapping each requested shard. Because blocks carry explicit
  start offsets, the restoring mesh may have a different shape or axis
  layout than the saving one (resharding happens block-by-block on read).
  This covers the STRATEGY as well as the mesh: optimizer state saved
  from a ZeRO-1/FSDP run (data-sharded moments next to replicated
  ``inject_hyperparams`` scalars) restores into whatever the live
  strategy's ``init_opt_state`` template dictates — ZeRO-1 -> FSDP, FSDP
  -> replicated, any direction (tests/test_zero.py). A corrupt block in
  the newest step raises :class:`ShardCorruptionError` (block-addressed);
  auto-restore (``step=None``) skips that step and falls back to the
  previous retained one, while an explicitly requested step never
  silently substitutes.

The block machinery (`extract_blocks` / `restore_from_index` / the
overlap-reassembly reader) is deliberately reusable: the diskless buddy
redundancy tier (``resilience/redundancy.py``) encodes its in-memory
mirrors in exactly this layout, so a mirror restores through the same
code path a disk checkpoint does — only the medium differs. ``read_stats``
counts every block this module reads FROM DISK, which is how the recovery
tests assert the buddy path's zero-disk-reads claim.

Restore assumes the checkpoint directory is visible to every process
(shared filesystem / object store) — the standard deployment for sharded
formats; the single-writer npz/HDF5 paths remain for non-shared setups and
interchange.

Layout::

    dir/ckpt-<step>/
        manifest.json   # step, seed, input_shape, leaf shapes/dtypes, nprocs
        proc-0.npz      # this process's blocks: "<leaf-path>@<starts>" -> data
        proc-1.npz      # (+ "__crc__": JSON {block key -> crc32})
        ...
"""

from __future__ import annotations

import json
import re
import threading
import zlib
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from .core import (
    _ASYNC_CHECKPOINTERS,
    _atomic_write,
    _data_state_of,
    _device_snapshot,
    _is_chief,
    iter_leaf_paths as _iter_leaf_paths,
)

__all__ = ["ShardedCheckpointer", "ShardCorruptionError", "read_stats"]

# Disk-read accounting for the recovery tiers: every block read from a
# proc-*.npz lands here. The buddy-redundancy tests and `bench.py
# recovery` snapshot these counters around a restore to PROVE a
# buddy-tier recovery touched zero disk blocks (docs/RESILIENCE.md
# "Recovery tiers").
read_stats = {"block_reads": 0, "block_bytes": 0}

CRC_KEY = "__crc__"


class ShardCorruptionError(RuntimeError):
    """A sharded-checkpoint block failed validation (CRC mismatch, torn
    file, garbage where an npz should be). Carries the offending file and
    block key so the error names exactly what is damaged instead of
    surfacing as a generic deserialization failure deep in restore."""

    def __init__(self, message: str, *, path=None, key: Optional[str] = None):
        super().__init__(message)
        self.path = str(path) if path is not None else None
        self.key = key


def _starts_of(index, shape) -> Tuple[int, ...]:
    """Concrete start offsets of a shard's index (slices may have None)."""
    starts = []
    for sl, _dim in zip(index, shape):
        starts.append(0 if sl.start is None else int(sl.start))
    return tuple(starts)


def _block_key(path: str, starts: Tuple[int, ...], shape: Tuple[int, ...]) -> str:
    # Start offsets AND block shape live in the key so restore can decide
    # overlap without reading any data.
    return (
        f"{path}@{','.join(map(str, starts))}@{','.join(map(str, shape))}"
    )


_KEY_RE = re.compile(r"^(?P<path>.*)@(?P<starts>[\d,]*)@(?P<shape>[\d,]*)$")


def _parse_key(key: str) -> Tuple[str, Tuple[int, ...], Tuple[int, ...]]:
    m = _KEY_RE.match(key)
    if not m:
        raise ValueError(f"malformed shard block key: {key!r}")

    def ints(s):
        return tuple(int(v) for v in s.split(",")) if s else ()

    return m.group("path"), ints(m.group("starts")), ints(m.group("shape"))


def block_crc(data: np.ndarray) -> int:
    """CRC32 of a block's raw bytes — the same integrity idiom as
    ``data/records.py`` record framing, applied per checkpoint block."""
    return zlib.crc32(np.ascontiguousarray(data).tobytes()) & 0xFFFFFFFF


def extract_blocks(tree, proc: int) -> Tuple[Dict[str, np.ndarray],
                                             Dict[str, dict], int]:
    """This process's owned shard blocks of a pytree, in the canonical
    block-key encoding: ``(blocks, leaves_meta, max_block_bytes)``.

    A ``jax.Array`` leaf contributes its addressable shards with
    ``replica_id == 0`` (each unique block written once cluster-wide);
    host-side leaves are replicated by construction, so the chief
    contributes them as one full block. ``leaves_meta`` records every
    leaf's GLOBAL shape/dtype regardless of who owns its blocks — it is
    identical on all processes and becomes the manifest. Shared by
    ``ShardedCheckpointer.save`` and the buddy-redundancy mirror encoding
    (``resilience/redundancy.py``)."""
    blocks: Dict[str, np.ndarray] = {}
    leaves_meta: Dict[str, dict] = {}
    max_block = 0
    for path, leaf in _iter_leaf_paths(tree):
        if isinstance(leaf, jax.Array):
            shape, dtype = tuple(leaf.shape), np.dtype(leaf.dtype)
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue  # an identical copy is written elsewhere
                data = np.asarray(shard.data)
                max_block = max(max_block, data.nbytes)
                starts = _starts_of(shard.index, shape)
                blocks[_block_key(path, starts, data.shape)] = data
        else:
            # Host-side leaf (plain numpy/python scalar): replicated by
            # construction, chief writes it as one full block.
            data = np.asarray(leaf)
            shape, dtype = tuple(data.shape), data.dtype
            if proc == 0:
                max_block = max(max_block, data.nbytes)
                blocks[_block_key(path, (0,) * data.ndim, data.shape)] = data
        leaves_meta[path] = {
            "shape": list(shape),
            "dtype": dtype.name,
        }
    return blocks, leaves_meta, max_block


def _write_proc_npz(path: Path, blocks: Dict[str, np.ndarray]) -> None:
    """Atomic write of one process's block file, CRC map included."""
    crcs = {k: block_crc(v) for k, v in blocks.items()}
    payload = dict(blocks)
    payload[CRC_KEY] = np.frombuffer(
        json.dumps(crcs).encode(), dtype=np.uint8
    ).copy()
    _atomic_write(path, lambda tmp: np.savez(open(tmp, "wb"), **payload))


class _BlockIndex:
    """All saved blocks of one checkpoint: (leaf path) -> [(starts, file,
    key)], with lazily-opened npz handles so restore reads only the blocks
    it needs. Block reads are CRC-validated when the file carries a CRC
    map (older checkpoints without one load unvalidated) and counted into
    ``read_stats`` — this is the DISK reader; the buddy tier supplies its
    own in-memory index with the same two-method surface."""

    def __init__(self, step_dir: Path, nprocs: int):
        self._files = [step_dir / f"proc-{i}.npz" for i in range(nprocs)]
        self._handles: Dict[int, Any] = {}
        self._crcs: Dict[int, Optional[dict]] = {}
        self.blocks: Dict[str, list] = {}
        for fi, f in enumerate(self._files):
            if not f.exists():
                raise FileNotFoundError(
                    f"checkpoint shard file missing: {f} (manifest promises "
                    f"{nprocs} processes — is the directory shared?)"
                )
            try:
                with np.load(f, allow_pickle=False) as z:
                    names = list(z.files)
            except Exception as e:
                # Garbage where a zip should be (torn write, clobbered
                # file): name the file, let auto-restore fall back.
                raise ShardCorruptionError(
                    f"checkpoint shard file {f} is unreadable "
                    f"({type(e).__name__}: {e})", path=f,
                ) from e
            for key in names:
                if key == CRC_KEY:
                    continue
                path, starts, shape = _parse_key(key)
                self.blocks.setdefault(path, []).append(
                    (starts, shape, fi, key)
                )

    def _handle(self, fi: int):
        h = self._handles.get(fi)
        if h is None:
            try:
                h = np.load(self._files[fi], allow_pickle=False)
            except Exception as e:
                raise ShardCorruptionError(
                    f"checkpoint shard file {self._files[fi]} is unreadable "
                    f"({type(e).__name__}: {e})", path=self._files[fi],
                ) from e
            self._handles[fi] = h
            crcs = None
            if CRC_KEY in h.files:
                try:
                    crcs = json.loads(bytes(h[CRC_KEY]).decode())
                except Exception as e:
                    raise ShardCorruptionError(
                        f"CRC map of {self._files[fi]} is unreadable "
                        f"({type(e).__name__}: {e})", path=self._files[fi],
                    ) from e
            self._crcs[fi] = crcs
        return h

    def read(self, fi: int, key: str) -> np.ndarray:
        h = self._handle(fi)
        try:
            data = h[key]
        except Exception as e:
            raise ShardCorruptionError(
                f"block {key!r} of {self._files[fi]} failed to load "
                f"({type(e).__name__}: {e})",
                path=self._files[fi], key=key,
            ) from e
        crcs = self._crcs.get(fi)
        if crcs is not None:
            want = crcs.get(key)
            if want is not None and block_crc(data) != int(want):
                raise ShardCorruptionError(
                    f"CRC mismatch for block {key!r} in {self._files[fi]}: "
                    f"stored {int(want)}, computed {block_crc(data)} — the "
                    "block is corrupt on disk",
                    path=self._files[fi], key=key,
                )
        read_stats["block_reads"] += 1
        read_stats["block_bytes"] += int(data.nbytes)
        return data

    def close(self):
        for h in self._handles.values():
            h.close()
        self._handles.clear()


def restore_from_index(model, index, manifest: dict) -> Tuple[int, int]:
    """Rebuild params/state/opt_state onto ``model`` from a block index.

    ``index`` needs only ``blocks`` ({leaf path -> [(starts, shape,
    handle, key)]}) and ``read(handle, key) -> np.ndarray`` — the disk
    ``_BlockIndex`` and the buddy tier's in-memory mirror index both
    satisfy it, so a RAM restore is byte-for-byte the same reassembly as
    a disk one. ``manifest`` carries step/seed/input_shape/leaves (+
    optional data_state). Returns ``(step, max_block_bytes)``."""
    step = int(manifest["step"])
    if not model.built:
        model.build(manifest["input_shape"], seed=manifest.get("seed", 0))

    leaves_meta = manifest["leaves"]
    max_block = 0
    # Templates define structure AND target shardings. opt_state uses the
    # strategy's eager init so restored optimizer state keeps the same
    # placement as a fresh compile.
    templates = {
        "params": model.params,
        "state": model.state if model.state else {},
    }
    has_opt = any(p.startswith("opt_state") for p in leaves_meta)
    if model.compiled and has_opt:
        templates["opt_state"] = model.strategy.init_opt_state(
            model.tx, model.params
        )
    # Saved-before-compile checkpoints have no opt leaves: keep the
    # model's fresh optimizer init (same contract as Checkpointer).

    def rebuild(path, template_leaf):
        nonlocal max_block
        meta = leaves_meta.get(path)
        if meta is None:
            raise KeyError(
                f"checkpoint step {step} has no leaf {path!r} — "
                "wrong model or optimizer for this checkpoint"
            )
        shape = tuple(meta["shape"])
        dtype = np.dtype(meta["dtype"])
        t_shape = tuple(np.shape(template_leaf))
        if t_shape != shape:
            raise ValueError(
                f"checkpoint leaf {path!r} has global shape {shape} "
                f"but the model expects {t_shape}"
                " — wrong model for this checkpoint"
            )
        saved = index.blocks.get(path, [])
        if not saved:
            raise KeyError(
                f"no saved blocks for leaf {path!r} in step {step}"
            )
        cache: Dict[Tuple[Any, str], np.ndarray] = {}

        def read_block(fi, key):
            got = cache.get((fi, key))
            if got is None:
                got = index.read(fi, key)
                cache[(fi, key)] = got
            return got

        def cb(req_index):
            nonlocal max_block
            req = [
                (0 if sl.start is None else int(sl.start),
                 dim if sl.stop is None else int(sl.stop))
                for sl, dim in zip(req_index, shape)
            ]
            out = np.empty(
                tuple(hi - lo for lo, hi in req), dtype
            )
            filled = 0
            for starts, bshape, fi, key in saved:
                # Overlap of [bstart, bstop) with [lo, hi) per dim —
                # decided from the key alone; only overlapping
                # blocks are read from the medium.
                dst = []
                ok = True
                for d, (lo, hi) in enumerate(req):
                    bstart = starts[d] if d < len(starts) else 0
                    bstop = bstart + bshape[d]
                    s, e = max(bstart, lo), min(bstop, hi)
                    if s >= e:
                        ok = False
                        break
                    dst.append((s - lo, e - lo, s - bstart, e - bstart))
                if not ok:
                    continue
                block = read_block(fi, key)
                if tuple(block.shape) != tuple(bshape):
                    # np.load(mmap_mode=...) surfaces 0-d blocks as (1,);
                    # the key records the true shape — restore it (a view,
                    # no copy).
                    block = block.reshape(bshape)
                max_block = max(max_block, block.nbytes)
                out_sel = tuple(slice(a, b) for a, b, _, _ in dst)
                blk_sel = tuple(slice(c, d) for _, _, c, d in dst)
                out[out_sel] = block[blk_sel]
                filled += int(np.prod(out[out_sel].shape))
            if filled < int(np.prod(out.shape)):
                raise ValueError(
                    f"saved blocks for {path!r} do not cover the "
                    f"requested shard {req} (filled {filled} of "
                    f"{int(np.prod(out.shape))} elements)"
                )
            return out

        if isinstance(template_leaf, jax.Array):
            return jax.make_array_from_callback(
                shape, template_leaf.sharding, cb
            )
        full = cb(tuple(slice(0, d) for d in shape))
        return np.asarray(full, dtype)

    restored = {}
    for section, template in templates.items():
        paths, leaves = [], []
        for path, leaf in _iter_leaf_paths({section: template}):
            paths.append(path)
            leaves.append(leaf)
        new_leaves = [rebuild(p, l) for p, l in zip(paths, leaves)]
        treedef = jax.tree_util.tree_structure(template)
        restored[section] = jax.tree_util.tree_unflatten(
            treedef, new_leaves
        )

    model.params = restored["params"]
    if restored.get("state") is not None and model.state:
        model.state = restored["state"]
    if model.compiled and "opt_state" in restored:
        model.opt_state = restored["opt_state"]
    model.step = step
    model._seed = int(manifest.get("seed", model._seed))
    # fit() restores the data source from this via load_state() (the
    # state records the GLOBAL stream cursor, so it composes with
    # reshard("auto") after an elastic resize).
    model._restored_data_state = manifest.get("data_state")
    return step, max_block


class ShardedCheckpointer:
    """Per-process sharded checkpoints with mesh-shape-independent restore.

    Drop-in sibling of ``Checkpointer`` (same ``save(model)`` /
    ``restore_into(model)`` / ``all_steps`` surface), but save cost and
    host memory are O(addressable shards), not O(total params).

    ``async_save=True`` moves the device->host shard fetch, CRC, and npz
    serialization onto a background "dtpu-shard-writer" thread after a
    cheap donation-safe on-device snapshot. The cross-host commit (barrier
    + chief manifest) is DEFERRED to the next main-thread touchpoint — the
    following ``save()``, an explicit ``wait()``, or ``restore_into`` —
    so no collective ever runs on the writer thread concurrently with
    training collectives (the constraint that used to forbid async sharded
    saves outright). Until that commit the step directory has no manifest
    and is invisible to ``all_steps()``: interrupted async saves are
    aborted saves, exactly like a mid-write crash. On multi-process gangs
    the commit first allgathers per-process writer outcomes, so one
    process's failed write aborts the commit everywhere instead of
    publishing a checkpoint with a missing shard — the writer's exception
    re-raises on its own process at ``wait()``.
    """

    def __init__(self, directory, keep: int = 3, async_save: bool = False):
        self.directory = Path(directory)
        self.keep = int(keep)
        self.async_save = bool(async_save)
        # Diagnostics for tests/ops: the largest single host block touched
        # by the most recent save/restore (must stay << full leaf size for
        # sharded leaves — the whole point of the format).
        self.last_max_block_bytes = 0
        self._writer: Optional[threading.Thread] = None
        self._writer_error: Optional[BaseException] = None
        self._writer_lock = threading.Lock()
        self._pending: Optional[dict] = None  # manifest awaiting commit

    def wait(self) -> None:
        """Barrier: join any in-flight background shard write, then run the
        deferred cross-host commit (collective-safe: always the calling
        thread). Re-raises the writer's exception if it failed — the
        pending step is then abandoned, never committed. No-op for
        synchronous checkpointers, so generic callers (ModelCheckpoint
        train-end, the preemption flush) can call it unconditionally."""
        with self._writer_lock:
            writer, self._writer = self._writer, None
        if writer is not None:
            writer.join()
        err, self._writer_error = self._writer_error, None
        self._finalize_pending(failed=err is not None)
        if err is not None:
            raise err

    # ------------------------------------------------------------- layout --
    def _step_dir(self, step: int) -> Path:
        return self.directory / f"ckpt-{step}"

    def all_steps(self):
        if not self.directory.is_dir():
            return []
        steps = []
        for p in self.directory.glob("ckpt-*"):
            m = re.fullmatch(r"ckpt-(\d+)", p.name)
            # manifest.json is the commit marker: a dir without it is an
            # aborted save.
            if m and (p / "manifest.json").exists():
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # --------------------------------------------------------------- save --
    def save(self, model, step: Optional[int] = None) -> Path:
        # Serialize the step family: an older in-flight write must land —
        # and its deferred commit run — before a newer save may start.
        self.wait()
        step = model.step if step is None else step
        tree = {
            "params": model.params,
            "state": model.state if model.state else {},
            "opt_state": model.opt_state,
        }
        step_dir = self._step_dir(int(step))
        step_dir.mkdir(parents=True, exist_ok=True)
        proc = jax.process_index()

        manifest = {
            "step": int(step),
            "seed": int(model._seed),
            "input_shape": list(model.input_shape or ()),
            "nprocs": jax.process_count(),
        }
        # Iterator cursor of the active fit source (data.Pipeline
        # state_dict), aligned to the trained step — captured NOW, on the
        # caller's thread, even for async saves (the source advances while
        # the writer runs). The manifest is read by EVERY process at
        # restore (shared directory), so unlike Checkpointer's chief-only
        # meta it resumes streaming input on whole gangs, including
        # resized (elastic) ones.
        dstate = _data_state_of(model, int(step))
        if dstate is not None:
            manifest["data_state"] = dstate

        if self.async_save:
            # Donation-safe on-device snapshot on the caller's thread
            # (ordered before any later donating dispatch); the writer
            # fetches shards from the snapshot at leisure. Extraction
            # touches only addressable shards — no collective.
            snap = _device_snapshot(tree)

            def write():
                try:
                    blocks, leaves_meta, max_block = extract_blocks(
                        snap, proc
                    )
                    _write_proc_npz(step_dir / f"proc-{proc}.npz", blocks)
                    self.last_max_block_bytes = max_block
                    manifest["leaves"] = leaves_meta
                except BaseException as e:  # surfaced at the next save/wait
                    self._writer_error = e

            self._pending = manifest
            writer = threading.Thread(
                target=write, name="dtpu-shard-writer", daemon=True
            )
            with self._writer_lock:
                self._writer = writer
            # Same global-flush contract as Checkpointer: the preemption
            # path's wait_all_async() joins this writer AND runs the
            # deferred commit before the final save (every rank takes the
            # preemption boundary together, so the commit's collective
            # stays lockstep).
            _ASYNC_CHECKPOINTERS.add(self)
            writer.start()
            return step_dir

        blocks, leaves_meta, max_block = extract_blocks(tree, proc)
        self.last_max_block_bytes = max_block
        _write_proc_npz(step_dir / f"proc-{proc}.npz", blocks)
        manifest["leaves"] = leaves_meta
        self._pending = manifest
        self._finalize_pending(failed=False)
        return step_dir

    def _finalize_pending(self, *, failed: bool) -> None:
        """The deferred commit: cross-host agreement that every process's
        shard write landed, then the chief publishes the manifest (the
        commit marker) and gc's old steps. Always runs on the calling
        thread — save()/wait()/restore_into() are executed in lockstep by
        every process of a gang, so the collective aligns."""
        pending, self._pending = self._pending, None
        if pending is None:
            return
        any_failed = failed
        if jax.process_count() > 1:
            # One collective doubles as the write barrier AND the outcome
            # agreement: a failed writer on ANY process aborts the commit
            # on ALL of them (a manifest must never promise a shard file
            # that was not fully written).
            from jax.experimental import multihost_utils

            flags = multihost_utils.process_allgather(
                np.array([1 if failed else 0], np.int32)
            )
            any_failed = bool(np.asarray(flags).sum() > 0)
        if any_failed:
            return
        if _is_chief():
            _atomic_write(
                self._step_dir(int(pending["step"])) / "manifest.json",
                lambda tmp: Path(tmp).write_text(json.dumps(pending)),
            )
            self._gc()

    def _gc(self):
        import shutil

        steps = self.all_steps()
        for s in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    # ------------------------------------------------------------ restore --
    def _agreed_step(self, excluded) -> Optional[int]:
        """The newest committed step not yet ruled out, agreed gang-wide:
        the chief's view of the (shared) directory decides — filesystem
        visibility can lag on some hosts, and a per-process scan could
        silently desynchronize the gang onto different steps."""
        cands = [s for s in self.all_steps() if s not in excluded]
        step = cands[-1] if cands else None
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            chosen = np.array([-1 if step is None else int(step)], np.int64)
            step = int(multihost_utils.broadcast_one_to_all(chosen)[0])
            if step < 0:
                step = None
        return step

    def restore_into(self, model, step: Optional[int] = None) -> int:
        """Restore under the model's *current* strategy/mesh.

        Unlike ``Checkpointer._restore_multihost`` there is no broadcast:
        every process reads the (shared) directory itself and builds only
        its addressable shards. Host memory is therefore O(the target
        sharding's addressable shard sizes) — for a sharded target (FSDP/
        TP) no host ever assembles a full leaf; restoring into a
        *replicated* target necessarily assembles full leaves per host,
        exactly matching what that target keeps in device memory anyway.

        Auto-restore (``step=None``) survives corruption: a step whose
        blocks fail CRC (or whose shard files are torn) is skipped with a
        ``corrupt_checkpoint_skipped`` event and the scan falls back to
        the previous retained step — corruption costs one checkpoint
        interval, not the run. An EXPLICIT step propagates the
        block-addressed :class:`ShardCorruptionError` instead: silent
        substitution would hide the damage from a caller who named the
        step. (All processes of a gang read the same shared files, so a
        corruption-driven fallback is deterministic gang-wide.)
        """
        self.wait()  # flush + commit any pending async save first
        if step is not None:
            return self._restore_step(model, int(step))
        from ..utils import event_schema as evs
        from ..utils import events as events_lib
        from ..utils import logging as dlog

        excluded: set = set()
        while True:
            cand = self._agreed_step(excluded)
            if cand is None:
                raise FileNotFoundError(
                    f"No sharded checkpoints in {self.directory}"
                    + (f" ({len(excluded)} step(s) present but corrupt)"
                       if excluded else "")
                )
            try:
                return self._restore_step(model, cand)
            except ShardCorruptionError as e:
                dlog.warning(
                    f"ShardedCheckpointer: skipping corrupt step {cand} "
                    f"({e}); falling back to the previous retained step"
                )
                events_lib.emit(
                    evs.CORRUPT_CHECKPOINT_SKIPPED, step=int(cand),
                    path=e.path or str(self._step_dir(cand)), error=str(e),
                )
                excluded.add(cand)

    def _restore_step(self, model, step: int) -> int:
        step_dir = self._step_dir(step)
        try:
            manifest = json.loads((step_dir / "manifest.json").read_text())
        except FileNotFoundError:
            raise
        except (OSError, ValueError) as e:
            raise ShardCorruptionError(
                f"manifest of {step_dir} is unreadable "
                f"({type(e).__name__}: {e})", path=step_dir / "manifest.json",
            ) from e
        index = _BlockIndex(step_dir, int(manifest["nprocs"]))
        try:
            got, max_block = restore_from_index(model, index, manifest)
        finally:
            index.close()
        self.last_max_block_bytes = max_block
        return got
