from .config import ClusterSpec, from_barrier, from_env, resolve
from .init import (
    ELASTIC_WORLD_ENV,
    barrier,
    initialize,
    is_chief,
    is_initialized,
    process_count,
    process_index,
    reset_for_relaunch,
    shutdown,
)
from .net import check_reachable, free_port, my_ip, preflight

__all__ = [
    "ClusterSpec",
    "from_env",
    "from_barrier",
    "resolve",
    "initialize",
    "is_initialized",
    "reset_for_relaunch",
    "shutdown",
    "is_chief",
    "barrier",
    "ELASTIC_WORLD_ENV",
    "process_index",
    "process_count",
    "my_ip",
    "free_port",
    "check_reachable",
    "preflight",
]
