from .config import ClusterSpec, from_barrier, from_env, resolve
from .init import barrier, initialize, is_chief, is_initialized, process_count, process_index
from .net import check_reachable, free_port, my_ip, preflight

__all__ = [
    "ClusterSpec",
    "from_env",
    "from_barrier",
    "resolve",
    "initialize",
    "is_initialized",
    "is_chief",
    "barrier",
    "process_index",
    "process_count",
    "my_ip",
    "free_port",
    "check_reachable",
    "preflight",
]
