"""Cluster specification and resolution.

Parity target: the reference's ``TF_CONFIG`` env contract
(/root/reference/README.md:84-89 R form, 322-327 Python form):

    {"cluster": {"worker": ["ip:port", ...]}, "task": {"type": "worker", "index": i}}

set identically on every worker except ``task.index``, before library init.

TPU-native redesign: ``ClusterSpec`` keeps that explicit-worker-list form (it
is what CPU-simulation CI and bespoke clusters need) but adds the pod-slice
resolution path where topology is discovered from the TPU runtime and no list
is written at all (``resolve()`` order: explicit arg > DTPU_CONFIG > TF_CONFIG
> TPU runtime auto-detect > single-process default).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional

ENV_VAR = "DTPU_CONFIG"
TF_ENV_VAR = "TF_CONFIG"  # accepted for migration compatibility


@dataclasses.dataclass
class ClusterSpec:
    """One process's view of the cluster."""

    workers: List[str]  # "host:port" for every process, rank-ordered
    index: int  # this process's rank (reference: task.index)

    @property
    def num_processes(self) -> int:
        return len(self.workers)

    @property
    def coordinator(self) -> str:
        """Rank 0's endpoint — the chief (reference: index 0 is chief,
        /root/reference/README.md:84-89)."""
        return self.workers[0]

    @property
    def is_chief(self) -> bool:
        return self.index == 0

    # ---------------------------------------------------------------- codecs
    def to_json(self) -> str:
        return json.dumps(
            {
                "cluster": {"worker": list(self.workers)},
                "task": {"type": "worker", "index": self.index},
            }
        )

    @classmethod
    def from_json(cls, text: str) -> "ClusterSpec":
        obj = json.loads(text)
        task = obj.get("task", {})
        if task.get("type", "worker") != "worker":
            raise ValueError(
                f"Only 'worker' tasks exist (got {task.get('type')!r}); the "
                "reference likewise has no parameter servers (SURVEY.md §2c)"
            )
        cluster = obj.get("cluster", {})
        if "worker" not in cluster:
            raise ValueError(
                f"Cluster spec must contain a 'worker' job (got jobs "
                f"{sorted(cluster)}); parameter-server / evaluator jobs are "
                "not supported"
            )
        workers = cluster["worker"]
        return cls(workers=list(workers), index=int(task.get("index", 0)))

    def validate(self):
        if not self.workers:
            raise ValueError("Empty worker list")
        if not 0 <= self.index < len(self.workers):
            raise ValueError(
                f"task index {self.index} out of range for {len(self.workers)} workers"
            )
        for w in self.workers:
            if ":" not in w:
                raise ValueError(f"Worker address {w!r} must be host:port")
        return self


def from_env() -> Optional[ClusterSpec]:
    for var in (ENV_VAR, TF_ENV_VAR):
        text = os.environ.get(var)
        if text:
            return ClusterSpec.from_json(text).validate()
    return None


def from_barrier(addresses: List[str], partition: int, base_port: int = 8000) -> ClusterSpec:
    """Build a spec from a barrier-style peer list + own rank, re-porting the
    peers — exactly the reference's Spark-closure construction
    (/root/reference/README.md:180-183: strip Spark's port, assign 8000+seq)."""
    hosts = [a.rsplit(":", 1)[0] for a in addresses]
    workers = [f"{h}:{base_port + i + 1}" for i, h in enumerate(hosts)]
    return ClusterSpec(workers=workers, index=int(partition)).validate()


def resolve(spec: Optional[ClusterSpec] = None) -> Optional[ClusterSpec]:
    """Resolution order: explicit > env (DTPU_CONFIG/TF_CONFIG) > None
    (meaning: let the TPU runtime auto-discover, or run single-process)."""
    if spec is not None:
        return spec.validate()
    return from_env()
