"""Multi-host runtime bootstrap.

Replaces the reference's cluster handshake — TF_CONFIG parsed at strategy
construction, per-worker gRPC servers, blocking collective handshake at first
fit() (/root/reference/README.md:395-399) — with ``jax.distributed``: every
host runs the same SPMD program, process 0 hosts the coordinator service, and
all collectives are XLA-compiled over ICI/DCN (no gRPC worker in the loop).

``initialize()`` is idempotent and resolution-ordered (explicit spec >
DTPU_CONFIG/TF_CONFIG env > TPU runtime auto-detect > single-process no-op),
mirroring the reference's config-by-environment contract (SURVEY.md §1).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from ..utils import logging as dlog
from . import config as config_lib

_initialized = False


def initialize(
    spec: Optional[config_lib.ClusterSpec] = None,
    *,
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> config_lib.ClusterSpec:
    """Join (or form) the cluster. Call once, before any device computation —
    the same ordering constraint the reference enforces by requiring a fresh
    session before setting TF_CONFIG (/root/reference/README.md:316-317).

    Returns the resolved ClusterSpec (a synthetic one under auto-detect).
    """
    global _initialized
    if coordinator is not None:
        spec = config_lib.ClusterSpec(
            workers=[coordinator] + [f"?:{i}" for i in range(1, num_processes or 1)],
            index=process_id or 0,
        )
        if num_processes and num_processes > 1 and not _initialized:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
            )
            _initialized = True
        return spec

    spec = config_lib.resolve(spec)
    if spec is not None and spec.num_processes > 1:
        if not _initialized:
            jax.distributed.initialize(
                coordinator_address=spec.coordinator,
                num_processes=spec.num_processes,
                process_id=spec.index,
            )
            _initialized = True
            if spec.is_chief:
                dlog.info(
                    f"cluster up: {spec.num_processes} processes, "
                    f"coordinator {spec.coordinator}, "
                    f"{jax.device_count()} devices total"
                )
        return spec
    # Auto-detect path: on a real TPU pod slice each host sees its local chips
    # and jax.distributed.initialize() with no args uses the TPU metadata.
    if os.environ.get("DTPU_AUTO_INIT") == "1" and not _initialized:
        jax.distributed.initialize()
        _initialized = True
    return config_lib.ClusterSpec(
        workers=[f"localhost:0"], index=0
    )


def is_initialized() -> bool:
    return _initialized


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_chief() -> bool:
    return jax.process_index() == 0


def barrier(name: str = "barrier", timeout_s: int = 600):
    """Host-level sync point (the reference gets this implicitly from its
    first collective, README.md:399; we expose it explicitly)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
