"""Multi-host runtime bootstrap.

Replaces the reference's cluster handshake — TF_CONFIG parsed at strategy
construction, per-worker gRPC servers, blocking collective handshake at first
fit() (/root/reference/README.md:395-399) — with ``jax.distributed``: every
host runs the same SPMD program, process 0 hosts the coordinator service, and
all collectives are XLA-compiled over ICI/DCN (no gRPC worker in the loop).

``initialize()`` is idempotent and resolution-ordered (explicit spec >
DTPU_CONFIG/TF_CONFIG env > TPU runtime auto-detect > single-process no-op),
mirroring the reference's config-by-environment contract (SURVEY.md §1).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from ..utils import logging as dlog
from . import config as config_lib

_initialized = False
_gathered_cache = None  # explicit-coordinator spec, cached after the gather

# Elastic world-size override, exported by a resizing Supervisor: the
# relaunched gang must form a clean N'-process runtime even when the
# inherited DTPU_CONFIG/TF_CONFIG still names the old N workers.
ELASTIC_WORLD_ENV = "DTPU_ELASTIC_WORLD"


def _enable_cpu_collectives():
    """Give a multi-process CPU gang a working collectives layer.

    XLA:CPU compiles cross-process computations only through a host
    collectives implementation (gloo); without one, the FIRST cross-process
    operation — even a replicated ``device_put`` onto a 2-process mesh —
    fails with "Multiprocess computations aren't implemented on the CPU
    backend". TPU/GPU backends bring their own collectives, so this flips
    the switch only when the platform is explicitly CPU (the CI sim and
    the launcher tests), and must run BEFORE the backend initializes —
    which holds here because initialize() is documented as
    before-any-device-computation. Best-effort: a jax build without the
    gloo option keeps its old behavior."""
    plats = (
        jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS") or ""
    ).lower()
    if "cpu" not in [p.strip() for p in plats.split(",")]:
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # unknown config / unsupported build
        pass


def _gathered_workers(coordinator: str, n: int, index: int) -> list:
    """Real rank-ordered worker list for an explicit-coordinator init: every
    process contributes its own address via a host-level allgather (must run
    on ALL processes — it is a collective). Rank 0's entry keeps the
    coordinator's service port; other ranks report port 0 (informational
    address — jax processes run no per-worker server, unlike the reference's
    per-worker gRPC endpoints, /root/reference/README.md:398)."""
    from . import net

    mine = coordinator if index == 0 else f"{net.my_ip()}:0"
    if n <= 1:
        return [mine]
    import numpy as np
    from jax.experimental import multihost_utils

    cap = 256
    raw = mine.encode()
    if len(raw) > cap:
        raise ValueError(
            f"worker address {mine!r} exceeds {cap} bytes"
        )
    buf = np.zeros(cap, np.uint8)
    buf[: len(raw)] = np.frombuffer(raw, np.uint8)
    gathered = multihost_utils.process_allgather(buf)  # (P, cap)
    return [
        bytes(row).rstrip(b"\x00").decode(errors="replace")
        for row in np.asarray(gathered)
    ]


def _apply_elastic_world(
    spec: config_lib.ClusterSpec,
) -> config_lib.ClusterSpec:
    """Honor ``DTPU_ELASTIC_WORLD`` over an env-inherited spec: truncate
    the worker list to the elastic world's first N' entries (rank order is
    the supervisor's contract — surviving workers keep a dense rank
    prefix). A rank outside the new world must not join at all: raising
    here beats N' workers hanging at a collective waiting for a ghost.
    Growing past the inherited list is impossible from this side (the
    override carries no addresses) — the launcher regenerates the spec on
    a real grow, so warn and keep the spec."""
    raw = os.environ.get(ELASTIC_WORLD_ENV)
    if not raw:
        return spec
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"{ELASTIC_WORLD_ENV} must be an integer, got {raw!r}"
        )
    if n < 1:
        raise ValueError(f"{ELASTIC_WORLD_ENV} must be >= 1, got {n}")
    if n == spec.num_processes:
        return spec
    if n > spec.num_processes:
        dlog.warning(
            f"{ELASTIC_WORLD_ENV}={n} exceeds the inherited spec's "
            f"{spec.num_processes} workers; an elastic grow needs a "
            "regenerated spec (the override carries no addresses) — "
            "keeping the inherited spec"
        )
        return spec
    if spec.index >= n:
        raise ValueError(
            f"rank {spec.index} is outside the elastic world of {n} "
            f"(inherited spec had {spec.num_processes} workers); this "
            "process should not have been launched"
        )
    return config_lib.ClusterSpec(
        workers=list(spec.workers[:n]), index=spec.index
    ).validate()


def _tpu_pod_spec() -> Optional[config_lib.ClusterSpec]:
    """Spec from the TPU runtime's own pod metadata (GCE TPU-VM env),
    giving auto-detected clusters a real worker list too."""
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES")
    if not hosts:
        return None
    index = int(
        os.environ.get("TPU_WORKER_ID")
        or os.environ.get("CLOUD_TPU_TASK_ID")
        or 0
    )
    workers = [f"{h.strip()}:8476" for h in hosts.split(",") if h.strip()]
    try:
        return config_lib.ClusterSpec(workers=workers, index=index).validate()
    except ValueError:
        return None


def _should_auto_init() -> bool:
    """Pod auto-detect is the DEFAULT on TPU platforms: fire when the TPU
    runtime's pod-slice markers are present. DTPU_AUTO_INIT=1 forces it,
    DTPU_AUTO_INIT=0 opts out (SURVEY.md §7 item 3)."""
    gate = os.environ.get("DTPU_AUTO_INIT")
    if gate == "1":
        return True
    if gate == "0":
        return False
    # Multi-host markers only: a single-host slice (TPU_WORKER_HOSTNAMES
    # with one entry, e.g. "localhost") needs no jax.distributed at all.
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hosts.split(",") if h.strip()]) > 1:
        return True
    return bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))


def initialize(
    spec: Optional[config_lib.ClusterSpec] = None,
    *,
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> config_lib.ClusterSpec:
    """Join (or form) the cluster. Call once, before any device computation —
    the same ordering constraint the reference enforces by requiring a fresh
    session before setting TF_CONFIG (/root/reference/README.md:316-317).

    Resolution order: explicit coordinator args > explicit/env spec
    (DTPU_CONFIG/TF_CONFIG) > TPU pod auto-detect (default on pod slices) >
    single-process. Returns the resolved ClusterSpec with a REAL worker
    list in every path that can know one.
    """
    global _initialized, _gathered_cache
    if coordinator is not None:
        n = int(num_processes or 1)
        idx = int(process_id or 0)
        if _gathered_cache is not None:
            # Repeat call (e.g. two libraries both bootstrapping): the
            # gather below is a collective and would hang if peers don't
            # re-enter it; the first call's result answers this one.
            return _gathered_cache
        if n > 1 and not _initialized:
            _enable_cpu_collectives()
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=n,
                process_id=idx,
            )
            _initialized = True
        _gathered_cache = config_lib.ClusterSpec(
            workers=_gathered_workers(coordinator, n, idx), index=idx
        )
        return _gathered_cache

    explicit = spec is not None
    spec = config_lib.resolve(spec)
    if spec is not None:
        if not explicit:
            # Env-inherited specs can be stale across an elastic resize;
            # an explicitly passed spec is the caller's authority and is
            # never rewritten.
            spec = _apply_elastic_world(spec)
        # An explicit/env spec always wins — including a single-process one
        # (debugging one worker on a pod VM must not be hijacked by
        # auto-detect).
        if spec.num_processes > 1 and not _initialized:
            _enable_cpu_collectives()
            jax.distributed.initialize(
                coordinator_address=spec.coordinator,
                num_processes=spec.num_processes,
                process_id=spec.index,
            )
            _initialized = True
            if spec.is_chief:
                dlog.info(
                    f"cluster up: {spec.num_processes} processes, "
                    f"coordinator {spec.coordinator}, "
                    f"{jax.device_count()} devices total"
                )
        return spec
    # Auto-detect path (only when nothing explicit resolved): on a TPU pod
    # slice each host sees its local chips and jax.distributed.initialize()
    # with no args uses the TPU metadata. This is the documented default
    # when pod markers are present; DTPU_AUTO_INIT=0 opts out.
    if _should_auto_init() and not _initialized:
        try:
            jax.distributed.initialize()
            _initialized = True
        except RuntimeError as e:
            # Best-effort: jax.distributed must run before any backend use;
            # initialize() called late in a single-host flow should degrade
            # to local semantics, not crash the program.
            dlog.warning(f"pod auto-init skipped: {e}")
    if jax.process_count() > 1:
        # Multi-process for real — whether our auto-init did it or the user
        # called jax.distributed.initialize() themselves. The returned spec
        # must agree with the actual runtime: only adopt the pod metadata's
        # worker list when it matches what jax.distributed really formed.
        # Conversely, when auto-init was opted out (DTPU_AUTO_INIT=0) or
        # failed and the runtime stayed single-process, pod env markers may
        # still be present — returning them would disable chief-gating on a
        # process that is in fact the only one (the single-process fall-
        # through below handles that case).
        pod = _tpu_pod_spec()
        if (
            pod is not None
            and pod.num_processes == jax.process_count()
            and pod.index == jax.process_index()
        ):
            return pod
        # Joined a real cluster but the runtime exposes no (consistent)
        # host list: still return truthful rank/size so chief-gating
        # works; addresses are unknowable here.
        return config_lib.ClusterSpec(
            workers=[f"unknown:{i}" for i in range(jax.process_count())],
            index=jax.process_index(),
        )
    return config_lib.ClusterSpec(workers=["localhost:0"], index=0)


def is_initialized() -> bool:
    return _initialized


def reset_for_relaunch() -> None:
    """Clear the module's memo state (``_initialized`` guard and the
    explicit-coordinator spec cache) so a re-formed — possibly resized —
    gang can ``initialize()`` cleanly in the same process. Without this an
    in-process relaunch silently reuses the stale cached spec: the old
    world size, the old coordinator, the old rank.

    This clears bookkeeping only; it does NOT tear down a live
    ``jax.distributed`` runtime — use :func:`shutdown` when this process
    actually joined one. (Single-process test gangs and the
    explicit-coordinator n=1 path never start the runtime, so for them
    this is the complete reset.)"""
    global _initialized, _gathered_cache
    _initialized = False
    _gathered_cache = None


def shutdown() -> None:
    """Leave the cluster: tear down ``jax.distributed`` (when this process
    initialized it) and clear the memo state, making ``initialize()``
    re-formable at a new world size. Best-effort on the runtime teardown —
    a coordinator that already died must not turn a relaunch into a crash."""
    global _initialized
    if _initialized:
        try:
            jax.distributed.shutdown()
        except Exception as e:  # dead coordinator / already torn down
            dlog.warning(f"jax.distributed shutdown failed (ignored): {e}")
    reset_for_relaunch()


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_chief() -> bool:
    return jax.process_index() == 0


def barrier(name: str = "barrier", timeout_s: int = 600):
    """Host-level sync point (the reference gets this implicitly from its
    first collective, README.md:399; we expose it explicitly)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
