"""Network helpers: IP discovery and reachability preflight.

Parity targets: the reference's IP helper
(/root/reference/README.md:271-275: ``socket.gethostbyname(socket.gethostname())``)
and its manual ``ping <ip>`` preflight advice (README.md:251), turned into a
programmatic TCP check the launcher runs before gang-start.
"""

from __future__ import annotations

import socket
import time
from typing import Dict, List, Tuple


def my_ip() -> str:
    """Best-effort local IP (README.md:271-275 equivalent, with a UDP-connect
    fallback that works when the hostname doesn't resolve)."""
    try:
        ip = socket.gethostbyname(socket.gethostname())
        if not ip.startswith("127."):
            return ip
    except OSError:
        pass
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))  # no packets sent; just picks a route
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def free_ports(n: int) -> List[int]:
    """n distinct free ports, all held (bound) simultaneously before release
    so none is a duplicate and all were genuinely free at the same moment —
    unlike probing one port and assuming the next n-1 consecutive ones."""
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def backoff_schedule(attempts: int, backoff: float = 0.5,
                     backoff_max: float = 8.0) -> List[float]:
    """Sleep lengths BETWEEN ``attempts`` tries: bounded exponential,
    ``backoff * 2**i`` capped at ``backoff_max`` (len == attempts - 1).
    Shared by the reachability retry below and unit-testable on its own."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    return [min(backoff * 2.0**i, backoff_max) for i in range(attempts - 1)]


def check_reachable(addr: str, timeout: float = 2.0, attempts: int = 1,
                    backoff: float = 0.5, backoff_max: float = 8.0,
                    _sleep=time.sleep) -> bool:
    """TCP reachability to host:port (the programmatic 'ping', README.md:251).

    A connection *refusal* still means the host is up (nothing bound to the
    port yet — normal before gang-start); only DNS failure or a timeout /
    network unreachability counts as down. Those failures are retried up to
    ``attempts`` times with bounded exponential backoff (``backoff``,
    doubling, capped at ``backoff_max``): a worker VM that is still booting
    resolves/routes a few seconds late, and one slow host must delay
    gang-start, not fail it. A positive answer returns immediately."""
    host, port = addr.rsplit(":", 1)
    delays = backoff_schedule(attempts, backoff, backoff_max)
    for i in range(attempts):
        try:
            with socket.create_connection((host, int(port)), timeout=timeout):
                return True
        except ConnectionRefusedError:
            return True  # host answered; port simply not bound yet
        except OSError:
            if i < len(delays):
                _sleep(delays[i])
    return False


def preflight(workers: List[str], timeout: float = 2.0, attempts: int = 3,
              backoff: float = 0.5, backoff_max: float = 8.0) -> Dict[str, bool]:
    """Reachability map for a worker list, run by the launcher before
    gang-start (replaces the reference's manual `ping`, README.md:251).
    Retries each unreachable worker with bounded exponential backoff
    (``attempts`` tries) so workers still booting pass the gang-start
    check instead of failing on the first refused/unrouted probe."""
    return {
        w: check_reachable(w, timeout=timeout, attempts=attempts,
                           backoff=backoff, backoff_max=backoff_max)
        for w in workers
    }
