from .datasets import (
    load,
    load_cifar10,
    load_fashion_mnist,
    load_imagenet,
    fetch_mnist,
    load_digits_real,
    load_mnist,
    synthetic_images,
)
from .filesource import FileSource, write_shards
from .pipeline import Pipeline, native_available
from .prefetch import DevicePrefetcher
from .records import RecordCorruptionError, RecordSource, write_records

__all__ = [
    "Pipeline",
    "DevicePrefetcher",
    "FileSource",
    "write_shards",
    "RecordSource",
    "RecordCorruptionError",
    "write_records",
    "native_available",
    "load",
    "fetch_mnist",
    "load_digits_real",
    "load_mnist",
    "load_fashion_mnist",
    "load_cifar10",
    "load_imagenet",
    "synthetic_images",
]
