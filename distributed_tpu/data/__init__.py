from .datasets import (
    load,
    load_cifar10,
    load_fashion_mnist,
    load_imagenet,
    load_mnist,
    synthetic_images,
)
from .pipeline import Pipeline, native_available

__all__ = [
    "Pipeline",
    "native_available",
    "load",
    "load_mnist",
    "load_fashion_mnist",
    "load_cifar10",
    "load_imagenet",
    "synthetic_images",
]
