from .datasets import load, load_cifar10, load_fashion_mnist, load_mnist, synthetic_images

__all__ = [
    "load",
    "load_mnist",
    "load_fashion_mnist",
    "load_cifar10",
    "synthetic_images",
]
