"""Dataset loaders: MNIST / Fashion-MNIST / CIFAR-10.

Parity target: ``dataset_mnist()`` / ``tf.keras.datasets.mnist.load_data()``
(/root/reference/README.md:51, 286-287) including the reference's
reshape-to-NHWC + /255 preprocessing (README.md:53-56, 288-290), folded in
behind ``normalize=True``.

Resolution order per dataset:
1. explicit ``data_dir`` / ``$DTPU_DATA_DIR``
2. conventional caches (``~/.keras/datasets``, ``~/.cache/distributed_tpu``)
   in either npz (keras layout) or raw IDX / CIFAR-pickle form
3. deterministic synthetic data (unless ``synthetic_ok=False``) — class-
   conditional templates + noise, so models genuinely learn on it; built for
   hermetic CI/bench environments with no network egress.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

Arrays = Tuple[np.ndarray, np.ndarray]


def _search_dirs(data_dir: Optional[str]):
    dirs = []
    if data_dir:
        dirs.append(Path(data_dir))
    env = os.environ.get("DTPU_DATA_DIR")
    if env:
        dirs.append(Path(env))
    dirs += [
        Path.home() / ".cache" / "distributed_tpu",
        Path.home() / ".keras" / "datasets",
    ]
    return [d for d in dirs if d.is_dir()]


# --------------------------------------------------------------------- IDX --
def _read_idx(path: Path) -> np.ndarray:
    """Parse an IDX file (optionally gzipped) — MNIST's native format."""
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dtype = {0x08: np.uint8, 0x09: np.int8, 0x0D: np.float32}[(magic >> 8) & 0xFF]
        shape = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=dtype)
    return data.reshape(shape)


_IDX_NAMES = {
    ("train", "x"): ["train-images-idx3-ubyte", "train-images.idx3-ubyte"],
    ("train", "y"): ["train-labels-idx1-ubyte", "train-labels.idx1-ubyte"],
    ("test", "x"): ["t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"],
    ("test", "y"): ["t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"],
}


def _try_idx(dirs, subdirs, split) -> Optional[Arrays]:
    for d in dirs:
        for sub in subdirs:
            base = d / sub if sub else d
            for xn in _IDX_NAMES[(split, "x")]:
                for ext in ("", ".gz"):
                    xp = base / (xn + ext)
                    if not xp.exists():
                        continue
                    for yn in _IDX_NAMES[(split, "y")]:
                        yp = base / (yn + ext)
                        if yp.exists():
                            return _read_idx(xp), _read_idx(yp)
    return None


def _try_npz(dirs, names, split) -> Optional[Arrays]:
    for d in dirs:
        for name in names:
            p = d / name
            if p.exists():
                with np.load(p, allow_pickle=False) as z:
                    if split == "train":
                        return z["x_train"], z["y_train"]
                    return z["x_test"], z["y_test"]
    return None


# --------------------------------------------------------------- synthetic --
def synthetic_images(
    n: int,
    shape: Tuple[int, ...],
    num_classes: int,
    seed: int,
    *,
    template_seed: Optional[int] = None,
) -> Arrays:
    """Learnable synthetic data: one smooth random template per class plus
    pixel noise. A small CNN separates these easily (>98% acc), which is what
    the accuracy-convergence tests need; deterministic in `seed`.

    ``template_seed`` defaults to ``seed``; train/test splits of one dataset
    must share it (same class templates) while drawing different noise."""
    rng = np.random.default_rng(seed)
    trng = np.random.default_rng(seed if template_seed is None else template_seed)
    # Templates are generated at reduced spatial resolution and upsampled
    # (nearest-neighbor): at ImageNet scale (1000 classes x 224x224x3) full-
    # resolution templates plus smoothing temporaries would peak at multiple
    # GB; 32x32 templates cost ~12MB and carry the same class signal.
    h, w = shape[0], shape[1]
    hs, ws = min(h, 32), min(w, 32)
    small = (num_classes, hs, ws) + tuple(shape[2:])
    templates = trng.uniform(0.0, 255.0, size=small).astype(np.float32)
    # Smooth the templates so convolutions have local structure to find, then
    # restore full contrast (smoothing alone collapses everything toward 127,
    # drowning the class signal in the pixel noise).
    for _ in range(2):
        templates = (
            templates
            + np.roll(templates, 1, axis=1)
            + np.roll(templates, -1, axis=1)
            + np.roll(templates, 1, axis=2)
            + np.roll(templates, -1, axis=2)
        ) / 5.0
    flat = templates.reshape(num_classes, -1)
    lo = flat.min(axis=1)[:, None]
    hi = flat.max(axis=1)[:, None]
    templates = ((flat - lo) / np.maximum(hi - lo, 1e-6) * 255.0).reshape(
        templates.shape
    )
    row_idx = (np.arange(h) * hs) // h  # nearest-neighbor upsample indices
    col_idx = (np.arange(w) * ws) // w
    y = rng.integers(0, num_classes, size=n)
    # Materialize samples in chunks, upsampling after the label lookup:
    # whole-set template lookup + noise would hold two full float32 copies
    # of the dataset (and upsampling all class templates first would cost
    # num_classes x full-res).
    x = np.empty((n,) + tuple(shape), np.uint8)
    # Budget ~128MB of float32 temporaries per chunk: each iteration holds
    # ~3 float32 copies of the chunk (upsampled templates, noise draw, sum).
    row_bytes = max(int(np.prod(shape)), 1) * 4 * 3
    chunk = max(1, min(n, (1 << 27) // row_bytes))
    for i in range(0, n, chunk):
        yi = y[i : i + chunk]
        t = templates[yi]
        if (hs, ws) != (h, w):
            t = t[:, row_idx][:, :, col_idx]
        noisy = t + 25.0 * rng.standard_normal(
            (len(yi),) + tuple(shape), dtype=np.float32
        )
        x[i : i + chunk] = np.clip(noisy, 0, 255).astype(np.uint8)
    return x, y.astype(np.int32)


def _synthetic_split(split, shape, num_classes, train_n, test_n, base_seed):
    # Same templates for both splits (template_seed), different noise draws.
    if split == "train":
        return synthetic_images(train_n, shape, num_classes, base_seed, template_seed=base_seed)
    return synthetic_images(test_n, shape, num_classes, base_seed + 1, template_seed=base_seed)


# ----------------------------------------------------------------- loaders --
def _finalize(x: np.ndarray, y: np.ndarray, normalize: bool, channels: int) -> Arrays:
    if x.ndim == 3:  # (N, H, W) -> NHWC, the reference's array_reshape
        x = x[..., None]
    if x.shape[-1] != channels:
        raise ValueError(
            f"Dataset has {x.shape[-1]} channels, expected {channels} "
            "(corrupt or mislabeled cache file?)"
        )
    if normalize:
        x = x.astype(np.float32) / 255.0  # README.md:56, 290
    return x, y.astype(np.int32)


_MNIST_MIRRORS = (
    # Public mirrors of the canonical IDX files, most reliable first.
    "https://storage.googleapis.com/cvdf-datasets/mnist/",
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
)
_MNIST_FILES = (
    "train-images-idx3-ubyte.gz",
    "train-labels-idx1-ubyte.gz",
    "t10k-images-idx3-ubyte.gz",
    "t10k-labels-idx1-ubyte.gz",
)
_MNIST_SHAPES = {
    "train-images-idx3-ubyte.gz": (60000, 28, 28),
    "train-labels-idx1-ubyte.gz": (60000,),
    "t10k-images-idx3-ubyte.gz": (10000, 28, 28),
    "t10k-labels-idx1-ubyte.gz": (10000,),
}
# Canonical MD5 digests of the four gzipped IDX files (the widely-published
# values, e.g. torchvision.datasets.MNIST pins these same constants). A
# mirror that serves different bytes — truncated, altered, or substituted —
# is rejected before anything reaches the cache. ``DTPU_MNIST_NO_CHECKSUM=1``
# disables the pin (escape hatch in case a future canonical re-encoding
# changes the compressed bytes while the payload stays valid).
_MNIST_MD5 = {
    "train-images-idx3-ubyte.gz": "f68b3c2dcbeaaa9fbdd348bbdeb94873",
    "train-labels-idx1-ubyte.gz": "d53e105ee54ea40749a09fcbcd1e9432",
    "t10k-images-idx3-ubyte.gz": "9fb629c4189551a2d022fa330f9573f3",
    "t10k-labels-idx1-ubyte.gz": "ec29112dd5afa0611ce80d1b7f02629c",
}
# Hard cap on bytes read per file: the largest real file (train images) is
# ~9.9MB compressed; a hostile or broken mirror can't exhaust host memory.
_MNIST_MAX_BYTES = 12 * 1024 * 1024


def fetch_mnist(dest_dir: Optional[str] = None,
                timeout: float = 20.0) -> Optional[Path]:
    """Network-guarded fetch of the real MNIST IDX files into the cache.

    Tries each public mirror with a hard per-request timeout, validates
    every file's IDX magic and shape before committing it (tmp-then-rename,
    so a partial download never poisons the cache), and returns the cache
    directory — or None on ANY failure (no network egress, bad mirror,
    corrupt payload). Never raises: hermetic environments fall through to
    the synthetic stand-in, which callers report via their ``data`` field
    (bench.bench_convergence). Already-complete caches return immediately.
    """
    import socket
    import urllib.parse
    import urllib.request

    dest = (Path(dest_dir) if dest_dir
            else Path.home() / ".cache" / "distributed_tpu" / "mnist")
    if all((dest / f).exists() for f in _MNIST_FILES):
        return dest
    try:
        dest.mkdir(parents=True, exist_ok=True)
    except OSError:
        return None
    # Cheap egress probe first: a firewall that silently DROPs packets would
    # otherwise stall every urlopen for the full timeout (2 mirrors x 4
    # files). The probes run in DAEMON threads with a hard join deadline:
    # socket timeouts do NOT bound the DNS lookup inside create_connection
    # (a blackholed resolver can block getaddrinfo for the system resolver
    # timeout), and daemon threads — unlike ThreadPoolExecutor workers —
    # are not joined at interpreter exit, so a stuck probe can't stall
    # process shutdown either.
    import threading

    results = {}

    def _probe(mirror):
        host = urllib.parse.urlparse(mirror).hostname
        port = 443 if mirror.startswith("https") else 80
        try:
            socket.create_connection((host, port), timeout=3.0).close()
            results[mirror] = True
        except OSError:
            results[mirror] = False

    threads = [
        # Deliberately UNNAMED: a probe stuck in the system resolver is
        # abandoned past the join deadline below, and the conftest leak
        # checker polices dtpu-* names — an abandonable thread must stay
        # outside that contract.  # dtpu-lint: allow[thread-hygiene]
        threading.Thread(target=_probe, args=(m,), daemon=True)
        for m in _MNIST_MIRRORS
    ]
    deadline = 4.0
    import time as _time

    t0 = _time.monotonic()
    for th in threads:
        th.start()
    for th in threads:
        th.join(max(0.0, deadline - (_time.monotonic() - t0)))
    # Mirror-preference order preserved: _MNIST_MIRRORS is most reliable
    # first, and the download loop tries `reachable` in order.
    reachable = [m for m in _MNIST_MIRRORS if results.get(m)]
    if not reachable:
        return None
    for fname in _MNIST_FILES:
        path = dest / fname
        if path.exists():
            continue
        check = os.environ.get("DTPU_MNIST_NO_CHECKSUM", "0") in ("", "0")
        payload = None
        for mirror in reachable:
            try:
                with urllib.request.urlopen(
                    mirror + fname, timeout=timeout
                ) as r:
                    # Bounded read: request one byte past the cap so an
                    # oversized body is detectable without buffering it.
                    payload = r.read(_MNIST_MAX_BYTES + 1)
                if len(payload) > _MNIST_MAX_BYTES:
                    payload = None
                    continue
                if check:
                    import hashlib

                    # A corrupt/tampered mirror is a per-mirror failure —
                    # fall through to the next one, like the size cap.
                    if hashlib.md5(payload).hexdigest() != _MNIST_MD5[fname]:
                        payload = None
                        continue
                break
            except Exception:
                continue
        if payload is None:
            return None
        # Per-process-unique temp name (concurrent fetches must not share a
        # partial file) with the .gz suffix kept so _read_idx's gzip
        # detection applies during validation.
        tmp = path.with_name(f"part-{os.getpid()}-{fname}")
        try:
            tmp.write_bytes(payload)
            arr = _read_idx(tmp)  # validates gzip + IDX magic + dtype
            if arr.shape != _MNIST_SHAPES[fname]:
                raise ValueError(f"{fname}: unexpected shape {arr.shape}")
            os.replace(tmp, path)
        except Exception:
            tmp.unlink(missing_ok=True)
            return None
    return dest


def load_mnist(
    split: str = "train",
    *,
    normalize: bool = True,
    data_dir: Optional[str] = None,
    synthetic_ok: bool = True,
    force_synthetic: bool = False,
    synthetic_train_n: int = 60000,
    synthetic_test_n: int = 10000,
) -> Arrays:
    # force_synthetic exists so a caller that needs BOTH splits from the
    # same source (e.g. the convergence bench) can't end up training on a
    # cached real split and evaluating on a synthetic one when only one
    # split file is present on the machine.
    got = None
    if not force_synthetic:
        dirs = _search_dirs(data_dir)
        got = _try_npz(dirs, ["mnist.npz"], split) or _try_idx(
            dirs, ["mnist", "MNIST/raw", ""], split
        )
        if got is None and not synthetic_ok:
            raise FileNotFoundError(
                "MNIST not found in " + ", ".join(map(str, dirs)) + " and synthetic_ok=False"
            )
    if got is None:
        got = _synthetic_split(split, (28, 28), 10, synthetic_train_n, synthetic_test_n, 1234)
    return _finalize(*got, normalize=normalize, channels=1)


def load_digits_real(
    split: str = "train",
    *,
    normalize: bool = True,
    image_size: int = 28,
    test_fraction: float = 0.2,
    seed: int = 0,
) -> Arrays:
    """Real handwritten digits from scikit-learn's bundled UCI ML set.

    1,797 genuine 8x8 grayscale scans (sklearn ships them offline — no
    network needed), bilinearly upsampled to ``image_size`` and rescaled to
    0-255 so the reference's MNIST CNN input contract
    (/root/reference/README.md:53-56) applies unchanged. Split is a
    deterministic stratified holdout (same ``seed`` => same partition on
    every machine), so train/test never leak into each other.

    This is the real-data fallback for the convergence benchmark on
    machines where the MNIST IDX files are absent and there is no network
    egress: small, but every pixel was drawn by a human hand.
    """
    try:
        from sklearn.datasets import load_digits as _sk_load_digits
    except ImportError as e:  # pragma: no cover - sklearn is baked in here
        raise FileNotFoundError(
            "scikit-learn (which bundles the real digits set) is not "
            "installed"
        ) from e
    bunch = _sk_load_digits()
    imgs = bunch.images.astype(np.float32) * (255.0 / 16.0)
    labels = bunch.target.astype(np.int32)
    rng = np.random.default_rng(seed)
    train_idx, test_idx = [], []
    for c in range(10):
        idx = np.where(labels == c)[0]
        rng.shuffle(idx)
        k = int(round(len(idx) * test_fraction))
        test_idx.append(idx[:k])
        train_idx.append(idx[k:])
    pick = np.concatenate(train_idx if split == "train" else test_idx)
    rng.shuffle(pick)
    imgs, labels = imgs[pick], labels[pick]
    if image_size != imgs.shape[1]:
        try:
            from scipy.ndimage import zoom
            scale = image_size / imgs.shape[1]
            imgs = zoom(imgs, (1, scale, scale), order=1)
        except ImportError:  # nearest-neighbor fallback, no scipy
            src = (np.arange(image_size) * imgs.shape[1]) // image_size
            imgs = imgs[:, src][:, :, src]
    x = np.clip(imgs, 0, 255).astype(np.uint8)
    return _finalize(x, labels, normalize=normalize, channels=1)


def load_fashion_mnist(split: str = "train", **kw) -> Arrays:
    dirs = _search_dirs(kw.pop("data_dir", None))
    got = _try_npz(dirs, ["fashion-mnist.npz", "fashion_mnist.npz"], split) or _try_idx(
        dirs, ["fashion-mnist", "fashion_mnist", "FashionMNIST/raw"], split
    )
    if got is None:
        if not kw.pop("synthetic_ok", True):
            raise FileNotFoundError("Fashion-MNIST not found")
        got = _synthetic_split(split, (28, 28), 10, 60000, 10000, 5678)
    return _finalize(*got, normalize=kw.pop("normalize", True), channels=1)


def _try_cifar(dirs, split) -> Optional[Arrays]:
    for d in dirs:
        for sub in ("cifar-10-batches-py", "cifar10/cifar-10-batches-py", ""):
            base = d / sub if sub else d
            names = (
                [f"data_batch_{i}" for i in range(1, 6)]
                if split == "train"
                else ["test_batch"]
            )
            if not all((base / n).exists() for n in names):
                continue
            xs, ys = [], []
            for n in names:
                with open(base / n, "rb") as f:
                    batch = pickle.load(f, encoding="bytes")
                xs.append(
                    batch[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
                )
                ys.append(np.array(batch[b"labels"], np.uint8))
            return np.concatenate(xs), np.concatenate(ys)
    return None


def load_cifar10(
    split: str = "train",
    *,
    normalize: bool = True,
    data_dir: Optional[str] = None,
    synthetic_ok: bool = True,
) -> Arrays:
    dirs = _search_dirs(data_dir)
    got = _try_cifar(dirs, split)
    if got is None:
        if not synthetic_ok:
            raise FileNotFoundError("CIFAR-10 not found")
        got = _synthetic_split(split, (32, 32, 3), 10, 50000, 10000, 91011)
    return _finalize(*got, normalize=normalize, channels=3)


def load_imagenet(
    split: str = "train",
    *,
    normalize: bool = True,
    data_dir: Optional[str] = None,
    synthetic_ok: bool = True,
    image_size: int = 224,
    num_classes: int = 1000,
    synthetic_train_n: int = 1024,
    synthetic_test_n: int = 256,
) -> Arrays:
    """ImageNet-scale loader (BASELINE.json configs[3]: ResNet-50 ImageNet
    data-parallel). Resolution order: npz cache (``imagenet.npz`` with
    x_train/y_train/x_test/y_test) else deterministic synthetic images at
    ``image_size``. Synthetic defaults are intentionally small — this backs
    input-pipeline/bench tests, not a real ImageNet epoch."""
    dirs = _search_dirs(data_dir)
    got = _try_npz(dirs, ["imagenet.npz", f"imagenet{image_size}.npz"], split)
    if got is None:
        if not synthetic_ok:
            raise FileNotFoundError(
                "ImageNet not found in " + ", ".join(map(str, dirs))
            )
        got = _synthetic_split(
            split, (image_size, image_size, 3), num_classes,
            synthetic_train_n, synthetic_test_n, 314159,
        )
    return _finalize(*got, normalize=normalize, channels=3)


_LOADERS = {
    "mnist": load_mnist,
    "fashion_mnist": load_fashion_mnist,
    "cifar10": load_cifar10,
    "imagenet": load_imagenet,
}


def load(name: str, split: str = "train", **kw) -> Arrays:
    try:
        loader = _LOADERS[name]
    except KeyError:
        raise ValueError(
            f"Unknown dataset {name!r}; known: {sorted(_LOADERS)}"
        ) from None
    # Loader call outside the try: its own KeyErrors (e.g. a malformed npz
    # cache missing x_test) must surface as themselves, not "unknown dataset".
    return loader(split, **kw)
