"""File-backed dataset source: a directory of npy shards, memory-mapped.

The reference feeds whole datasets from host memory
(/root/reference/README.md:369-373) and so did this framework's Pipeline —
fine for MNIST, impossible for ImageNet (~190 GB of raw 224^2 uint8). A
``FileSource`` presents a directory of ``shard-NNNNN-x.npy`` files as one
logical (N, ...) uint8 array without loading it: each shard is an
``np.memmap``, and both the C++ prefetcher (span pointers, see
native/pipeline.cc) and the Python fallback gather rows straight from the
mapped pages, so the OS pages the working set in and out on demand.
Labels (``shard-NNNNN-y.npy``) are tiny (4 bytes/row) and load fully into
RAM as one int32 array.

Layout written by :func:`write_shards`::

    dir/shard-00000-x.npy   # uint8 (rows_i, ...row_shape)
    dir/shard-00000-y.npy   # int   (rows_i,)          [optional]
    dir/shard-00001-x.npy
    ...

Determinism: a ``Pipeline`` over a FileSource emits the exact stream the
in-memory pipeline would for the concatenated array (same seed/pass/step
permutations — the tests assert bit-equality), so switching a recipe to
sharded files changes nothing about training order.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["FileSource", "write_shards"]

_X_RE = re.compile(r"^shard-(\d+)-x\.npy$")


class FileSource:
    """Memory-mapped view over a shard directory (see module docstring)."""

    def __init__(self, directory):
        self.directory = Path(directory)
        if not self.directory.is_dir():
            raise FileNotFoundError(f"shard directory not found: {directory}")
        # Numeric order, not lexicographic: 'shard-10' must follow
        # 'shard-2', and unpadded/overflowing indices must not reorder rows.
        xs = sorted(
            (p for p in self.directory.iterdir() if _X_RE.match(p.name)),
            key=lambda p: int(_X_RE.match(p.name).group(1)),
        )
        if not xs:
            raise FileNotFoundError(
                f"no shard-*-x.npy files in {self.directory}"
            )
        self.x_shards = [np.load(p, mmap_mode="r") for p in xs]
        shape0 = self.x_shards[0].shape[1:]
        for p, m in zip(xs, self.x_shards):
            if m.dtype != np.uint8:
                raise TypeError(f"{p.name}: shards must be uint8, got {m.dtype}")
            if m.shape[1:] != shape0:
                raise ValueError(
                    f"{p.name}: row shape {m.shape[1:]} != {shape0}"
                )
            if m.ndim < 1 or m.shape[0] < 1:
                raise ValueError(f"{p.name}: empty shard")
            if not m.flags["C_CONTIGUOUS"]:
                # The native gather reads raw row-major bytes from the
                # mapped base pointer; an F-order shard would silently feed
                # scrambled rows there while the Python path read it fine.
                raise ValueError(
                    f"{p.name}: shard must be C-contiguous (saved from a "
                    "row-major array)"
                )
        self.row_shape: Tuple[int, ...] = tuple(shape0)
        self.span_rows = [int(m.shape[0]) for m in self.x_shards]
        self.n = int(sum(self.span_rows))
        # Cumulative starts for row -> (shard, offset) resolution.
        self._starts = np.cumsum([0] + self.span_rows)

        ys = [p.with_name(p.name.replace("-x.npy", "-y.npy")) for p in xs]
        have = [p.exists() for p in ys]
        if any(have) and not all(have):
            missing = [p.name for p, h in zip(ys, have) if not h]
            raise FileNotFoundError(
                f"label shards are partial; missing {missing}"
            )
        if all(have):
            parts = [np.load(p) for p in ys]
            for p, arr, rows in zip(ys, parts, self.span_rows):
                if arr.shape != (rows,):
                    raise ValueError(
                        f"{p.name}: labels shape {arr.shape} != ({rows},)"
                    )
                if not np.issubdtype(arr.dtype, np.integer):
                    # Same strictness as the x-shard checks: a float label
                    # file would otherwise be silently truncated to int32.
                    raise TypeError(
                        f"{p.name}: labels must be integer, got {arr.dtype}"
                    )
            self.y: Optional[np.ndarray] = np.concatenate(parts).astype(
                np.int32
            )
        else:
            self.y = None

    def gather(self, idx: np.ndarray) -> np.ndarray:
        """Rows ``idx`` (global indices) as one uint8 array — reads only the
        touched pages of the mapped shards. Vectorized: indices are grouped
        by shard and each group is one fancy-index read (a per-row Python
        loop dominated many-shard reads), emitting rows at their original
        positions — bit-identical to a row-at-a-time gather."""
        idx = np.asarray(idx, np.int64)
        out = np.empty((len(idx),) + self.row_shape, np.uint8)
        span = np.searchsorted(self._starts, idx, side="right") - 1
        for s in np.unique(span):
            sel = span == s
            out[sel] = self.x_shards[s][idx[sel] - self._starts[s]]
        return out

    def __len__(self) -> int:
        return self.n


def write_shards(
    directory,
    x: np.ndarray,
    y: Optional[np.ndarray] = None,
    *,
    rows_per_shard: int = 4096,
) -> Path:
    """Write (x, y) into the FileSource shard layout. ``x`` must be uint8;
    existing shards in the directory are an error (no silent mixing)."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    if any(_X_RE.match(p.name) for p in directory.iterdir()):
        raise FileExistsError(f"{directory} already contains shards")
    x = np.ascontiguousarray(x)
    if x.dtype != np.uint8:
        raise TypeError(f"x must be uint8, got {x.dtype}")
    if y is not None and len(y) != len(x):
        raise ValueError("x and y lengths differ")
    if rows_per_shard < 1:
        raise ValueError("rows_per_shard must be >= 1")
    for si, start in enumerate(range(0, len(x), rows_per_shard)):
        stop = min(start + rows_per_shard, len(x))
        np.save(directory / f"shard-{si:05d}-x.npy", x[start:stop])
        if y is not None:
            np.save(
                directory / f"shard-{si:05d}-y.npy",
                np.asarray(y[start:stop], np.int32),
            )
    return directory
