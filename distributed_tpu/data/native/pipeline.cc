// Native input pipeline: threaded shuffle + gather + normalize + prefetch.
//
// Role in the framework: the TPU-native analogue of the native machinery the
// reference system leans on out-of-repo (its input feeding and collective
// path run in TF's C++ core; see SURVEY.md §2b). Host-side batch
// preparation — permuting indices, gathering rows, uint8->float32 /255
// normalization — is the part of the hot loop that is NOT XLA's job, and in
// Python it stalls the accelerator between steps at ImageNet scale. Here it
// runs in C++ worker threads that keep a bounded queue of ready batches
// ahead of the consumer, so the host overlaps batch prep with device
// execution.
//
// Exposed as a plain C ABI (no pybind11 in this image) and driven from
// Python via ctypes; see ../pipeline.py, which also carries a pure-Python
// fallback with the same semantics.
//
// Determinism: batch b of pass p depends only on (seed, p, b), so two
// pipelines constructed with the same arguments emit identical streams
// regardless of thread count or timing. With external_perms (the default
// from Python since the shuffle unification) the per-pass permutation is
// SUPPLIED by the driver via dtpu_pipeline_supply_perm — one numpy
// computation shared with the Python fallback, so native and Python emit
// bit-identical streams; workers block until the pass they need has been
// supplied (the driver hands over every reachable pass before each next()
// call, so they never wait in steady state). Without it (legacy mode,
// DTPU_NATIVE_LEGACY_SHUFFLE=1), a splitmix64-seeded Fisher-Yates
// permutation is generated here, as before the unification.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// splitmix64: tiny, seedable, high-quality enough for shuffling.
struct SplitMix64 {
  uint64_t s;
  explicit SplitMix64(uint64_t seed) : s(seed) {}
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
  // Unbiased bounded draw (rejection sampling).
  uint64_t below(uint64_t bound) {
    uint64_t threshold = (~bound + 1) % bound;  // 2^64 mod bound
    for (;;) {
      uint64_t r = next();
      if (r >= threshold) return r % bound;
    }
  }
};

struct Slot {
  std::vector<float> x;
  std::vector<int32_t> y;
  int64_t step = -1;  // which global step this slot holds; -1 = empty
  bool filled = false;
};

}  // namespace

struct DtpuPipeline {
  // Row storage as spans: one span for an in-memory array, several for a
  // file-backed source (each span is one memory-mapped shard file). Rows
  // are resolved span-first via binary search on cumulative starts, so the
  // gather path is identical for both; the OS pages mapped shards in and
  // out on demand, which is what makes larger-than-RAM datasets feedable.
  std::vector<const uint8_t*> xs;
  std::vector<int64_t> span_starts;  // size == xs.size() + 1; last == n
  const int32_t* y;
  int64_t n, row, batch, steps_per_pass;
  // Per-host sharding: this producer prepares only rows
  // [shard_index * shard_rows, (shard_index + 1) * shard_rows) of each
  // global batch; the step/pass/permutation sequence is identical on every
  // host (same seed), so the host slices assemble into the exact global
  // batch an unsharded pipeline would emit.
  int64_t shard_index, shard_count, shard_rows;
  bool shuffle;
  uint64_t seed;
  float scale;
  int depth;

  std::mutex mu;
  std::condition_variable cv_produce, cv_consume;
  std::vector<Slot> slots;
  std::atomic<int64_t> next_step{0};  // claimed by producers
  std::atomic<int64_t> consumed{0};   // next step the consumer will take
  bool stop = false;

  // Per-pass permutations (guarded by perm_mu): generated lazily here
  // (legacy mode) or supplied by the driver (external_perms). Only passes
  // that can still be in a producer's fill window are retained; older ones
  // are pruned so memory stays bounded over arbitrarily long runs (each
  // pass's permutation is n * 8 bytes — ~10MB at ImageNet scale).
  // shared_ptr keeps a pruned-but-in-use permutation alive for its reader.
  std::mutex perm_mu;
  std::condition_variable cv_perm;
  std::map<int64_t, std::shared_ptr<std::vector<int64_t>>> perms;
  bool external_perms = false;
  bool perm_stop = false;  // guarded by perm_mu; set at destroy

  std::vector<std::thread> workers;

  std::shared_ptr<std::vector<int64_t>> perm_for(int64_t pass) {
    std::unique_lock<std::mutex> lock(perm_mu);
    auto it = perms.find(pass);
    if (it == perms.end()) {
      if (external_perms) {
        // The driver supplies every reachable pass before each next()
        // call; a wait here only happens at startup or right after a
        // seek, and destroy() unblocks it via perm_stop.
        cv_perm.wait(lock, [&] { return perm_stop || perms.count(pass); });
        if (perm_stop) return nullptr;
        it = perms.find(pass);
      } else {
        auto order = std::make_shared<std::vector<int64_t>>(n);
        for (int64_t i = 0; i < n; ++i) (*order)[i] = i;
        if (shuffle) {
          // Seed mixes (seed, pass) so each pass reshuffles
          // deterministically.
          SplitMix64 rng(seed * 0x9e3779b97f4a7c15ULL + (uint64_t)pass + 1);
          for (int64_t i = n - 1; i > 0; --i) {
            int64_t j = (int64_t)rng.below((uint64_t)i + 1);
            std::swap((*order)[i], (*order)[j]);
          }
        }
        it = perms.emplace(pass, std::move(order)).first;
      }
    }
    std::shared_ptr<std::vector<int64_t>> result = it->second;
    // Any step still fillable is >= consumed, so passes below
    // consumed / steps_per_pass can no longer be requested.
    const int64_t min_pass = consumed.load() / steps_per_pass;
    perms.erase(perms.begin(), perms.lower_bound(min_pass));
    return result;
  }

  void supply_perm(int64_t pass, const int64_t* perm) {
    auto order = std::make_shared<std::vector<int64_t>>(perm, perm + n);
    {
      std::lock_guard<std::mutex> lock(perm_mu);
      perms.emplace(pass, std::move(order));
    }
    cv_perm.notify_all();
  }

  bool fill(Slot& slot, int64_t step) {
    int64_t pass = step / steps_per_pass;
    int64_t within = step % steps_per_pass;
    // Hold the shared_ptr for the whole fill: pruning may drop the map entry.
    std::shared_ptr<std::vector<int64_t>> order_sp = perm_for(pass);
    if (!order_sp) return false;  // stopped while waiting for the pass
    const std::vector<int64_t>& order = *order_sp;
    const int64_t start = within * batch + shard_index * shard_rows;
    slot.x.resize((size_t)(shard_rows * row));
    slot.y.resize((size_t)shard_rows);
    for (int64_t b = 0; b < shard_rows; ++b) {
      const int64_t src = order[start + b];
      // Span holding row `src`: last start <= src.
      const size_t span =
          (size_t)(std::upper_bound(span_starts.begin(), span_starts.end(),
                                    src) -
                   span_starts.begin()) -
          1;
      const uint8_t* in = xs[span] + (src - span_starts[span]) * row;
      float* out = slot.x.data() + b * row;
      for (int64_t e = 0; e < row; ++e) out[e] = (float)in[e] * scale;
      slot.y[(size_t)b] = y ? y[src] : 0;
    }
    // slot.step is published under mu in worker(): the consumer's wait
    // predicate reads it, and an unlocked write here would race.
    return true;
  }

  void worker() {
    for (;;) {
      const int64_t step = next_step.fetch_add(1);
      const int idx = (int)(step % depth);
      Slot& slot = slots[(size_t)idx];
      // Wait until the consumer has drained the previous occupant of this
      // ring slot (step - depth), then fill and publish.
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_produce.wait(lock, [&] { return stop || consumed + depth > step; });
        if (stop) return;
      }
      if (!fill(slot, step)) return;  // destroyed mid-wait for a perm
      {
        std::lock_guard<std::mutex> lock(mu);
        slot.step = step;
        slot.filled = true;
      }
      cv_consume.notify_all();
    }
  }
};

extern "C" {

// Spans form: `xs` is `n_spans` base pointers, `span_rows` their row
// counts (summing to n). The single-array entry point below wraps this
// with one span; a file-backed source passes one span per mapped shard.
DtpuPipeline* dtpu_pipeline_create_spans(
    const uint8_t* const* xs, const int64_t* span_rows, int64_t n_spans,
    const int32_t* y, int64_t n, int64_t row_elems, int64_t batch,
    int shuffle, uint64_t seed, int depth, int threads, float scale,
    int64_t start_step, int64_t shard_index, int64_t shard_count,
    int external_perms) {
  if (n <= 0 || batch <= 0 || batch > n || row_elems <= 0) return nullptr;
  if (n_spans < 1) return nullptr;
  if (shard_count < 1 || shard_index < 0 || shard_index >= shard_count ||
      batch % shard_count != 0) {
    return nullptr;
  }
  int64_t total = 0;
  for (int64_t i = 0; i < n_spans; ++i) {
    if (span_rows[i] <= 0 || xs[i] == nullptr) return nullptr;
    total += span_rows[i];
  }
  if (total != n) return nullptr;
  auto* p = new DtpuPipeline();
  p->xs.assign(xs, xs + n_spans);
  p->span_starts.resize((size_t)n_spans + 1);
  p->span_starts[0] = 0;
  for (int64_t i = 0; i < n_spans; ++i) {
    p->span_starts[(size_t)i + 1] = p->span_starts[(size_t)i] + span_rows[i];
  }
  p->y = y;
  p->n = n;
  p->row = row_elems;
  p->batch = batch;
  p->shard_index = shard_index;
  p->shard_count = shard_count;
  p->shard_rows = batch / shard_count;
  p->steps_per_pass = n / batch;
  p->shuffle = shuffle != 0;
  p->seed = seed;
  p->scale = scale;
  p->external_perms = external_perms != 0;
  p->depth = depth < 1 ? 1 : depth;
  // Resume support: start emitting at an arbitrary global step (O(1) seek —
  // step order depends only on (seed, pass, within), not on history).
  if (start_step < 0) start_step = 0;
  p->next_step.store(start_step);
  p->consumed.store(start_step);
  p->slots.resize((size_t)p->depth);
  int nthreads = threads < 1 ? 1 : threads;
  if (nthreads > p->depth) nthreads = p->depth;
  for (int i = 0; i < nthreads; ++i) {
    p->workers.emplace_back([p] { p->worker(); });
  }
  return p;
}

DtpuPipeline* dtpu_pipeline_create(const uint8_t* x, const int32_t* y,
                                   int64_t n, int64_t row_elems,
                                   int64_t batch, int shuffle, uint64_t seed,
                                   int depth, int threads, float scale,
                                   int64_t start_step, int64_t shard_index,
                                   int64_t shard_count) {
  const uint8_t* xs[1] = {x};
  const int64_t rows[1] = {n};
  return dtpu_pipeline_create_spans(xs, rows, 1, y, n, row_elems, batch,
                                    shuffle, seed, depth, threads, scale,
                                    start_step, shard_index, shard_count,
                                    /*external_perms=*/0);
}

// Hand the pipeline the permutation for one pass (n int64 row indices,
// copied). Only meaningful with external_perms; producers needing a pass
// not yet supplied block until it arrives.
void dtpu_pipeline_supply_perm(DtpuPipeline* p, int64_t pass,
                               const int64_t* perm) {
  if (!p || !perm) return;
  p->supply_perm(pass, perm);
}

// Copies the next batch (in deterministic step order) into caller buffers of
// shape [batch / shard_count, row_elems] float32 and [batch / shard_count]
// int32. Returns the 0-based step index, or -1 if the pipeline is stopped.
int64_t dtpu_pipeline_next(DtpuPipeline* p, float* x_out, int32_t* y_out) {
  Slot* slot;
  int64_t step;
  {
    std::unique_lock<std::mutex> lock(p->mu);
    step = p->consumed;
    slot = &p->slots[(size_t)(step % p->depth)];
    p->cv_consume.wait(lock, [&] {
      return p->stop || (slot->filled && slot->step == step);
    });
    if (p->stop) return -1;
  }
  std::memcpy(x_out, slot->x.data(),
              sizeof(float) * (size_t)(p->shard_rows * p->row));
  if (y_out) {
    std::memcpy(y_out, slot->y.data(),
                sizeof(int32_t) * (size_t)p->shard_rows);
  }
  {
    std::lock_guard<std::mutex> lock(p->mu);
    slot->filled = false;
    slot->step = -1;
    p->consumed = step + 1;
  }
  p->cv_produce.notify_all();
  return step;
}

int64_t dtpu_pipeline_steps_per_pass(DtpuPipeline* p) {
  return p->steps_per_pass;
}

void dtpu_pipeline_destroy(DtpuPipeline* p) {
  if (!p) return;
  {
    std::lock_guard<std::mutex> lock(p->mu);
    p->stop = true;
  }
  {
    // Unblock workers parked in perm_for waiting for an external pass.
    std::lock_guard<std::mutex> lock(p->perm_mu);
    p->perm_stop = true;
  }
  p->cv_produce.notify_all();
  p->cv_consume.notify_all();
  p->cv_perm.notify_all();
  for (std::thread& t : p->workers) t.join();
  delete p;
}

}  // extern "C"
