"""Input pipeline: prefetched shuffle/gather/normalize batches.

Two implementations behind one API:

- **Native (C++)**: ``native/pipeline.cc`` compiled to a shared library and
  driven via ctypes. Worker threads keep a bounded ring of ready float32
  batches ahead of the consumer, overlapping host batch prep with device
  execution — the host-side analogue of the native machinery the reference
  gets from TF's C++ core (SURVEY.md §2b), which is what keeps a TPU fed at
  ImageNet scale.
- **Pure Python fallback**: same semantics (per-pass reshuffle, steps-per-
  pass, /255 normalization), used when no C++ toolchain is available.

Batch streams are deterministic in (seed, pass, step) *within* an
implementation; the native and Python shuffles use different RNGs, so pick
one implementation per experiment when bit-reproducibility matters.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from ..utils import logging as dlog

_NATIVE_DIR = Path(__file__).parent / "native"
_LIB_NAME = "libdtpu_pipeline.so"

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _load_native() -> Optional[ctypes.CDLL]:
    """Load (building on demand) the native pipeline library; None if
    unavailable. Gated off entirely by DTPU_NO_NATIVE=1."""
    global _lib, _lib_tried
    with _lib_lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        if os.environ.get("DTPU_NO_NATIVE") == "1":
            return None
        so = _NATIVE_DIR / _LIB_NAME
        src = _NATIVE_DIR / "pipeline.cc"
        try:
            if not so.exists() or (
                src.exists() and src.stat().st_mtime > so.stat().st_mtime
            ):
                # Inter-process file lock: gang workers on one host may all
                # hit the missing-.so case at once, and make writes the .so
                # in place — an unsynchronized peer could dlopen a half-
                # written file and silently fall back to the Python RNG,
                # diverging its data order from the rest of the gang.
                import fcntl

                lock_path = _NATIVE_DIR / ".build.lock"
                with open(lock_path, "w") as lock_f:
                    fcntl.flock(lock_f, fcntl.LOCK_EX)
                    try:
                        if not so.exists() or (
                            src.exists()
                            and src.stat().st_mtime > so.stat().st_mtime
                        ):
                            subprocess.run(
                                ["make", "-C", str(_NATIVE_DIR)],
                                check=True,
                                capture_output=True,
                                timeout=120,
                            )
                    finally:
                        fcntl.flock(lock_f, fcntl.LOCK_UN)
            lib = ctypes.CDLL(str(so))
        except (OSError, subprocess.SubprocessError) as e:
            dlog.warning(f"native pipeline unavailable ({e}); using Python")
            return None
        lib.dtpu_pipeline_create_spans.restype = ctypes.c_void_p
        lib.dtpu_pipeline_create_spans.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),  # xs (span base pointers)
            ctypes.POINTER(ctypes.c_int64),   # span_rows
            ctypes.c_int64,   # n_spans
            ctypes.c_void_p,  # y
            ctypes.c_int64,   # n
            ctypes.c_int64,   # row_elems
            ctypes.c_int64,   # batch
            ctypes.c_int,     # shuffle
            ctypes.c_uint64,  # seed
            ctypes.c_int,     # depth
            ctypes.c_int,     # threads
            ctypes.c_float,   # scale
            ctypes.c_int64,   # start_step
            ctypes.c_int64,   # shard_index
            ctypes.c_int64,   # shard_count
        ]
        lib.dtpu_pipeline_next.restype = ctypes.c_int64
        lib.dtpu_pipeline_next.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.dtpu_pipeline_steps_per_pass.restype = ctypes.c_int64
        lib.dtpu_pipeline_steps_per_pass.argtypes = [ctypes.c_void_p]
        lib.dtpu_pipeline_destroy.restype = None
        lib.dtpu_pipeline_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load_native() is not None


class Pipeline:
    """Iterator of ``(x_float32, y_int32)`` batches with background prefetch.

    Args:
      x: uint8 array (N, ...), e.g. raw image bytes.
      y: int labels (N,) or None.
      batch_size: rows per emitted batch.
      shuffle: reshuffle every pass (epoch) deterministically from ``seed``.
      scale: multiplier applied during uint8->float32 (default 1/255, the
        reference's normalization, /root/reference/README.md:56).
      prefetch: ring depth — how many batches may be ready ahead.
      num_threads: native producer threads.
      use_native: force (True/False) or auto (None).
      shard: optional ``(index, count)`` per-host input sharding: this
        pipeline prepares only rows ``[index * b/count, (index+1) * b/count)``
        of each global batch (``batch_size`` stays the GLOBAL batch). Every
        host runs the same (seed, pass, step) sequence, so the host slices
        assemble into exactly the batch an unsharded pipeline would emit —
        global-batch semantics unchanged, per-host memory and prep work
        divided by ``count`` (SURVEY.md §7 hard parts; contrast the
        reference's full-dataset-everywhere feeding,
        /root/reference/README.md:369-373). ``shard="auto"`` derives
        ``(jax.process_index(), jax.process_count())`` from the live
        runtime — the right spelling for elastic gangs, where the world
        size differs between relaunches (see :meth:`reshard`).

    The stream is infinite (passes repeat, reshuffled); ``steps_per_pass``
    tells one epoch's length, matching ``fit(steps_per_epoch=...)``.
    """

    def __init__(
        self,
        x,
        y: Optional[np.ndarray] = None,
        batch_size: int = 32,
        *,
        shuffle: bool = True,
        seed: int = 0,
        scale: float = 1.0 / 255.0,
        prefetch: int = 4,
        num_threads: int = 2,
        use_native: Optional[bool] = None,
        shard: Optional[Tuple[int, int]] = None,
    ):
        from .filesource import FileSource

        # Teardown-critical fields FIRST: __del__ runs on instances whose
        # __init__ raised partway (bad batch_size, a failed native handle),
        # and close() must find a consistent shape to tear down.
        self._lib = None
        self._handle = None
        self._closed = False
        self._py_step = 0
        self.steps_emitted = 0  # lets fit() fast-forward on resume

        # x is either an in-memory uint8 array or a file-backed shard set
        # (FileSource, or a directory path); the file case streams through
        # memory-mapped spans and never loads the dataset into RAM.
        self._source: Optional[FileSource] = None
        if isinstance(x, (str, os.PathLike)):
            x = FileSource(x)
        if isinstance(x, FileSource):
            self._source = x
            if y is None:
                y = x.y  # labels from the shard set, if present
            n_rows = x.n
            row_shape = x.row_shape
            self._x = None
        else:
            x = np.ascontiguousarray(x)
            if x.dtype != np.uint8:
                raise TypeError(
                    f"Pipeline feeds raw uint8 data, got {x.dtype}"
                )
            self._x = x
            n_rows = x.shape[0]
            row_shape = x.shape[1:]
        if batch_size <= 0 or batch_size > n_rows:
            raise ValueError(
                f"batch_size {batch_size} invalid for {n_rows} rows"
            )
        self._y = (
            None if y is None else np.ascontiguousarray(y, dtype=np.int32)
        )
        if self._y is not None and len(self._y) != n_rows:
            raise ValueError("x and y lengths differ")
        self.batch_size = int(batch_size)
        self._row_shape = tuple(row_shape)
        self._set_shard(shard)
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        self.scale = float(scale)
        self.prefetch = max(1, int(prefetch))
        self.num_threads = max(1, int(num_threads))
        self._n = int(n_rows)
        self.steps_per_pass = self._n // self.batch_size
        self._row = int(np.prod(row_shape, dtype=np.int64))

        lib = _load_native() if use_native in (None, True) else None
        if use_native is True and lib is None:
            raise RuntimeError("Native pipeline requested but unavailable")
        self._lib = lib
        if lib is not None:
            self._handle = self._create_handle(0)

    def _set_shard(self, shard) -> None:
        """Validate + adopt a ``(index, count)`` slice of the global batch
        (None -> unsharded, "auto" -> the live process's rank/world).
        Shared by ``__init__`` and :meth:`reshard` so both agree on what a
        legal shard is; emitted shape follows (``batch_size`` stays the
        GLOBAL batch)."""
        if isinstance(shard, str):
            if shard != "auto":
                raise ValueError(
                    f"shard must be (index, count), None, or 'auto'; "
                    f"got {shard!r}"
                )
            import jax

            shard = (jax.process_index(), jax.process_count())
        if shard is None:
            shard = (0, 1)
        index, count = (int(shard[0]), int(shard[1]))
        if count < 1 or not (0 <= index < count):
            raise ValueError(f"shard index {index} not in [0, {count})")
        if self.batch_size % count:
            raise ValueError(
                f"batch_size {self.batch_size} not divisible by "
                f"shard count {count}"
            )
        self.shard = (index, count) if count > 1 else None
        self.shard_rows = self.batch_size // count
        self.batch_shape = (self.shard_rows,) + self._row_shape

    def reshard(self, shard) -> "Pipeline":
        """Adopt a new ``(index, count)`` slice of the SAME global batch
        stream at the current position — the elastic-resize primitive. The
        global sequence depends only on (seed, pass, step), so after
        ``reshard`` the next emitted batch is this shard's rows of exactly
        the global batch the old sharding would have assembled next: the
        re-formed gang's slices still concatenate into the unsharded
        stream, and the loss trajectory is preserved across the resize
        (docs/RESILIENCE.md "Elastic gangs"). ``shard="auto"`` re-derives
        ``(process_index, process_count)`` from the live runtime. O(1) —
        the native ring is recreated at the current step, nothing is
        replayed or re-prepared."""
        if self._closed:
            raise ValueError("Pipeline is closed")
        self._set_shard(shard)
        if self._handle is not None:
            # Same detach-before-recreate dance as seek(): a failed
            # recreate must not leave a handle close() would double-free.
            handle, self._handle = self._handle, None
            self._lib.dtpu_pipeline_destroy(handle)
            self._handle = self._create_handle(self.steps_emitted)
        return self

    def _create_handle(self, start_step: int):
        # One span for an in-memory array; one per memory-mapped shard for
        # a FileSource (np.memmap exposes the mapping's base address via
        # .ctypes like any ndarray — no copy).
        if self._source is not None:
            arrays = self._source.x_shards
        else:
            arrays = [self._x]
        n_spans = len(arrays)
        xs = (ctypes.c_void_p * n_spans)(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays]
        )
        rows = (ctypes.c_int64 * n_spans)(*[a.shape[0] for a in arrays])
        handle = self._lib.dtpu_pipeline_create_spans(
            ctypes.cast(xs, ctypes.POINTER(ctypes.c_void_p)),
            ctypes.cast(rows, ctypes.POINTER(ctypes.c_int64)),
            n_spans,
            None if self._y is None
            else self._y.ctypes.data_as(ctypes.c_void_p),
            self._n,
            self._row,
            self.batch_size,
            1 if self.shuffle else 0,
            self.seed,
            self.prefetch,
            self.num_threads,
            self.scale,
            start_step,
            0 if self.shard is None else self.shard[0],
            1 if self.shard is None else self.shard[1],
        )
        if not handle:
            raise RuntimeError("dtpu_pipeline_create failed")
        return handle

    def seek(self, step: int):
        """Jump to global step ``step`` in O(1): the stream position depends
        only on (seed, pass, within), so resume never replays or re-prepares
        skipped batches. Used by ``fit()`` on checkpoint-restart."""
        if self._closed:
            raise ValueError("Pipeline is closed")
        step = int(step)
        if step < 0:
            raise ValueError(f"seek target must be >= 0, got {step}")
        if self._handle is not None:
            # Detach before destroy/recreate: if _create_handle fails here,
            # close()/__del__ must not double-destroy the old handle.
            handle, self._handle = self._handle, None
            self._lib.dtpu_pipeline_destroy(handle)
            self._handle = self._create_handle(step)
        else:
            self._py_step = step
            self._perm_cache = None
        self.steps_emitted = step

    @property
    def is_native(self) -> bool:
        return self._handle is not None

    def __iter__(self):
        return self

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._closed:
            raise ValueError("Pipeline is closed")
        xb = np.empty(self.batch_shape, np.float32)
        yb = np.empty((self.shard_rows,), np.int32)
        self._fill(xb, yb)
        return xb, yb

    def _fill(self, xb: np.ndarray, yb: np.ndarray) -> None:
        """Write the next batch into caller-provided buffers (contiguous
        float32/int32 of batch_shape/(shard_rows,)) — the one batch-emit
        implementation behind __next__ and next_k."""
        if self._handle is not None:
            step = self._lib.dtpu_pipeline_next(
                self._handle,
                xb.ctypes.data_as(ctypes.c_void_p),
                yb.ctypes.data_as(ctypes.c_void_p),
            )
            if step < 0:
                raise StopIteration
            self.steps_emitted += 1
            return
        # Python fallback: identical pass/step semantics, numpy RNG shuffle.
        step = self._py_step
        self._py_step += 1
        pass_idx, within = divmod(step, self.steps_per_pass)
        cached = getattr(self, "_perm_cache", None)
        if cached is not None and cached[0] == pass_idx:
            order = cached[1]
        else:
            rng = np.random.default_rng((self.seed, pass_idx))
            order = (
                rng.permutation(self._n)
                if self.shuffle
                else np.arange(self._n)
            )
            self._perm_cache = (pass_idx, order)
        start = within * self.batch_size
        if self.shard is not None:
            start += self.shard[0] * self.shard_rows
        idx = order[start : start + self.shard_rows]
        rows = (
            self._source.gather(idx) if self._source is not None
            else self._x[idx]
        )
        xb[:] = rows.astype(np.float32) * self.scale
        if self._y is not None:
            yb[:] = self._y[idx]
        else:
            yb[:] = 0
        self.steps_emitted += 1

    def next_k(self, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """The next ``k`` batches collated into stacked arrays of shape
        ``(k,) + batch_shape`` / ``(k, shard_rows)`` — the super-batch
        ``Model.fit`` transfers once under ``steps_per_execution=K``.

        Each batch is written straight into its row of the output (the
        native ring's producer buffer, or the Python path's gather, fills
        the slice in place), so collation adds NO copy over ``k`` separate
        ``__next__`` calls — it just moves the allocation up front."""
        k = int(k)
        if k < 1:
            raise ValueError(f"next_k needs k >= 1, got {k}")
        if self._closed:
            raise ValueError("Pipeline is closed")
        xs = np.empty((k,) + self.batch_shape, np.float32)
        ys = np.empty((k, self.shard_rows), np.int32)
        for i in range(k):
            self._fill(xs[i], ys[i])
        return xs, ys

    def close(self):
        """Idempotent shutdown, safe in every degraded state: a partially
        constructed instance (``__init__`` raised before the native handle
        existed), a repeated close, and interpreter shutdown — where module
        globals (the ctypes lib, its function pointers) may already be torn
        down while native prefetch threads are still live. Every lookup is
        defensive and the destroy itself is allowed to fail silently; the
        alternative is an exception out of ``__del__`` at exit."""
        self._closed = True
        handle = getattr(self, "_handle", None)
        self._handle = None
        if handle:
            destroy = getattr(getattr(self, "_lib", None),
                              "dtpu_pipeline_destroy", None)
            if destroy is not None:
                try:
                    destroy(handle)
                except Exception:
                    pass  # shutdown-time ctypes teardown; nothing to save

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
