"""Input pipeline: prefetched shuffle/gather/decode/normalize batches.

Two implementations behind one API:

- **Native (C++)**: ``native/pipeline.cc`` compiled to a shared library and
  driven via ctypes. Worker threads keep a bounded ring of ready float32
  batches ahead of the consumer, overlapping host batch prep with device
  execution — the host-side analogue of the native machinery the reference
  gets from TF's C++ core (SURVEY.md §2b), which is what keeps a TPU fed at
  ImageNet scale.
- **Pure Python fallback**: same semantics (per-pass reshuffle, steps-per-
  pass, /255 normalization), used when no C++ toolchain is available.

Batch streams are deterministic in (seed, pass, step) ACROSS
implementations: the per-pass permutation is computed once, in numpy
(``np.random.default_rng((seed, pass))``), and handed to the native
pipeline as an index buffer — native and Python emit bit-identical
streams. ``DTPU_NATIVE_LEGACY_SHUFFLE=1`` restores the pre-unification
native order (splitmix64 Fisher-Yates, computed in C++) for experiments
pinned to old artifacts.

Record sources (``data.RecordSource``) add a third stage: host-side
**decode** of variable-length encoded records, optionally fanned across a
bounded worker pool (``decode_workers=W``) with work assigned by step
index and reassembled in order — the batch stream is bit-identical for
any ``W`` (including ``W=0``, which decodes inline).
"""

from __future__ import annotations

import ctypes
import os
import queue
import subprocess
import threading
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from ..utils import logging as dlog

_NATIVE_DIR = Path(__file__).parent / "native"
_LIB_NAME = "libdtpu_pipeline.so"

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _load_native() -> Optional[ctypes.CDLL]:
    """Load (building on demand) the native pipeline library; None if
    unavailable. Gated off entirely by DTPU_NO_NATIVE=1."""
    global _lib, _lib_tried
    with _lib_lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        if os.environ.get("DTPU_NO_NATIVE") == "1":
            return None
        so = _NATIVE_DIR / _LIB_NAME
        src = _NATIVE_DIR / "pipeline.cc"
        try:
            if not so.exists() or (
                src.exists() and src.stat().st_mtime > so.stat().st_mtime
            ):
                # Inter-process file lock: gang workers on one host may all
                # hit the missing-.so case at once, and make writes the .so
                # in place — an unsynchronized peer could dlopen a half-
                # written file and silently fall back to the Python RNG,
                # diverging its data order from the rest of the gang.
                import fcntl

                lock_path = _NATIVE_DIR / ".build.lock"
                with open(lock_path, "w") as lock_f:
                    fcntl.flock(lock_f, fcntl.LOCK_EX)
                    try:
                        if not so.exists() or (
                            src.exists()
                            and src.stat().st_mtime > so.stat().st_mtime
                        ):
                            subprocess.run(
                                ["make", "-C", str(_NATIVE_DIR)],
                                check=True,
                                capture_output=True,
                                timeout=120,
                            )
                    finally:
                        fcntl.flock(lock_f, fcntl.LOCK_UN)
            lib = ctypes.CDLL(str(so))
        except (OSError, subprocess.SubprocessError) as e:
            dlog.warning(f"native pipeline unavailable ({e}); using Python")
            return None
        lib.dtpu_pipeline_create_spans.restype = ctypes.c_void_p
        lib.dtpu_pipeline_create_spans.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),  # xs (span base pointers)
            ctypes.POINTER(ctypes.c_int64),   # span_rows
            ctypes.c_int64,   # n_spans
            ctypes.c_void_p,  # y
            ctypes.c_int64,   # n
            ctypes.c_int64,   # row_elems
            ctypes.c_int64,   # batch
            ctypes.c_int,     # shuffle
            ctypes.c_uint64,  # seed
            ctypes.c_int,     # depth
            ctypes.c_int,     # threads
            ctypes.c_float,   # scale
            ctypes.c_int64,   # start_step
            ctypes.c_int64,   # shard_index
            ctypes.c_int64,   # shard_count
            ctypes.c_int,     # external_perms
        ]
        lib.dtpu_pipeline_next.restype = ctypes.c_int64
        lib.dtpu_pipeline_next.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.dtpu_pipeline_steps_per_pass.restype = ctypes.c_int64
        lib.dtpu_pipeline_steps_per_pass.argtypes = [ctypes.c_void_p]
        lib.dtpu_pipeline_supply_perm.restype = None
        lib.dtpu_pipeline_supply_perm.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.dtpu_pipeline_destroy.restype = None
        lib.dtpu_pipeline_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load_native() is not None


_POLL_S = 0.05  # decode worker/consumer wake-up period for stop checks


class _DecodePool:
    """Bounded, order-preserving parallel decode stage.

    Work items are whole batch steps — ``(step, indices)`` — decoded by
    ``fn(indices)`` on one of ``workers`` daemon threads and reassembled
    by step key, so the consumed stream is bit-identical for ANY worker
    count: assignment order and thread timing never reach the output
    (``fn`` must be pure). The submission side (the Pipeline) bounds
    outstanding work, so results held here are bounded too.
    """

    def __init__(self, fn, workers: int):
        self._fn = fn
        self._tasks: "queue.Queue" = queue.Queue()
        self._results = {}
        self._cv = threading.Condition()
        self._error: Optional[BaseException] = None
        self._threads = [
            threading.Thread(
                target=self._run, name=f"dtpu-decode-{i}", daemon=True
            )
            for i in range(workers)
        ]
        for t in self._threads:
            t.start()

    def _run(self):
        while True:
            task = self._tasks.get()
            if task is None:  # poison pill from close()
                return
            step, idx = task
            try:
                out = self._fn(idx)
            except BaseException as e:  # surfaced to the consumer in get()
                with self._cv:
                    if self._error is None:
                        self._error = e
                    self._cv.notify_all()
                return
            with self._cv:
                self._results[step] = out
                self._cv.notify_all()

    def submit(self, step: int, idx: np.ndarray):
        self._tasks.put((int(step), idx))

    def get(self, step: int):
        """Block until step ``step``'s decode lands; re-raise any worker
        error with its original type."""
        with self._cv:
            while step not in self._results:
                if self._error is not None:
                    raise self._error
                self._cv.wait(timeout=_POLL_S)
            return self._results.pop(step)

    def close(self, join_timeout: float = 10.0):
        """Idempotent shutdown: drain pending tasks, poison every worker,
        join. Never raises — errors the consumer cares about surface in
        get()."""
        while True:  # unsubmitted work is abandoned, not decoded
            try:
                self._tasks.get_nowait()
            except queue.Empty:
                break
        for _ in self._threads:
            self._tasks.put(None)
        for t in self._threads:
            t.join(timeout=join_timeout)
        with self._cv:
            self._results.clear()


class Pipeline:
    """Iterator of ``(x_float32, y_int32)`` batches with background prefetch.

    Args:
      x: uint8 array (N, ...), a file-backed shard set (``FileSource`` or a
        directory path), or an indexed record store (``RecordSource``,
        whose pluggable ``decode_fn`` turns variable-length encoded
        records into fixed-shape rows).
      y: int labels (N,) or None.
      batch_size: rows per emitted batch.
      shuffle: reshuffle every pass (epoch) deterministically from ``seed``.
        The per-pass permutation is ONE numpy computation
        (``np.random.default_rng((seed, pass))``) shared by the native and
        Python implementations, so the stream is bit-identical across them
        (``DTPU_NATIVE_LEGACY_SHUFFLE=1`` restores the old C++ splitmix
        order).
      scale: multiplier applied during ->float32 conversion. Default
        (None): 1/255 — the reference's normalization,
        /root/reference/README.md:56 — for uint8 sources; 1.0 for record
        sources, whose ``decode_fn`` owns normalization.
      prefetch: ring depth — how many batches may be ready ahead.
      num_threads: native producer threads.
      use_native: force (True/False) or auto (None). Record sources always
        run the Python path (``decode_fn`` is Python).
      shard: optional ``(index, count)`` per-host input sharding: this
        pipeline prepares only rows ``[index * b/count, (index+1) * b/count)``
        of each global batch (``batch_size`` stays the GLOBAL batch). Every
        host runs the same (seed, pass, step) sequence, so the host slices
        assemble into exactly the batch an unsharded pipeline would emit —
        global-batch semantics unchanged, per-host memory and prep work
        divided by ``count`` (SURVEY.md §7 hard parts; contrast the
        reference's full-dataset-everywhere feeding,
        /root/reference/README.md:369-373). ``shard="auto"`` derives
        ``(jax.process_index(), jax.process_count())`` from the live
        runtime — the right spelling for elastic gangs, where the world
        size differs between relaunches (see :meth:`reshard`).
      decode_workers: record sources only — fan record decode across this
        many worker threads (0 decodes inline on the consumer thread).
        Work is assigned by step index and reassembled in order, so the
        batch stream is BIT-IDENTICAL for any worker count; workers give
        real speedup when ``decode_fn`` releases the GIL (zlib, PIL,
        numpy) or blocks on I/O (docs/PERF.md "Streaming input").
      decode_readahead: how many batch steps may be decoding (or decoded,
        unconsumed) ahead of the consumer. Default ``2 * decode_workers``.

    The stream is infinite (passes repeat, reshuffled); ``steps_per_pass``
    tells one epoch's length, matching ``fit(steps_per_epoch=...)``.
    :meth:`state_dict`/:meth:`load_state` capture and restore the iterator
    cursor for mid-epoch checkpoint resume (``Checkpointer`` records it
    automatically; see docs/API.md "Data").
    """

    def __init__(
        self,
        x,
        y: Optional[np.ndarray] = None,
        batch_size: int = 32,
        *,
        shuffle: bool = True,
        seed: int = 0,
        scale: Optional[float] = None,
        prefetch: int = 4,
        num_threads: int = 2,
        use_native: Optional[bool] = None,
        shard: Optional[Tuple[int, int]] = None,
        decode_workers: int = 0,
        decode_readahead: Optional[int] = None,
    ):
        from .filesource import FileSource
        from .records import RecordSource

        # Teardown-critical fields FIRST: __del__ runs on instances whose
        # __init__ raised partway (bad batch_size, a failed native handle),
        # and close() must find a consistent shape to tear down.
        self._lib = None
        self._handle = None
        self._closed = False
        self._py_step = 0
        self._decode_pool = None
        self.steps_emitted = 0  # lets fit() fast-forward on resume

        # x is an in-memory uint8 array, a file-backed shard set
        # (FileSource, or a directory path — streams through memory-mapped
        # spans, never loading the dataset into RAM), or a RecordSource of
        # variable-length encoded records (decoded on the host, optionally
        # in parallel).
        self._source: Optional[FileSource] = None
        self._records: Optional[RecordSource] = None
        self._decode_labels = False
        if isinstance(x, (str, os.PathLike)):
            x = FileSource(x)
        if isinstance(x, RecordSource):
            if x.decode_fn is None:
                raise ValueError(
                    "Pipeline needs a RecordSource with a decode_fn: "
                    "records are encoded bytes, and only the decoder "
                    "knows the row shape"
                )
            if use_native is True:
                raise ValueError(
                    "use_native=True is unavailable for record sources: "
                    "decode_fn runs in Python (decode parallelism comes "
                    "from decode_workers instead)"
                )
            self._records = x
            row_shape, self._decode_labels = x.probe()
            if self._decode_labels and y is not None:
                raise ValueError(
                    "labels come from decode_fn (it returns (row, label)); "
                    "do not also pass y"
                )
            n_rows = x.n
            self._x = None
        elif isinstance(x, FileSource):
            self._source = x
            if y is None:
                y = x.y  # labels from the shard set, if present
            n_rows = x.n
            row_shape = x.row_shape
            self._x = None
        else:
            x = np.ascontiguousarray(x)
            if x.dtype != np.uint8:
                raise TypeError(
                    f"Pipeline feeds raw uint8 data, got {x.dtype}"
                )
            self._x = x
            n_rows = x.shape[0]
            row_shape = x.shape[1:]
        if batch_size <= 0 or batch_size > n_rows:
            raise ValueError(
                f"batch_size {batch_size} invalid for {n_rows} rows"
            )
        self._y = (
            None if y is None else np.ascontiguousarray(y, dtype=np.int32)
        )
        if self._y is not None and len(self._y) != n_rows:
            raise ValueError("x and y lengths differ")
        self.batch_size = int(batch_size)
        self._row_shape = tuple(row_shape)
        self._set_shard(shard)
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        if scale is None:
            scale = 1.0 if self._records is not None else 1.0 / 255.0
        self.scale = float(scale)
        self.prefetch = max(1, int(prefetch))
        self.num_threads = max(1, int(num_threads))
        self._n = int(n_rows)
        self.steps_per_pass = self._n // self.batch_size
        self._row = int(np.prod(row_shape, dtype=np.int64))
        self._perm_cache = {}  # pass -> permutation (numpy, both impls)

        self.decode_workers = max(0, int(decode_workers))
        if self.decode_workers and self._records is None:
            raise ValueError(
                "decode_workers requires a RecordSource input (raw uint8 "
                "sources have nothing to decode)"
            )
        self._decode_readahead = (
            2 * self.decode_workers
            if decode_readahead is None
            else max(0, int(decode_readahead))
        )
        self._next_submit = 0  # next step handed to the decode pool

        lib = None
        if self._records is None:
            lib = _load_native() if use_native in (None, True) else None
            if use_native is True and lib is None:
                raise RuntimeError(
                    "Native pipeline requested but unavailable"
                )
        self._lib = lib
        # Unified shuffle: the native pipeline consumes numpy-computed
        # per-pass permutations unless the legacy env flag pins the old
        # C++ splitmix order (compat for artifacts recorded before the
        # unification).
        self._external_perms = (
            lib is not None
            and self.shuffle
            and os.environ.get("DTPU_NATIVE_LEGACY_SHUFFLE") != "1"
        )
        self._supplied_passes = set()
        if lib is not None:
            self._handle = self._create_handle(0)
        elif self.decode_workers:
            self._decode_pool = _DecodePool(
                self._decode_batch, self.decode_workers
            )

    def _set_shard(self, shard) -> None:
        """Validate + adopt a ``(index, count)`` slice of the global batch
        (None -> unsharded, "auto" -> the live process's rank/world).
        Shared by ``__init__`` and :meth:`reshard` so both agree on what a
        legal shard is; emitted shape follows (``batch_size`` stays the
        GLOBAL batch)."""
        if isinstance(shard, str):
            if shard != "auto":
                raise ValueError(
                    f"shard must be (index, count), None, or 'auto'; "
                    f"got {shard!r}"
                )
            import jax

            shard = (jax.process_index(), jax.process_count())
        if shard is None:
            shard = (0, 1)
        index, count = (int(shard[0]), int(shard[1]))
        if count < 1 or not (0 <= index < count):
            raise ValueError(f"shard index {index} not in [0, {count})")
        if self.batch_size % count:
            raise ValueError(
                f"batch_size {self.batch_size} not divisible by "
                f"shard count {count}"
            )
        self.shard = (index, count) if count > 1 else None
        self.shard_rows = self.batch_size // count
        self.batch_shape = (self.shard_rows,) + self._row_shape

    def reshard(self, shard) -> "Pipeline":
        """Adopt a new ``(index, count)`` slice of the SAME global batch
        stream at the current position — the elastic-resize primitive. The
        global sequence depends only on (seed, pass, step), so after
        ``reshard`` the next emitted batch is this shard's rows of exactly
        the global batch the old sharding would have assembled next: the
        re-formed gang's slices still concatenate into the unsharded
        stream, and the loss trajectory is preserved across the resize
        (docs/RESILIENCE.md "Elastic gangs"). ``shard="auto"`` re-derives
        ``(process_index, process_count)`` from the live runtime. O(1) —
        the native ring (or decode pool) is recreated at the current step,
        nothing is replayed or re-prepared."""
        if self._closed:
            raise ValueError("Pipeline is closed")
        self._set_shard(shard)
        if self._handle is not None:
            # Same detach-before-recreate dance as seek(): a failed
            # recreate must not leave a handle close() would double-free.
            handle, self._handle = self._handle, None
            self._lib.dtpu_pipeline_destroy(handle)
            self._supplied_passes = set()
            self._handle = self._create_handle(self.steps_emitted)
        else:
            # Decoded-but-unconsumed results were sliced for the OLD
            # shard; drop and re-stage them for the new one.
            self._reset_decode_pool(self._py_step)
        return self

    def _create_handle(self, start_step: int):
        # One span for an in-memory array; one per memory-mapped shard for
        # a FileSource (np.memmap exposes the mapping's base address via
        # .ctypes like any ndarray — no copy).
        if self._source is not None:
            arrays = self._source.x_shards
        else:
            arrays = [self._x]
        n_spans = len(arrays)
        xs = (ctypes.c_void_p * n_spans)(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays]
        )
        rows = (ctypes.c_int64 * n_spans)(*[a.shape[0] for a in arrays])
        handle = self._lib.dtpu_pipeline_create_spans(
            ctypes.cast(xs, ctypes.POINTER(ctypes.c_void_p)),
            ctypes.cast(rows, ctypes.POINTER(ctypes.c_int64)),
            n_spans,
            None if self._y is None
            else self._y.ctypes.data_as(ctypes.c_void_p),
            self._n,
            self._row,
            self.batch_size,
            1 if self.shuffle else 0,
            self.seed,
            self.prefetch,
            self.num_threads,
            self.scale,
            start_step,
            0 if self.shard is None else self.shard[0],
            1 if self.shard is None else self.shard[1],
            1 if self._external_perms else 0,
        )
        if not handle:
            raise RuntimeError("dtpu_pipeline_create failed")
        # Producers may immediately fill up to prefetch steps ahead; hand
        # them every permutation they can reach before they need it.
        self._supply_native_perms(handle, start_step + self.prefetch)
        return handle

    # ------------------------------------------------------------- shuffle --
    def _pass_perm(self, pass_idx: int) -> np.ndarray:
        """THE per-pass row permutation (identity when shuffle=False) —
        one seeded numpy computation shared by the Python fallback, the
        record decode stage, and the native pipeline (which receives it
        as an index buffer), so every implementation emits the same
        stream. Cached per pass; passes behind the consumer are pruned so
        memory stays bounded over arbitrarily long runs."""
        order = self._perm_cache.get(pass_idx)
        if order is None:
            if self.shuffle:
                rng = np.random.default_rng((self.seed, pass_idx))
                order = rng.permutation(self._n).astype(np.int64)
            else:
                order = np.arange(self._n, dtype=np.int64)
            self._perm_cache[pass_idx] = order
            cur = self.steps_emitted // max(1, self.steps_per_pass)
            for old in [p for p in self._perm_cache if p < cur]:
                del self._perm_cache[old]
        return order

    def _supply_native_perms(self, handle, max_step: int) -> None:
        """Feed the native ring every per-pass permutation its producers
        can reach while filling through ``max_step`` — called before
        every native next() so workers never wait on a missing pass."""
        if not self._external_perms or handle is None:
            return
        spp = max(1, self.steps_per_pass)
        for p in range(self.steps_emitted // spp, max_step // spp + 1):
            if p in self._supplied_passes:
                continue
            order = np.ascontiguousarray(self._pass_perm(p))
            self._lib.dtpu_pipeline_supply_perm(
                handle, p,
                order.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            )
            self._supplied_passes.add(p)
        cur = self.steps_emitted // spp
        self._supplied_passes = {
            p for p in self._supplied_passes if p >= cur
        }

    # -------------------------------------------------------------- decode --
    def _indices_for_step(self, step: int) -> np.ndarray:
        pass_idx, within = divmod(step, self.steps_per_pass)
        order = self._pass_perm(pass_idx)
        start = within * self.batch_size
        if self.shard is not None:
            start += self.shard[0] * self.shard_rows
        return order[start: start + self.shard_rows]

    def _decode_batch(self, idx: np.ndarray):
        """Fetch + CRC-validate + decode the records of one batch step.
        Pure in ``idx`` (decode_fn is required pure), so it runs
        identically inline or on any decode worker. Scaling happens here
        too, so under decode_workers it parallelizes with the decode."""
        src = self._records
        xb = np.empty((len(idx),) + self._row_shape, np.float32)
        yb = (
            np.empty((len(idx),), np.int32) if self._decode_labels else None
        )
        for i, g in enumerate(idx):
            out = src.decode(int(g))
            if self._decode_labels:
                row, label = out
                yb[i] = label
            else:
                row = out
            row = np.asarray(row)
            if row.shape != self._row_shape:
                raise ValueError(
                    f"decode_fn returned shape {row.shape} for record "
                    f"{int(g)}, but record 0 decoded to "
                    f"{self._row_shape} — rows must share one shape"
                )
            xb[i] = row
        if self.scale != 1.0:
            xb *= np.float32(self.scale)
        return xb, yb

    def _reset_decode_pool(self, step: int) -> None:
        """Recreate the decode pool at ``step``: in-flight and decoded-
        but-unconsumed work belongs to an abandoned cursor (seek/reshard)
        and is dropped, never consumed."""
        if self._decode_pool is not None:
            self._decode_pool.close()
            self._decode_pool = _DecodePool(
                self._decode_batch, self.decode_workers
            )
        self._next_submit = step

    def _fill_records(self, xb: np.ndarray, yb: np.ndarray) -> None:
        step = self._py_step
        self._py_step += 1
        if self._decode_pool is None:
            rows, labels = self._decode_batch(self._indices_for_step(step))
        else:
            # Keep the pool primed readahead steps past the consumer; work
            # is keyed by step and reassembled in order, so the stream is
            # identical for any worker count.
            if self._next_submit <= step:
                self._next_submit = step
            while self._next_submit <= step + self._decode_readahead:
                self._decode_pool.submit(
                    self._next_submit,
                    self._indices_for_step(self._next_submit),
                )
                self._next_submit += 1
            rows, labels = self._decode_pool.get(step)
        xb[:] = rows
        if labels is not None:
            yb[:] = labels
        elif self._y is not None:
            yb[:] = self._y[self._indices_for_step(step)]
        else:
            yb[:] = 0

    # ------------------------------------------------------------ iteration --
    def seek(self, step: int):
        """Jump to global step ``step`` in O(1): the stream position depends
        only on (seed, pass, within), so resume never replays or re-prepares
        skipped batches. Used by ``fit()`` on checkpoint-restart."""
        if self._closed:
            raise ValueError("Pipeline is closed")
        step = int(step)
        if step < 0:
            raise ValueError(f"seek target must be >= 0, got {step}")
        if self._handle is not None:
            # Detach before destroy/recreate: if _create_handle fails here,
            # close()/__del__ must not double-destroy the old handle.
            handle, self._handle = self._handle, None
            self._lib.dtpu_pipeline_destroy(handle)
            self._supplied_passes = set()
            self._perm_cache = {}
            self.steps_emitted = step  # perm pruning keys off the cursor
            self._handle = self._create_handle(step)
        else:
            self._py_step = step
            self._perm_cache = {}
            self._reset_decode_pool(step)
        self.steps_emitted = step

    # ------------------------------------------------------ iterator state --
    def state_dict(self, consumed_steps: Optional[int] = None) -> dict:
        """JSON-serializable iterator cursor for mid-epoch checkpoint
        resume: (pass, step-in-pass, global step) plus the identity
        fields ``load_state`` validates against. ``consumed_steps``
        overrides the recorded cursor — ``Checkpointer`` passes the
        step the MODEL actually trained, which can trail
        ``steps_emitted`` when a prefetch producer has staged batches
        ahead. The shard cursor is recorded for diagnostics but NOT
        restored: after an elastic resize the live pipeline keeps its
        own (new-world) shard and still replays the same global stream
        (see :meth:`reshard`)."""
        steps = (
            self.steps_emitted
            if consumed_steps is None else int(consumed_steps)
        )
        spp = max(1, self.steps_per_pass)
        return {
            "kind": "dtpu.data.Pipeline",
            "steps_emitted": int(steps),
            "pass": int(steps // spp),
            "step_in_pass": int(steps % spp),
            "seed": int(self.seed),
            "batch_size": int(self.batch_size),
            "shuffle": bool(self.shuffle),
            "n_rows": int(self._n),
            "shard_cursor": list(self.shard) if self.shard else [0, 1],
        }

    def load_state(self, state: dict) -> "Pipeline":
        """Restore the cursor captured by :meth:`state_dict` in O(1) — no
        batch is replayed or re-prepared. The stream identity fields
        (seed, batch_size, shuffle, row count) must match the live
        pipeline or this raises: silently resuming a DIFFERENT stream at
        a saved step would train on wrong data without any signal. The
        saved shard cursor is ignored (elastic resizes legitimately
        change it)."""
        for key, mine in (
            ("seed", self.seed),
            ("batch_size", self.batch_size),
            ("shuffle", self.shuffle),
            ("n_rows", self._n),
        ):
            if key in state and state[key] != mine:
                raise ValueError(
                    f"iterator state mismatch: checkpoint has {key}="
                    f"{state[key]!r} but this pipeline has {mine!r} — "
                    "resuming would replay a different stream"
                )
        self.seek(int(state["steps_emitted"]))
        return self

    @property
    def is_native(self) -> bool:
        return self._handle is not None

    def __iter__(self):
        return self

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._closed:
            raise ValueError("Pipeline is closed")
        xb = np.empty(self.batch_shape, np.float32)
        yb = np.empty((self.shard_rows,), np.int32)
        self._fill(xb, yb)
        return xb, yb

    def _fill(self, xb: np.ndarray, yb: np.ndarray) -> None:
        """Write the next batch into caller-provided buffers (contiguous
        float32/int32 of batch_shape/(shard_rows,)) — the one batch-emit
        implementation behind __next__ and next_k."""
        if self._records is not None:
            self._fill_records(xb, yb)
            self.steps_emitted += 1
            return
        if self._handle is not None:
            # The call below advances consumed to steps_emitted + 1, after
            # which producers may fill through steps_emitted + prefetch:
            # supply every permutation that window can touch first.
            self._supply_native_perms(
                self._handle, self.steps_emitted + self.prefetch
            )
            step = self._lib.dtpu_pipeline_next(
                self._handle,
                xb.ctypes.data_as(ctypes.c_void_p),
                yb.ctypes.data_as(ctypes.c_void_p),
            )
            if step < 0:
                raise StopIteration
            self.steps_emitted += 1
            return
        # Python fallback: identical pass/step semantics, same numpy perm.
        step = self._py_step
        self._py_step += 1
        idx = self._indices_for_step(step)
        rows = (
            self._source.gather(idx) if self._source is not None
            else self._x[idx]
        )
        xb[:] = rows.astype(np.float32) * self.scale
        if self._y is not None:
            yb[:] = self._y[idx]
        else:
            yb[:] = 0
        self.steps_emitted += 1

    def next_k(self, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """The next ``k`` batches collated into stacked arrays of shape
        ``(k,) + batch_shape`` / ``(k, shard_rows)`` — the super-batch
        ``Model.fit`` transfers once under ``steps_per_execution=K``.

        Each batch is written straight into its row of the output (the
        native ring's producer buffer, or the Python path's gather, fills
        the slice in place), so collation adds NO copy over ``k`` separate
        ``__next__`` calls — it just moves the allocation up front."""
        k = int(k)
        if k < 1:
            raise ValueError(f"next_k needs k >= 1, got {k}")
        if self._closed:
            raise ValueError("Pipeline is closed")
        xs = np.empty((k,) + self.batch_shape, np.float32)
        ys = np.empty((k, self.shard_rows), np.int32)
        for i in range(k):
            self._fill(xs[i], ys[i])
        return xs, ys

    def close(self):
        """Idempotent shutdown, safe in every degraded state: a partially
        constructed instance (``__init__`` raised before the native handle
        existed), a repeated close, and interpreter shutdown — where module
        globals (the ctypes lib, its function pointers) may already be torn
        down while native prefetch threads are still live. Every lookup is
        defensive and the destroy itself is allowed to fail silently; the
        alternative is an exception out of ``__del__`` at exit."""
        self._closed = True
        pool = getattr(self, "_decode_pool", None)
        self._decode_pool = None
        if pool is not None:
            try:
                pool.close()
            except Exception:
                pass
        handle = getattr(self, "_handle", None)
        self._handle = None
        if handle:
            destroy = getattr(getattr(self, "_lib", None),
                              "dtpu_pipeline_destroy", None)
            if destroy is not None:
                try:
                    destroy(handle)
                except Exception:
                    pass  # shutdown-time ctypes teardown; nothing to save

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
