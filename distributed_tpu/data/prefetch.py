"""Device prefetch: overlap host batch prep + H2D transfer with compute.

The train loop's dispatch of a DONATED step blocks until the previous
dispatch's execution completes (the donated params buffer must be free
before the next program can take it), so everything the host does between
dispatches — assembling the next (super-)batch and starting its
host->device transfer — sits on the step's critical path. A
:class:`DevicePrefetcher` moves that work onto a bounded background
producer: while dispatch N executes, the producer prepares and *places*
the batch for dispatch N+1 (``strategy.put_batch(..., async_=True)`` — a
non-blocking ``jax.device_put``, never a ``block_until_ready``), so the
main thread's only per-dispatch cost is a queue pop.

Determinism: batches are produced by ONE thread, in order, from the same
source cursor the synchronous loop would advance — the staged stream is
bit-identical to the unprefetched one, and per-step RNG never moves (it is
keyed on the global step, not on wall time). ``sizes`` fixes the exact
dispatch sizes up front, so a normally-completed epoch consumes exactly
``sum(sizes)`` source steps — no over-read at epoch end. An early stop
(``stop_training`` mid-epoch) leaves up to ``depth + 1`` staged dispatches
unconsumed; :attr:`unconsumed_steps` reports how many source STEPS those
held so a seekable source (``data.Pipeline``) can be rewound to the step
the model actually reached.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Sequence

__all__ = ["DevicePrefetcher"]

_POLL_S = 0.05  # producer/consumer wake-up period for stop/error checks


class DevicePrefetcher:
    """Bounded background producer of device-staged batches.

    Args:
      stage: ``stage(k) -> staged_batch`` — prepares ``k`` source steps'
        worth of host data and starts its device placement. Runs on the
        producer thread (or inline when ``depth == 0``); must therefore be
        non-blocking on the device (no fetches, no collectives).
      sizes: the exact sequence of per-dispatch sizes this prefetcher will
        serve, in order (``[1, 1, ...]`` for the plain loop, ``[K, ...,
        tail]`` under ``steps_per_execution=K``).
      depth: how many staged dispatches may be ready ahead of the consumer
        (the double-buffering default is 2). ``0`` disables the thread
        entirely — ``get()`` stages inline, byte-for-byte the synchronous
        path.
    """

    def __init__(self, stage: Callable, sizes: Sequence[int], depth: int = 2):
        self._stage = stage
        self._sizes = [int(k) for k in sizes]
        self._depth = max(0, int(depth))
        self._served = 0  # dispatches handed to the consumer
        self._produced_steps = 0  # source steps pulled by the producer
        self._served_steps = 0
        self._error = None
        self._stop = threading.Event()
        self._q = None
        self._thread = None
        if self._depth > 0 and len(self._sizes) > 1:
            self._q = queue.Queue(maxsize=self._depth)
            self._thread = threading.Thread(
                target=self._run, name="dtpu-prefetch", daemon=True
            )
            self._thread.start()

    # ------------------------------------------------------------- producer
    def _run(self):
        try:
            for k in self._sizes:
                if self._stop.is_set():
                    return
                item = self._stage(k)
                # Counted at stage time, queued or not: these source steps
                # are gone from the stream either way, and unconsumed_steps
                # must account for an item stranded by a mid-put stop.
                self._produced_steps += k
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=_POLL_S)
                        break
                    except queue.Full:
                        continue
                else:
                    return
        except BaseException as e:  # surfaced to the consumer in get()
            self._error = e

    # ------------------------------------------------------------- consumer
    def get(self):
        """The next staged dispatch as ``(k, staged_batch)``. Blocks until
        the producer has it ready; re-raises any producer-side exception
        (source exhaustion, placement errors) with its original type."""
        if self._served >= len(self._sizes):
            raise IndexError("prefetcher exhausted: all dispatches served")
        k = self._sizes[self._served]
        if self._thread is None:  # depth 0 / single dispatch: synchronous
            if self._error is None:
                try:
                    item = self._stage(k)
                    self._produced_steps += k
                except BaseException as e:
                    self._error = e
            if self._error is not None:
                raise self._error
        else:
            while True:
                try:
                    item = self._q.get(timeout=_POLL_S)
                    break
                except queue.Empty:
                    if self._error is not None:
                        raise self._error
                    if not self._thread.is_alive() and self._q.empty():
                        raise RuntimeError(
                            "prefetch producer exited without staging the "
                            "requested dispatch"
                        )
        self._served += 1
        self._served_steps += k
        return k, item

    # ------------------------------------------------------------- shutdown
    @property
    def unconsumed_steps(self) -> int:
        """Source steps staged (or in staging) but never served — nonzero
        only after an early ``close()``. The caller rewinds a seekable
        source by this much to realign it with the consumed stream."""
        return self._produced_steps - self._served_steps

    def close(self, join_timeout: float = 10.0):
        """Idempotent shutdown: stop the producer, drain staged items, and
        join the thread. Never raises — close is cleanup; errors the
        consumer cares about surfaced in get()."""
        self._stop.set()
        if self._thread is not None:
            while True:  # unblock a producer stuck in q.put
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
            self._thread.join(timeout=join_timeout)
        # A staged-but-undrained item could still have landed between the
        # drain and the join; empty the queue once more so its device
        # buffers are released promptly.
        if self._q is not None:
            while True:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
