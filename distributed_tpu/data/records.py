"""Indexed record shards: variable-length encoded records at cloud scale.

``FileSource`` (filesource.py) lifted the reference's feed-whole-arrays
input (/root/reference/README.md:369-373) to fixed-shape uint8 npy shards —
the right format when rows are raw tensors. Production stores are not:
ImageNet-scale corpora ship as *encoded*, variable-length records (JPEG
bytes, tokenized documents, protos), and what starves the accelerator is
host-side **decode**, not fetch latency. This module is the storage half of
that pipeline; ``Pipeline(RecordSource(...), decode_workers=W)``
(pipeline.py) is the compute half.

Layout written by :func:`write_records`::

    dir/records-00000.drs       # "DRS1" magic, then per record:
                                #   [u32 LE payload length][u32 LE crc32][payload]
    dir/records-00000-idx.npy   # int64 (n_i,) byte offset of each record header
    dir/records-00001.drs
    ...

The sidecar index is what makes the format *seekable*: record ``i`` of a
shard is one ``pread`` at ``offsets[i]`` — no scan, so a shuffled Pipeline
reads exactly the records each batch needs, and mid-epoch resume is O(1).
Reads go through ``os.pread`` (stateless, no shared file cursor), so any
number of decode workers can read one shard concurrently.

Corruption is LOUD: a truncated shard or a CRC mismatch raises
:class:`RecordCorruptionError` naming the shard file and record index —
a flipped bit in a petabyte store must fail the step that touched it, not
silently train on garbage.
"""

from __future__ import annotations

import os
import re
import struct
import zlib
from pathlib import Path
from typing import Callable, Iterable, List, Optional, Tuple

import numpy as np

__all__ = ["RecordSource", "RecordCorruptionError", "write_records"]

_SHARD_RE = re.compile(r"^records-(\d+)\.drs$")
_MAGIC = b"DRS1"
_HEADER = struct.Struct("<II")  # payload length, crc32(payload)


class RecordCorruptionError(ValueError):
    """A record shard failed validation (truncation or CRC mismatch). The
    message names the shard file and the record index within it."""


def write_records(
    directory,
    records: Iterable[bytes],
    *,
    records_per_shard: int = 4096,
) -> Path:
    """Write an iterable of bytes-like records into the indexed shard
    layout above. Empty records are rejected (a zero-length record is
    indistinguishable from a torn write at read time); existing record
    shards in the directory are an error (no silent mixing)."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    if any(_SHARD_RE.match(p.name) for p in directory.iterdir()):
        raise FileExistsError(f"{directory} already contains record shards")
    if records_per_shard < 1:
        raise ValueError("records_per_shard must be >= 1")

    shard_idx = 0
    fh = None
    offsets: List[int] = []
    pos = 0
    total = 0

    def _close_shard():
        nonlocal fh
        if fh is None:
            return
        fh.flush()
        os.fsync(fh.fileno())
        fh.close()
        fh = None
        np.save(
            directory / f"records-{shard_idx:05d}-idx.npy",
            np.asarray(offsets, np.int64),
        )

    try:
        for rec in records:
            rec = bytes(rec)
            if not rec:
                raise ValueError(
                    f"record {total} is empty; zero-length records are not "
                    "representable (indistinguishable from truncation)"
                )
            if fh is None:
                fh = open(directory / f"records-{shard_idx:05d}.drs", "wb")
                fh.write(_MAGIC)
                pos = len(_MAGIC)
                offsets = []
            offsets.append(pos)
            fh.write(_HEADER.pack(len(rec), zlib.crc32(rec)))
            fh.write(rec)
            pos += _HEADER.size + len(rec)
            total += 1
            if len(offsets) >= records_per_shard:
                _close_shard()
                shard_idx += 1
        _close_shard()
    except BaseException:
        if fh is not None:
            fh.close()
        raise
    if total == 0:
        raise ValueError("no records to write")
    return directory


class _Shard:
    """One open record shard: fd for stateless pread + its offset index."""

    def __init__(self, path: Path):
        self.path = path
        idx_path = path.with_name(path.name[: -len(".drs")] + "-idx.npy")
        if not idx_path.exists():
            raise FileNotFoundError(
                f"{path.name}: sidecar index {idx_path.name} is missing — "
                "record shards are unreadable without their offset index "
                "(rewrite the shard set with write_records)"
            )
        self.offsets = np.load(idx_path)
        if self.offsets.ndim != 1 or not np.issubdtype(
            self.offsets.dtype, np.integer
        ):
            raise ValueError(
                f"{idx_path.name}: index must be a 1-D integer array, got "
                f"{self.offsets.dtype} with shape {self.offsets.shape}"
            )
        if len(self.offsets) == 0:
            raise ValueError(f"{path.name}: empty shard (index has 0 records)")
        self.size = path.stat().st_size
        self.fd = os.open(str(path), os.O_RDONLY)
        magic = os.pread(self.fd, len(_MAGIC), 0)
        if magic != _MAGIC:
            os.close(self.fd)
            raise RecordCorruptionError(
                f"{path.name}: bad magic {magic!r} (expected {_MAGIC!r}) — "
                "not a record shard, or its header is torn"
            )

    def read(self, rec: int) -> bytes:
        """Record ``rec`` of this shard, CRC-validated. Raises
        :class:`RecordCorruptionError` naming shard + record on any
        truncation or checksum mismatch."""
        off = int(self.offsets[rec])
        header = os.pread(self.fd, _HEADER.size, off)
        if len(header) < _HEADER.size:
            raise RecordCorruptionError(
                f"shard {self.path.name} is truncated at record {rec}: "
                f"header at offset {off} runs past the file end "
                f"({self.size} bytes)"
            )
        length, crc = _HEADER.unpack(header)
        if length == 0 or off + _HEADER.size + length > self.size:
            raise RecordCorruptionError(
                f"shard {self.path.name} is truncated at record {rec}: "
                f"payload of {length} bytes at offset {off} runs past the "
                f"file end ({self.size} bytes)"
            )
        payload = os.pread(self.fd, length, off + _HEADER.size)
        if len(payload) < length:
            raise RecordCorruptionError(
                f"shard {self.path.name} is truncated at record {rec}: "
                f"read {len(payload)} of {length} payload bytes"
            )
        if zlib.crc32(payload) != crc:
            raise RecordCorruptionError(
                f"CRC mismatch in shard {self.path.name}, record {rec}: "
                f"stored {crc:#010x}, computed {zlib.crc32(payload):#010x} "
                "— the record is corrupt on disk"
            )
        return payload

    def close(self):
        fd, self.fd = self.fd, -1
        if fd >= 0:
            try:
                os.close(fd)
            except OSError:
                pass


class RecordSource:
    """Read-side view over a directory of indexed record shards.

    Args:
      directory: shard directory written by :func:`write_records`.
      decode_fn: pluggable ``bytes -> row`` (or ``bytes -> (row, label)``)
        decoder. ``row`` is any array-like of one fixed shape (every
        record must decode to the same row shape — the Pipeline's batch
        shape is probed from record 0). Required for use as a
        ``Pipeline`` input; optional for raw ``read()`` access. Must be
        PURE (same bytes -> same row): the parallel decode stage calls it
        from worker threads, and stream determinism across worker counts
        relies on it.

    The global record order is shard-major (all of shard 0, then shard 1,
    ...), matching ``FileSource``'s row order, so the same seeded
    permutation addresses both formats identically.
    """

    def __init__(self, directory, decode_fn: Optional[Callable] = None):
        self.directory = Path(directory)
        if not self.directory.is_dir():
            raise FileNotFoundError(
                f"record directory not found: {directory}"
            )
        paths = sorted(
            (p for p in self.directory.iterdir() if _SHARD_RE.match(p.name)),
            key=lambda p: int(_SHARD_RE.match(p.name).group(1)),
        )
        if not paths:
            raise FileNotFoundError(
                f"no records-*.drs shards in {self.directory}"
            )
        self.shards = [_Shard(p) for p in paths]
        self._counts = [len(s.offsets) for s in self.shards]
        self.n = int(sum(self._counts))
        self._starts = np.cumsum([0] + self._counts)
        self.decode_fn = decode_fn
        self._probe_cache: Optional[Tuple[Tuple[int, ...], bool]] = None

    def __len__(self) -> int:
        return self.n

    def _locate(self, i: int) -> Tuple[_Shard, int]:
        if not 0 <= i < self.n:
            raise IndexError(f"record index {i} not in [0, {self.n})")
        s = int(np.searchsorted(self._starts, i, side="right") - 1)
        return self.shards[s], i - int(self._starts[s])

    def read(self, i: int) -> bytes:
        """Raw bytes of global record ``i``, CRC-validated."""
        shard, rec = self._locate(int(i))
        return shard.read(rec)

    def decode(self, i: int):
        """``decode_fn(read(i))`` — one decoded record."""
        if self.decode_fn is None:
            raise ValueError(
                "RecordSource has no decode_fn; pass one at construction "
                "to decode records"
            )
        return self.decode_fn(self.read(int(i)))

    def probe(self) -> Tuple[Tuple[int, ...], bool]:
        """(row_shape, has_labels) discovered by decoding record 0 once —
        how the Pipeline learns its batch shape without a schema file."""
        if self._probe_cache is None:
            out = self.decode(0)
            has_labels = isinstance(out, tuple)
            row = np.asarray(out[0] if has_labels else out)
            if row.ndim < 1:
                raise ValueError(
                    "decode_fn must return an array row (got a scalar); "
                    "wrap scalars as shape-(1,) arrays"
                )
            self._probe_cache = (tuple(row.shape), has_labels)
        return self._probe_cache

    def close(self):
        for s in getattr(self, "shards", []):
            s.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
