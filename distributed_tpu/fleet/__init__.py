"""Disaggregated serving fleet: router → prefill pool → decode pool.

One :class:`~distributed_tpu.serving.Engine` on one process is not a
production serving story (ROADMAP item 2). This package composes the
serving runtime (PR 6) and the elasticity/fault machinery (PR 7) into a
multi-replica tier:

- **Disaggregation** — prefill and decode run on SEPARATE replica pools;
  prompts become first tokens + packed KV blocks on the prefill side and
  are handed to a decode replica via the ``ShardedCheckpointer``
  block-layout idiom (``fleet.handoff``), with re-prefill as the
  documented fallback when transfer is unavailable.
- **Routing** — an SLO-aware front door (``fleet.router``): bounded
  queues, reject-on-predicted-SLO-breach, weighted per-tenant fair
  queuing.
- **Elasticity** — a queue-depth/SLO autoscaler (``fleet.autoscale``)
  generalizing ``ElasticPolicy``'s capacity ``probe()`` seam from
  failure-driven to load-driven; spin-up is cheap because replicas share
  compiled programs (``fleet.replica.EnginePrograms``).
- **Fault tolerance** — ``FaultInjector(mode="replica_kill",
  replica="decode-1")`` tears a named replica down mid-request; the
  router re-queues its in-flight sequences and surviving replicas finish
  them token-exact under greedy decode (zero lost requests — the
  scheduler's preemption-requeue semantics generalized across replicas).

    fleet = dtpu.fleet.ServingFleet(model, decode_replicas=4,
                                    prefill_replicas=1, max_slots=4,
                                    block_size=16, max_len=128)
    outs = fleet.run(requests, arrival_times=times, tenants=tenants)
    fleet.last_run_telemetry  # tokens/s, p50/p99 TTFT, per-request rows

``bench.py fleet`` (BENCH_fleet.json) measures tokens/s scaling vs
replica count, tail TTFT under bursty arrivals, and the kill-a-replica
recovery row; docs/SERVING.md "Fleet" documents semantics and limits —
including the virtual-clock harness used on single-host boxes.
"""

from .autoscale import QueueAutoscaler
from .core import FleetResult, ServingFleet
from .gossip import PrefixGossipIndex
from .handoff import (
    HandoffIncompatible, KVHandoff, adopt_prefix, install_kv, pack_kv,
    pack_prefix,
)
from .replica import DecodeReplica, EnginePrograms, PrefillReplica
from .router import Admission, Router

__all__ = [
    "ServingFleet",
    "FleetResult",
    "Router",
    "Admission",
    "QueueAutoscaler",
    "EnginePrograms",
    "PrefillReplica",
    "DecodeReplica",
    "KVHandoff",
    "HandoffIncompatible",
    "PrefixGossipIndex",
    "pack_kv",
    "install_kv",
    "pack_prefix",
    "adopt_prefix",
]
