"""Queue-depth / SLO autoscaling for the decode pool.

:class:`~distributed_tpu.resilience.ElasticPolicy` resizes a TRAINING
gang on capacity signals: its ``probe()`` seam returns "how many workers
can run right now" and the supervisor follows it at restart boundaries.
:class:`QueueAutoscaler` is that seam generalized from capacity-driven to
LOAD-driven for serving: the target replica count follows queue depth and
tail latency instead of worker failures, and ``probe()`` exposes the
current target in exactly the ElasticPolicy shape — so the same policy
object that resizes a training gang can be pointed at a serving fleet
(``ElasticPolicy(probe=autoscaler.probe)``) without either side knowing.

Decision rules (deliberately simple, hysteretic, and pure — testable from
synthetic traces):

- **Grow** by one replica when queue depth per replica exceeds
  ``queue_high``, or when the recent p99 TTFT exceeds ``slo_ttft_s``
  (when set). Bursts are what autoscaling exists for; growth is cheap
  because replica spin-up is pool allocation, not a recompile
  (``fleet.replica.EnginePrograms``), bounded in production by the warm
  compile cache (BENCH_compile_cache.json).
- **Shrink** by one replica when the queue is below ``queue_low`` per
  replica AND at least one replica's worth of decode slots sits idle —
  the load provably fits in fewer replicas. Shrinking waits out
  ``cooldown_s`` since the last change (growth reacts immediately after
  its own cooldown; shedding capacity is the decision to be slow about).
- Targets clamp to ``[min_replicas, max_replicas]``; every change is
  recorded with its reason for the fleet's telemetry.
"""

from __future__ import annotations

from typing import List, Optional

__all__ = ["QueueAutoscaler"]


class QueueAutoscaler:
    """See module docstring. ``spinup_s`` is the modeled replica warm-up
    latency the fleet adds before a grown replica takes work (on top of
    the measured pool-allocation cost)."""

    def __init__(self, min_replicas: int = 1, max_replicas: int = 4, *,
                 queue_high: float = 2.0, queue_low: float = 0.25,
                 slo_ttft_s: Optional[float] = None,
                 cooldown_s: float = 0.5, spinup_s: float = 0.0):
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas}"
            )
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) must be >= min_replicas "
                f"({min_replicas})"
            )
        if queue_low >= queue_high:
            raise ValueError(
                f"queue_low ({queue_low}) must be < queue_high "
                f"({queue_high}) — equal thresholds oscillate"
            )
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.slo_ttft_s = slo_ttft_s
        self.cooldown_s = float(cooldown_s)
        self.spinup_s = float(spinup_s)
        self._target = self.min_replicas
        self._last_change: Optional[float] = None
        self.events: List[dict] = []

    # ---------------------------------------------------------------- seam
    def probe(self) -> int:
        """The ElasticPolicy capacity seam: the worker count this policy
        currently wants. Safe to hand to ``ElasticPolicy(probe=...)``."""
        return self._target

    @property
    def target(self) -> int:
        return self._target

    # ------------------------------------------------------------- decide
    def _change(self, now: float, to: int, reason: str) -> int:
        self.events.append({
            "t": round(float(now), 4), "from": self._target, "to": to,
            "reason": reason,
        })
        self._target = to
        self._last_change = float(now)
        return to

    def decide(self, now: float, *, queue_depth: int, replicas: int,
               free_slots: int = 0, slots_per_replica: int = 1,
               recent_p99_ttft: Optional[float] = None) -> int:
        """One autoscaling decision at fleet time ``now`` from live pool
        signals (router + replica queue depths summed into
        ``queue_depth``; ``free_slots`` across live decode replicas).
        Returns the new target replica count."""
        in_cooldown = (
            self._last_change is not None
            and now - self._last_change < self.cooldown_s
        )
        if in_cooldown:
            return self._target
        per = queue_depth / max(replicas, 1)
        slo_breach = (
            self.slo_ttft_s is not None
            and recent_p99_ttft is not None
            and recent_p99_ttft > self.slo_ttft_s
        )
        if (per > self.queue_high or slo_breach) and (
                self._target < self.max_replicas):
            reason = ("p99_ttft %.3fs > slo %.3fs"
                      % (recent_p99_ttft, self.slo_ttft_s)) if slo_breach \
                else "queue_depth %d > %.2g/replica" % (queue_depth,
                                                        self.queue_high)
            return self._change(now, self._target + 1, reason)
        if (per < self.queue_low
                and free_slots >= slots_per_replica
                and not slo_breach
                and self._target > self.min_replicas):
            return self._change(
                now, self._target - 1,
                "queue_depth %d < %.2g/replica, %d slots idle"
                % (queue_depth, self.queue_low, free_slots),
            )
        return self._target
