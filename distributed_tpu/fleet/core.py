"""The serving fleet: disaggregated prefill/decode pools behind a router.

``ServingFleet`` composes the pieces this package and its neighbors
provide into one serving tier:

- a :class:`~distributed_tpu.fleet.router.Router` at the front (bounded
  queue, SLO admission, weighted per-tenant fairness);
- a pool of :class:`~distributed_tpu.fleet.replica.PrefillReplica` that
  turn prompts into first tokens + KV payloads
  (``fleet.handoff``), and a pool of
  :class:`~distributed_tpu.fleet.replica.DecodeReplica` that decode them
  to completion — prefill/decode DISAGGREGATION, the intra-engine split
  of ``serving.Engine`` promoted to an inter-replica one;
- a :class:`~distributed_tpu.fleet.autoscale.QueueAutoscaler` (optional)
  driving the decode-pool size from queue depth / tail latency through
  the same reconcile step that replaces killed replicas;
- a :class:`~distributed_tpu.resilience.FaultInjector` hook
  (``mode="replica_kill"``) so replica death mid-request is a provable,
  benchable event: the dead replica's in-flight sequences re-queue at
  the router and finish on surviving replicas, token-exact under greedy
  (the scheduler's preemption-requeue contract across replicas).

**The clock.** Replicas are cooperative objects in one process; the fleet
drives them with a discrete-event loop over a VIRTUAL clock: every device
dispatch is real JAX work timed for real, but its wall time advances only
the owning replica's timeline (``busy_until``), and fleet time jumps to
the next event (arrival, replica free, spin-up done). Tokens, scheduling
decisions, and failure handling are therefore exactly what a process-per-
replica deployment computes, while throughput/latency numbers describe
the fleet as if replicas ran in parallel — which one 1-core host cannot
do for real. Artifacts and docs state this honestly (the PERF.md
measured-mechanism precedent); on a real multi-host deployment the same
control logic runs against wall clocks.
"""

from __future__ import annotations

import itertools
import time
from typing import Dict, List, Optional, Sequence as SequenceT

import jax
import jax.numpy as jnp
import numpy as np

from ..serving.scheduler import Request, Sequence
from ..utils import event_schema as evs
from ..utils import events as events_lib
from ..serving.kv_cache import _chain_hashes
from .autoscale import QueueAutoscaler
from .gossip import PrefixGossipIndex
from .handoff import (
    HandoffIncompatible, adopt_prefix, pack_prefix, trim_kv,
)
from .replica import DecodeReplica, EnginePrograms, PrefillReplica
from .router import Router

__all__ = ["ServingFleet", "FleetResult"]


class FleetResult(list):
    """The per-request outputs (submission order; ``None`` for rejected
    requests) with the run's telemetry attached as ``.telemetry``."""

    telemetry: dict


class ServingFleet:
    """See module docstring.

    ``transfer="blocks"`` moves prefilled KV to the decode replica via
    the handoff payload; ``transfer="none"`` models a deployment without
    a transfer path — the decode replica re-prefills every context (the
    documented fallback; same tokens, more compute). ``prefill_replicas=0``
    colocates prefill on the decode replicas (the engine's own layout).

    ``prefix_cache=True`` gives every decode replica a refcounted prefix
    store (``serving.kv_cache.PrefixStore``): the router places requests
    by prefix affinity, admission adopts cached prompt blocks, and
    handoff payloads are TRIMMED to the non-cached suffix before
    shipping (``fleet.handoff.trim_kv``) — telemetry reports the bytes
    saved.

    ``prefix_gossip=True`` (requires ``prefix_cache``) federates those
    per-replica stores through a :class:`~distributed_tpu.fleet.gossip.
    PrefixGossipIndex`: replicas advertise their chain-hash keys after
    every step, placement consults the global view (a request whose
    prefix SOME peer holds treats every prefix-caching replica as warm),
    and the fleet moves the blocks at placement time —
    ``pack_prefix`` on the warm side, ``adopt_prefix`` on the cold one,
    the copy charged to both replicas' timelines. A cold replica then
    admits with ``cached_len > 0`` and never re-prefills a shared
    prefix (``handoffs.prefills_full`` telemetry proves it). Every
    advertisement and payload carries ``weights_version``;
    :meth:`update_weights` bumps it, flushes every store, and withdraws
    every advertisement, so stale-weights blocks can never travel.
    """

    def __init__(self, model, *, decode_replicas: int = 2,
                 prefill_replicas: int = 1, max_slots: int = 4,
                 block_size: int = 16, max_len: int = 128,
                 num_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 transfer: str = "blocks",
                 prefix_cache: bool = False,
                 prefix_gossip: bool = False,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 eos_id: Optional[int] = None, seed: int = 0,
                 router: Optional[Router] = None,
                 autoscaler: Optional[QueueAutoscaler] = None,
                 fault=None,
                 programs: Optional[EnginePrograms] = None):
        if decode_replicas < 1:
            raise ValueError(
                f"decode_replicas must be >= 1, got {decode_replicas}"
            )
        if prefill_replicas < 0:
            raise ValueError(
                f"prefill_replicas must be >= 0, got {prefill_replicas}"
            )
        if transfer not in ("blocks", "none"):
            raise ValueError(
                f"transfer must be 'blocks' or 'none', got {transfer!r}"
            )
        if prefix_gossip and not prefix_cache:
            raise ValueError(
                "prefix_gossip=True requires prefix_cache=True — the "
                "gossip index advertises the per-replica prefix stores"
            )
        self.model = model
        self.programs = programs or EnginePrograms(
            model, temperature=temperature, top_k=top_k, seed=seed
        )
        # Positional-capacity check up front, exactly like Engine: a
        # too-short learned positional table must fail HERE, not clamp
        # rows mid-serve on some replica.
        jax.eval_shape(
            lambda p: model.module.init_cache(p, 1, int(max_len),
                                              jnp.float32),
            model.params,
        )
        self.max_slots = int(max_slots)
        self.block_size = int(block_size)
        self.max_len = int(max_len)
        self.num_blocks = num_blocks
        self.prefill_chunk = prefill_chunk
        self.transfer = transfer
        self.prefix_cache = bool(prefix_cache)
        self.prefix_gossip = bool(prefix_gossip)
        self.gossip = PrefixGossipIndex() if prefix_gossip else None
        self.weights_version = 0
        self.eos_id = eos_id
        self.router = router or Router()
        self.autoscaler = autoscaler
        self.fault = fault
        self._ids = itertools.count()
        self._configured_decode = int(decode_replicas)
        self.decode_pool: Dict[str, DecodeReplica] = {}
        self._warming: Dict[str, float] = {}  # name -> ready_at
        self.prefill_pool: List[PrefillReplica] = [
            PrefillReplica(
                f"prefill-{i}", self.programs,
                block_size=self.block_size, max_len=self.max_len,
                prefill_chunk=self.prefill_chunk,
            )
            for i in range(int(prefill_replicas))
        ]
        self.pool_events: List[dict] = []
        self._retired_rows: Dict[str, dict] = {}  # stats outlive retirement
        self.spinup_measured_s = 0.0
        for _ in range(int(decode_replicas)):
            self._spawn(0.0, warm=False)
        self.last_run_telemetry: Optional[dict] = None

    # ----------------------------------------------------------- replicas
    def _spawn(self, now: float, *, warm: bool = True) -> DecodeReplica:
        """Add a decode replica. Pool allocation is timed for real and,
        together with the autoscaler's modeled ``spinup_s``, delays when
        the replica takes work — programs are shared, so spin-up never
        re-traces (the warm-compile-cache contract)."""
        name = f"decode-{next(self._ids)}"
        t0 = time.perf_counter()
        rep = DecodeReplica(
            name, self.programs, max_slots=self.max_slots,
            block_size=self.block_size, max_len=self.max_len,
            num_blocks=self.num_blocks, prefill_chunk=self.prefill_chunk,
            eos_id=self.eos_id, prefix_cache=self.prefix_cache,
        )
        alloc = time.perf_counter() - t0
        self.spinup_measured_s = max(self.spinup_measured_s, alloc)
        self.decode_pool[name] = rep
        if warm:
            extra = self.autoscaler.spinup_s if self.autoscaler else 0.0
            ready = now + alloc + extra
            self._warming[name] = ready
            rep.busy_until = ready
            self.pool_events.append({
                "t": round(now, 4), "event": "spawn", "replica": name,
                "ready_at": round(ready, 4),
            })
        return rep

    @staticmethod
    def _replica_row(rep: DecodeReplica) -> dict:
        return {
            "decode_steps": rep.decode_steps,
            "prefill_dispatches": rep.prefill_dispatches,
            "prefills_full": rep.prefills_full,
            "preemptions": rep.preemptions,
            "handoffs_installed": rep.handoffs_installed,
            "handoffs_fallback": rep.handoffs_fallback,
            "handoffs_trim_stale": rep.handoffs_trim_stale,
            "gossip_adopts": rep.gossip_adopts,
            "gossip_adopt_blocks": rep.gossip_adopt_blocks,
            "gossip_serves": rep.gossip_serves,
            "gossip_advertised": rep.gossip_advertised,
            "busy_s": round(rep.busy_s, 4),
            "alive": rep.alive,
        }

    def _retire(self, name: str, now: float) -> None:
        rep = self.decode_pool.pop(name)
        self._retired_rows[name] = self._replica_row(rep)
        self._warming.pop(name, None)
        if self.gossip is not None:
            # A retired/killed replica's pool dies with it: its
            # advertisements must not linger as adoptable claims.
            self.gossip.withdraw(name)
        self.pool_events.append({
            "t": round(now, 4), "event": "retire", "replica": name,
        })

    def _ready(self, rep: DecodeReplica, now: float) -> bool:
        return rep.alive and self._warming.get(rep.name, 0.0) <= now

    def _reconcile(self, now: float) -> bool:
        """Drive the live decode-pool size toward the target — the
        autoscaler's if present, else the configured count. One reconcile
        step serves BOTH elasticity and healing: a killed replica leaves
        the pool below target and the next pass replaces it."""
        target = (self.autoscaler.target if self.autoscaler
                  else self._configured_decode)
        changed = False
        while len(self.decode_pool) < target:
            self._spawn(now)
            changed = True
        if len(self.decode_pool) > target:
            # Shrink only drains: retire an idle replica; if none is
            # idle, keep serving and try again at the next event.
            for name, rep in sorted(self.decode_pool.items()):
                if self._ready(rep, now) and rep.in_flight == 0:
                    self._retire(name, now)
                    changed = True
                    break
        return changed

    # -------------------------------------------------------- weight swap
    def update_weights(self, params) -> int:
        """Hot-swap the fleet's served weights (the Engine
        ``update_weights`` contract, pool-wide): validate the new tree
        against the live one, re-place it under the model's strategy,
        and swap — replicas dispatch through ``programs.model.params``,
        so the swap is atomic at dispatch granularity for every replica
        at once.

        Staleness discipline: every replica's prefix store is flushed
        (cached KV was computed under the old weights) AND its gossip
        advertisement withdrawn, and ``weights_version`` bumps — so even
        an advertisement that somehow survived (or a payload packed
        before the swap, in a real multi-process deployment) fails the
        stamp check at adoption time instead of seeding a new request
        from one-update-old KV. Returns the new version."""
        from ..serving.engine import _validate_swap

        model = self.programs.model
        _validate_swap(model.params, params, "fleet.update_weights")
        placed = model.strategy.put_params(
            params, hints=model.module.sharding_hints()
        )
        jax.block_until_ready(placed)
        model.params = placed
        self.weights_version += 1
        for name, rep in sorted(self.decode_pool.items()):
            if rep.kv.prefix is not None:
                rep.kv.prefix.flush(rep.kv.allocator)
            if self.gossip is not None:
                self.gossip.withdraw(name)
        for rep in self.prefill_pool:
            if rep.kv.prefix is not None:
                rep.kv.prefix.flush(rep.kv.allocator)
        return self.weights_version

    # ---------------------------------------------------------------- run
    def run(self, requests: SequenceT, *,
            arrival_times: Optional[SequenceT] = None,
            tenants: Optional[SequenceT] = None) -> FleetResult:
        """Serve ``requests`` (``serving.Request`` or (prompt, n) pairs)
        under an open-loop arrival process: request i becomes visible to
        the router at ``arrival_times[i]`` (fleet seconds; default all
        0.0) with tenant ``tenants[i]`` (default "default"). Returns
        outputs in submission order (``None`` where admission rejected);
        telemetry lands in ``fleet.last_run_telemetry`` and on the
        result's ``.telemetry``."""
        reqs = [
            r if isinstance(r, Request) else Request(r[0], r[1])
            for r in requests
        ]
        for r in reqs:
            need = r.prompt.size + r.max_new_tokens
            if need > self.max_len:
                raise ValueError(
                    f"request {r.request_id}: prompt {r.prompt.size} + "
                    f"max_new_tokens {r.max_new_tokens} exceeds fleet "
                    f"max_len {self.max_len}"
                )
        times = [0.0] * len(reqs) if arrival_times is None else [
            float(t) for t in arrival_times
        ]
        tens = ["default"] * len(reqs) if tenants is None else list(tenants)
        if len(times) != len(reqs) or len(tens) != len(reqs):
            raise ValueError(
                "arrival_times/tenants must match requests in length"
            )
        arrivals = sorted(
            zip(times, range(len(reqs)), reqs, tens), key=lambda a: a[:2]
        )
        ai = 0
        now = 0.0
        wall0 = time.perf_counter()
        results: Dict[int, np.ndarray] = {}
        admitted: Dict[int, Sequence] = {}
        seqs_in_order: List[Optional[Sequence]] = [None] * len(reqs)
        head: Optional[Sequence] = None  # popped from router, unplaced
        pending_handoff: List[list] = []  # [ready_at, seq, payload]
        kills: List[dict] = []
        fallback_dispatches = 0  # re-prefills: transfer off / replica lost
        gossip_adoptions: List[dict] = []  # placement-time block moves
        gossip_stale = 0  # adoptions refused by the weights-version stamp
        handoff_bytes_full = 0     # payload bytes before suffix trimming
        handoff_bytes_shipped = 0  # payload bytes actually transferred
        suffix_trims = 0           # payloads that shipped suffix-only
        queue_peak = 0
        ttft_recent: List[float] = []

        def record_finish(seq: Sequence):
            results[seq.request.request_id] = seq.output()
            self.router.observe_finish(seq.finished_at)
            ttft_recent.append(seq.first_token_at - seq.submitted_at)
            del ttft_recent[:-64]

        while True:
            progressed = False
            # -- arrivals due now --------------------------------------
            while ai < len(arrivals) and arrivals[ai][0] <= now:
                t, i, req, tenant = arrivals[ai]
                ai += 1
                adm, seq = self.router.submit(req, tenant=tenant, now=t)
                if adm.accepted:
                    admitted[req.request_id] = seq
                    seqs_in_order[i] = seq
                progressed = True
            # -- fault injection: replica-addressable kills ------------
            if self.fault is not None:
                for name, rep in sorted(self.decode_pool.items()):
                    if not rep.alive:
                        continue
                    if self.fault.should_kill_replica(name,
                                                      rep.decode_steps):
                        lost = rep.kill(now)
                        self._retire(name, now)
                        self.router.requeue(lost, now)
                        kills.append({
                            "t": round(now, 4), "replica": name,
                            "requeued": len(lost),
                            "decode_steps": rep.decode_steps,
                        })
                        events_lib.emit(
                            evs.FLEET_REPLICA_KILLED, replica=name,
                            requeued=len(lost),
                        )
                        progressed = True
            # -- autoscaling + pool reconcile --------------------------
            if self.autoscaler is not None:
                live = [
                    r for r in self.decode_pool.values() if r.alive
                ]
                qd = self.router.queue_depth + sum(
                    r.queue_depth for r in live
                ) + (1 if head is not None else 0)
                p99 = (
                    float(np.percentile(ttft_recent, 99))
                    if ttft_recent else None
                )
                self.autoscaler.decide(
                    now, queue_depth=qd, replicas=max(len(live), 1),
                    free_slots=sum(
                        r.free_slots for r in live
                        if self._ready(r, now)
                    ),
                    slots_per_replica=self.max_slots,
                    recent_p99_ttft=p99,
                )
            if self._reconcile(now):
                progressed = True
            # -- prefill completions -> decode dispatch queue ----------
            # (Extraction alone is not progress: an item that fails to
            # place goes straight back, and claiming progress for the
            # round-trip would busy-spin the loop at a stuck `now`.)
            due = [p for p in pending_handoff if p[0] <= now]
            pending_handoff[:] = [
                p for p in pending_handoff if p[0] > now
            ]
            # -- route work --------------------------------------------
            # head buffer: at most one popped-but-unplaced sequence, so
            # WFQ order is preserved while a full pool applies
            # backpressure instead of dropping the pop.
            dispatchable = due
            while True:
                if head is None:
                    head = self.router.next_request()
                if head is None:
                    break
                seq = head
                fresh = seq.num_generated == 0
                idle_prefill = next(
                    (p for p in self.prefill_pool
                     if p.busy_until <= now), None
                ) if (fresh and self.prefill_pool) else None
                if fresh and self.prefill_pool:
                    if idle_prefill is None:
                        break  # prefill pool busy: arrivals wait here
                    dt, payload = idle_prefill.prefill(seq)
                    idle_prefill.busy_until = now + dt
                    if seq.first_token_at is None:
                        seq.first_token_at = now + dt
                    if seq.finished or seq.last_token == self.eos_id:
                        seq.finished_at = now + dt
                        record_finish(seq)
                    else:
                        pending_handoff.append([
                            now + dt, seq,
                            payload if self.transfer == "blocks" else None,
                        ])
                    head = None
                    progressed = True
                    continue
                # straight to decode: requeued sequences, and fresh ones
                # when no prefill pool exists (colocated layout).
                dispatchable.append([now, seq, None])
                head = None
                progressed = True
            for item in dispatchable:
                _, seq, payload = item
                # Gossip lookup BEFORE placement: if some peer advertises
                # this sequence's prefix at the current weights version,
                # every prefix-caching replica is adoptable-warm and the
                # router may spread the load instead of pinning it.
                peer, peer_keys = None, ()
                if self.gossip is not None and payload is None:
                    keys = _chain_hashes(
                        seq.tokens[:seq.prompt_len], self.block_size
                    )
                    if keys:
                        name, run = self.gossip.best_peer(
                            keys, weights_version=self.weights_version
                        )
                        if name is not None and run > 0:
                            peer, peer_keys = name, tuple(keys[:run])
                target = self.router.place(
                    seq,
                    (r for r in self.decode_pool.values()
                     if self._ready(r, now) and r.free_slots > 0),
                    gossip_adoptable=peer is not None,
                )
                if target is None:
                    # No capacity: hold as pending, re-offered next pass.
                    pending_handoff.append([now, seq, payload])
                    continue
                if (peer is not None and peer != target.name
                        and not target.holds_prefix(seq)):
                    src = self.decode_pool.get(peer)
                    if src is not None and src.alive:
                        t0 = time.perf_counter()
                        adopted = 0
                        try:
                            pay = pack_prefix(
                                src.kv, peer_keys,
                                weights_version=self.weights_version,
                            )
                            if pay is not None:
                                adopted = adopt_prefix(
                                    target.kv, pay,
                                    weights_version=self.weights_version,
                                )
                        except HandoffIncompatible:
                            gossip_stale += 1
                        # The gather/scatter is real device work on both
                        # ends: charge each replica's own timeline, like
                        # any other dispatch.
                        dt = time.perf_counter() - t0
                        src.busy_s += dt
                        src.busy_until = max(src.busy_until, now + dt)
                        target.busy_s += dt
                        target.busy_until = max(
                            target.busy_until, now + dt
                        )
                        if adopted:
                            src.gossip_serves += 1
                            target.gossip_adopts += 1
                            target.gossip_adopt_blocks += adopted
                            gossip_adoptions.append({
                                "t": round(now, 4),
                                "request_id": seq.request.request_id,
                                "from": src.name, "to": target.name,
                                "blocks": int(adopted),
                                "copy_s": round(dt, 6),
                            })
                            events_lib.emit(
                                evs.PREFIX_GOSSIP_ADOPT,
                                replica=target.name, source=src.name,
                                blocks=int(adopted),
                                tokens=int(adopted * self.block_size),
                                weights_version=self.weights_version,
                                transport="inproc",
                            )
                if payload is None and seq.num_generated > 0:
                    # Prefilled (or partially decoded) elsewhere but the
                    # KV could not travel: the decode side re-prefills.
                    fallback_dispatches += 1
                if payload is not None:
                    # Ship only the suffix the target's prefix store does
                    # not already hold. Trimming is per-target (stores
                    # differ), so it happens at placement, not at pack.
                    handoff_bytes_full += payload.nbytes
                    payload, skipped = trim_kv(payload, target.kv.prefix)
                    handoff_bytes_shipped += payload.nbytes
                    if skipped:
                        suffix_trims += 1
                target.submit(seq, now, payload=payload)
                seq.replica = target.name
                progressed = True
            # -- step replicas on their own timelines ------------------
            for name, rep in sorted(self.decode_pool.items()):
                if not self._ready(rep, now) or rep.busy_until > now:
                    continue
                if not rep.has_work:
                    continue
                dt, finished = rep.step(now)
                rep.busy_until = now + dt
                for seq in finished:
                    record_finish(seq)
                if self.gossip is not None and rep.kv.prefix is not None:
                    # Advertise-sync after the step wrote new prefix
                    # blocks: REPLACE semantics, so local eviction
                    # propagates too (no dangling claims).
                    rep.gossip_advertised += self.gossip.advertise(
                        name, rep.kv.prefix.keys(),
                        weights_version=self.weights_version,
                    )
                progressed = True
            queue_peak = max(
                queue_peak,
                self.router.queue_depth + sum(
                    r.queue_depth for r in self.decode_pool.values()
                ) + (1 if head is not None else 0) + len(pending_handoff),
            )
            if progressed:
                continue
            # -- advance the clock to the next event -------------------
            horizon = []
            if ai < len(arrivals):
                horizon.append(arrivals[ai][0])
            horizon += [p[0] for p in pending_handoff]
            horizon += [
                r.busy_until for r in self.decode_pool.values()
                if r.busy_until > now and (r.has_work or not self._ready(
                    r, now))
            ]
            horizon += [
                p.busy_until for p in self.prefill_pool
                if p.busy_until > now
            ]
            horizon += [
                t for t in self._warming.values() if t > now
            ]
            future = [t for t in horizon if t > now]
            outstanding = (
                head is not None or pending_handoff
                or self.router.queue_depth
                or any(r.has_work for r in self.decode_pool.values())
                or ai < len(arrivals)
            )
            if not outstanding:
                break
            if not future:
                raise RuntimeError(
                    "fleet deadlock: "
                    f"{len(admitted) - len(results)} request(s) cannot be "
                    "placed — decode pool too small for the workload "
                    "(raise num_blocks/max_slots or add replicas)"
                )
            now = min(future)

        self._finalize_telemetry(
            reqs, seqs_in_order, admitted, results, kills, queue_peak,
            fallback_dispatches, wall_s=time.perf_counter() - wall0,
            handoff_bytes=(handoff_bytes_full, handoff_bytes_shipped,
                           suffix_trims),
            gossip_rows=(gossip_adoptions, gossip_stale),
        )
        out = FleetResult(
            results.get(r.request_id) for r in reqs
        )
        out.telemetry = self.last_run_telemetry
        return out

    # ----------------------------------------------------------- telemetry
    def _finalize_telemetry(self, reqs, seqs_in_order, admitted, results,
                            kills, queue_peak, fallback_dispatches,
                            wall_s, handoff_bytes=(0, 0, 0),
                            gossip_rows=((), 0)):
        fins = [s for s in admitted.values()
                if s.request.request_id in results]
        ttfts = [s.first_token_at - s.submitted_at for s in fins]
        makespan = max((s.finished_at for s in fins), default=0.0)
        useful = int(sum(
            len(results[s.request.request_id]) - s.prompt_len
            for s in fins
        ))
        rows = dict(self._retired_rows)
        rows.update({
            n: self._replica_row(r)
            for n, r in sorted(self.decode_pool.items())
        })
        tel = {
            "clock": "virtual (per-replica timelines over real dispatch "
                     "walls; single-host harness — see docs/SERVING.md "
                     "'Fleet')",
            "requests_submitted": len(reqs),
            "requests_admitted": len(admitted),
            "requests_finished": len(results),
            "lost_requests": len(admitted) - len(results),
            "generated_tokens": useful,
            "makespan_s": round(float(makespan), 4),
            "wall_s": round(float(wall_s), 4),
            "tokens_per_sec": round(useful / makespan, 3)
            if makespan > 0 else 0.0,
            "time_to_first_token": {
                "mean": round(float(np.mean(ttfts)), 4) if ttfts else None,
                "p50": round(float(np.percentile(ttfts, 50)), 4)
                if ttfts else None,
                "p99": round(float(np.percentile(ttfts, 99)), 4)
                if ttfts else None,
                "max": round(float(np.max(ttfts)), 4) if ttfts else None,
            },
            "requests": [
                None if s is None else {
                    "request_id": s.request.request_id,
                    "tenant": getattr(s, "tenant", "default"),
                    "replica": getattr(s, "replica", None),
                    "enqueued_s": round(float(s.submitted_at), 4),
                    "admitted_s": round(float(s.admitted_at), 4)
                    if s.admitted_at is not None else None,
                    "first_token_s": round(float(s.first_token_at), 4)
                    if s.first_token_at is not None else None,
                    "finished_s": round(float(s.finished_at), 4)
                    if s.finished_at is not None else None,
                    "requeues": getattr(s, "requeues", 0),
                    "preemptions": s.preemptions,
                }
                for s in seqs_in_order
            ],
            "router": self.router.telemetry(),
            "queue_depth_peak": int(queue_peak),
            "decode_pool": {
                "final_replicas": len(self.decode_pool),
                "replicas": rows,
                "events": list(self.pool_events),
                "kills": kills,
                "spinup_alloc_s": round(self.spinup_measured_s, 4),
            },
            "prefill_pool": {
                "replicas": len(self.prefill_pool),
                "prefills": sum(p.prefills for p in self.prefill_pool),
                "busy_s": round(
                    sum(p.busy_s for p in self.prefill_pool), 4
                ),
            },
            "handoffs": {
                "transfer": self.transfer,
                "installed": sum(
                    r["handoffs_installed"] for r in rows.values()
                ),
                "fallback_reprefill": fallback_dispatches + sum(
                    r["handoffs_fallback"] for r in rows.values()
                ),
                "trim_stale": sum(
                    r["handoffs_trim_stale"] for r in rows.values()
                ),
                "prefills_full": sum(
                    r["prefills_full"] for r in rows.values()
                ),
                "bytes_full": int(handoff_bytes[0]),
                "bytes_shipped": int(handoff_bytes[1]),
                "bytes_saved": int(handoff_bytes[0] - handoff_bytes[1]),
                "suffix_trims": int(handoff_bytes[2]),
            },
            "preemptions": sum(r["preemptions"] for r in rows.values()),
            "decode_steps": sum(r["decode_steps"] for r in rows.values()),
        }
        if self.autoscaler is not None:
            tel["autoscaler"] = {
                "target": self.autoscaler.target,
                "events": list(self.autoscaler.events),
            }
        if self.gossip is not None:
            adoptions, stale = gossip_rows
            tel["gossip"] = {
                **self.gossip.telemetry(),
                "weights_version": self.weights_version,
                "adoptions": len(adoptions),
                "adopted_blocks": sum(a["blocks"] for a in adoptions),
                "stale_rejected": int(stale),
                "events": list(adoptions),
            }
            # One advertise event per replica, run-aggregate granularity:
            # per-step emission would swamp the log with near-identical
            # advertisements (event-volume discipline, docs/
            # OBSERVABILITY.md).
            for name, row in sorted(rows.items()):
                if row.get("gossip_advertised"):
                    events_lib.emit(
                        evs.PREFIX_GOSSIP_ADVERTISE, replica=name,
                        blocks=int(row["gossip_advertised"]),
                        weights_version=self.weights_version,
                    )
        # Publish into the unified metrics registry: the fleet's run view
        # is a stored report (same derived-view contract as fit/engine),
        # with the SLO-facing aggregates doubled as counters/gauges for
        # the Prometheus/JSONL exporters (docs/OBSERVABILITY.md).
        from ..obs import registry as obs_registry

        reg = obs_registry.default_registry()
        reg.counter("fleet/requests_finished", tel["requests_finished"])
        reg.counter("fleet/generated_tokens", tel["generated_tokens"])
        reg.counter("fleet/preemptions", tel["preemptions"])
        reg.gauge("fleet/tokens_per_sec", tel["tokens_per_sec"])
        reg.gauge("fleet/queue_depth_peak", queue_peak)
        reg.gauge("fleet/decode_replicas", len(self.decode_pool))
        reg.gauge("fleet/handoff_bytes_saved",
                  tel["handoffs"]["bytes_saved"])
        if self.gossip is not None:
            reg.counter("fleet/gossip_adoptions",
                        tel["gossip"]["adoptions"])
            reg.counter("fleet/gossip_adopted_blocks",
                        tel["gossip"]["adopted_blocks"])
        self.last_run_telemetry = reg.set_report("fleet.run", tel)
