"""Cross-replica prefix gossip: the fleet-wide chain-hash index.

Each decode replica's :class:`~distributed_tpu.serving.kv_cache.PrefixStore`
is local: a cold replica re-earns every prefix the warm one already
computed (BENCH_prefix.json's hit_rate 0.91 is a single warm engine, not
the fleet). Gossip closes the gap with two pieces:

- **The index** (:class:`PrefixGossipIndex`, this module): replicas
  ADVERTISE their store's chain-hash keys, stamped with the weights
  version the blocks were computed under; the router consults the global
  view at placement (a replica that can adopt a remote run scores prefix
  affinity too, ties still break by queue depth), and the fleet moves
  the blocks — ``fleet.handoff.pack_prefix`` on the warm side,
  ``adopt_prefix`` on the cold side.
- **The stamp**: advertisements and payload manifests carry
  ``weights_version`` so a peer can NEVER adopt blocks computed under
  old weights — ``update_weights`` flushes every store, withdraws every
  advertisement, AND bumps the version, so even an advertisement that
  raced the swap fails the stamp check at adoption time (the
  ``PrefixStore.flush`` staleness contract, extended fleet-wide).

Advertisement is SYNC semantics, not append: each call replaces the
replica's advertised set with its store's current keys, so local
eviction (refcount-aware LRU under pool pressure) propagates on the next
sync instead of leaving dangling claims. A claim can still go stale
between sync and adoption — ``pack_prefix`` probes the live store and
returns the (possibly shorter, possibly empty) run it actually holds,
and the adopter just keeps what arrives: chain keys make any leading run
self-consistent.

Host-side bookkeeping only (numpy/jax never enter); the transport for
real-process fleets is ``serve_service.transport`` (shm ``.npy`` blocks
same-host, ``DTS1`` inline frames cross-host), whose manifests carry the
same ``weights_version`` stamp.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["PrefixGossipIndex"]


class PrefixGossipIndex:
    """Chain-hash key -> advertising replicas, with weights-version
    stamps. See the module docstring for the protocol."""

    def __init__(self):
        # replica -> {chain key -> weights_version}
        self._by_replica: Dict[str, Dict[str, int]] = {}
        self.advertised_blocks = 0   # keys newly advertised, cumulative
        self.withdrawals = 0         # replicas withdrawn (flush/retire)
        self.lookups = 0
        self.peer_hits = 0           # lookups that found an adoptable run

    # ----------------------------------------------------------- publish
    def advertise(self, replica: str, keys: Sequence[str],
                  weights_version: int = 0) -> int:
        """Replace ``replica``'s advertised set with ``keys`` at
        ``weights_version``; returns how many keys are NEW (not in its
        previous advertisement) — the advertise-event granularity."""
        old = self._by_replica.get(replica, {})
        new = {str(k): int(weights_version) for k in keys}
        added = sum(1 for k in new if k not in old)
        self._by_replica[replica] = new
        self.advertised_blocks += added
        return added

    def withdraw(self, replica: str) -> int:
        """Drop every advertisement of ``replica`` (store flushed, or the
        replica retired/killed). Returns the number of keys dropped."""
        dropped = len(self._by_replica.pop(replica, {}))
        if dropped:
            self.withdrawals += 1
        return dropped

    # ------------------------------------------------------------ lookup
    def holders(self, key: str,
                weights_version: Optional[int] = None) -> List[str]:
        """Replicas advertising ``key`` (matching the stamp when given),
        sorted by name for determinism."""
        return sorted(
            name for name, keys in self._by_replica.items()
            if key in keys and (weights_version is None
                                or keys[key] == int(weights_version))
        )

    def best_peer(self, keys: Sequence[str], *,
                  weights_version: Optional[int] = None,
                  exclude: Sequence[str] = ()
                  ) -> Tuple[Optional[str], int]:
        """The replica advertising the LONGEST leading run of ``keys``
        at ``weights_version`` (chain keys: a run is only useful from
        block 0), and that run's length. Ties break by replica name.
        ``(None, 0)`` when nobody holds even the first block."""
        self.lookups += 1
        skip = set(exclude)
        best: Tuple[Optional[str], int] = (None, 0)
        for name in sorted(self._by_replica):
            if name in skip:
                continue
            held = self._by_replica[name]
            run = 0
            for k in keys:
                if k not in held or (weights_version is not None
                                     and held[k] != int(weights_version)):
                    break
                run += 1
            if run > best[1]:
                best = (name, run)
        if best[1] > 0:
            self.peer_hits += 1
        return best

    # --------------------------------------------------------- telemetry
    def telemetry(self) -> dict:
        return {
            "replicas_advertising": sum(
                1 for keys in self._by_replica.values() if keys
            ),
            "keys_live": sum(
                len(keys) for keys in self._by_replica.values()
            ),
            "advertised_blocks": self.advertised_blocks,
            "withdrawals": self.withdrawals,
            "lookups": self.lookups,
            "peer_hits": self.peer_hits,
        }
