"""Prefill→decode KV-block handoff (the disaggregation contract).

A prefill replica computes a request's prompt KV into its OWN paged pool;
the decode replica that will run the request owns a DIFFERENT pool with
different free blocks. The handoff payload is the bridge: the sequence's
blocks, gathered to host in logical order and keyed by the
``ShardedCheckpointer`` block-layout idiom — ``<leaf-path>@<starts>@<shape>``
(``checkpoint.sharded._block_key``), where ``starts`` is the LOGICAL block
offset of the run within the sequence, not a pool index. Pool block ids are
deliberately absent from the payload: they are placement, and placement is
the receiver's business — exactly how the sharded checkpoint's restore
rebuilds a leaf under the *current* mesh from blocks keyed by global
offsets. The decode side scatters each run into whatever blocks its own
allocator granted.

When transfer is unavailable (``ServingFleet(transfer="none")``), or the
pools disagree on block size / dtype / layer structure,
:func:`install_kv` raises :class:`HandoffIncompatible` and the fleet falls
back to RE-PREFILLING the context on the decode replica — the scheduler's
preemption-requeue semantics (token-exact under greedy), paid as recompute
instead of transfer.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..checkpoint.core import iter_leaf_paths
from ..checkpoint.sharded import _block_key, _parse_key

__all__ = ["KVHandoff", "HandoffIncompatible", "pack_kv", "install_kv"]


class HandoffIncompatible(ValueError):
    """The payload cannot be installed into this pool (block size, dtype,
    or layer-structure mismatch) — the caller must re-prefill instead."""


def _cache_leaves(caches):
    """(path, leaf) pairs of the paged pools in checkpoint path order,
    plus the flatten structure for rebuilds. iter_leaf_paths (sorted dict
    keys, '#i' list entries) and jax's tree_flatten agree on ordering for
    the cache containers (dicts/lists/tuples), asserted here so a future
    container type cannot silently misalign a scatter."""
    paths = [p for p, _ in iter_leaf_paths(caches)]
    leaves, treedef = jax.tree_util.tree_flatten(caches)
    if len(paths) != len(leaves):
        raise AssertionError(
            f"cache path walk found {len(paths)} leaves but tree_flatten "
            f"found {len(leaves)} — container ordering mismatch"
        )
    return paths, leaves, treedef


@dataclasses.dataclass
class KVHandoff:
    """One sequence's cached KV, detached from any pool.

    ``blocks`` maps ``<leaf-path>@<logical-block-start>@<shape>`` to a host
    array of shape ``(n_blocks, block_size, ...)`` — the sequence's blocks
    for that attention layer, in logical order. ``cached_len`` is the
    number of POSITIONS cached (the prefilled context; the first generated
    token's KV is NOT included — its row is written by the receiver's
    first decode step, mirroring the engine's post-prefill state)."""

    blocks: Dict[str, np.ndarray]
    cached_len: int
    block_size: int
    dtype: str

    @property
    def nbytes(self) -> int:
        return int(sum(a.nbytes for a in self.blocks.values()))


def pack_kv(kv, slot: int, cached_len: int) -> KVHandoff:
    """Gather ``slot``'s first ``blocks_for(cached_len)`` blocks out of
    every layer pool into one host payload. One fancy-index gather per
    layer leaf; block ids never leave the owning pool."""
    n = kv.blocks_for(cached_len)
    ids = np.asarray(kv._slot_blocks[slot][:n], np.int32)
    if len(ids) < n:
        raise ValueError(
            f"slot {slot} owns {len(ids)} blocks but {n} are needed to "
            f"cover {cached_len} cached positions"
        )
    paths, leaves, _ = _cache_leaves(kv.caches)
    blocks = {}
    dtype = None
    for path, pool in zip(paths, leaves):
        data = np.asarray(jax.device_get(pool[ids]))
        dtype = str(pool.dtype)
        blocks[_block_key(path, (0,) * data.ndim, data.shape)] = data
    return KVHandoff(blocks=blocks, cached_len=int(cached_len),
                     block_size=int(kv.block_size), dtype=dtype or "")


def install_kv(kv, slot: int, payload: KVHandoff):
    """Scatter ``payload`` into ``slot``'s already-reserved blocks of this
    pool (reserve first: the engine's admission path grants the blocks).
    Raises :class:`HandoffIncompatible` when the pools disagree — the
    caller then re-prefills. Returns the number of blocks installed."""
    if payload.block_size != kv.block_size:
        raise HandoffIncompatible(
            f"block_size mismatch: payload {payload.block_size} vs pool "
            f"{kv.block_size}"
        )
    need = kv.blocks_for(payload.cached_len)
    ids = kv._slot_blocks[slot]
    if len(ids) < need:
        raise ValueError(
            f"slot {slot} has {len(ids)} reserved blocks but the payload "
            f"covers {need} — reserve the sequence's context first"
        )
    paths, leaves, treedef = _cache_leaves(kv.caches)
    by_path: Dict[str, list] = {}
    for key, data in payload.blocks.items():
        path, starts, _shape = _parse_key(key)
        by_path.setdefault(path, []).append((starts[0] if starts else 0,
                                             data))
    if set(by_path) != set(paths):
        raise HandoffIncompatible(
            "layer structure mismatch between prefill and decode pools "
            f"(payload layers {sorted(by_path)[:3]}... vs pool "
            f"{sorted(paths)[:3]}...)"
        )
    installed = 0
    new_leaves = []
    for path, pool in zip(paths, leaves):
        if str(pool.dtype) != payload.dtype:
            raise HandoffIncompatible(
                f"dtype mismatch on {path}: payload {payload.dtype} vs "
                f"pool {pool.dtype}"
            )
        for start, data in sorted(by_path[path]):
            run = np.asarray(ids[start:start + data.shape[0]], np.int32)
            pool = pool.at[jnp.asarray(run)].set(
                jnp.asarray(data, pool.dtype)
            )
            installed += int(data.shape[0])
        new_leaves.append(pool)
    kv.caches = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return installed
