"""Prefill→decode KV-block handoff (the disaggregation contract).

A prefill replica computes a request's prompt KV into its OWN paged pool;
the decode replica that will run the request owns a DIFFERENT pool with
different free blocks. The handoff payload is the bridge: the sequence's
blocks, gathered to host in logical order and keyed by the
``ShardedCheckpointer`` block-layout idiom — ``<leaf-path>@<starts>@<shape>``
(``checkpoint.sharded._block_key``), where ``starts`` is the LOGICAL block
offset of the run within the sequence, not a pool index. Pool block ids are
deliberately absent from the payload: they are placement, and placement is
the receiver's business — exactly how the sharded checkpoint's restore
rebuilds a leaf under the *current* mesh from blocks keyed by global
offsets. The decode side scatters each run into whatever blocks its own
allocator granted.

When transfer is unavailable (``ServingFleet(transfer="none")``), or the
pools disagree on block size / dtype / layer structure,
:func:`install_kv` raises :class:`HandoffIncompatible` and the fleet falls
back to RE-PREFILLING the context on the decode replica — the scheduler's
preemption-requeue semantics (token-exact under greedy), paid as recompute
instead of transfer.

**Suffix-only shipping.** When the decode side runs a prefix store
(``PagedKVCache(prefix_cache=True)``), the sender attaches the prompt's
chain hashes (``serving.kv_cache._chain_hashes`` — the prefix store's own
keys) to the payload, and :func:`trim_kv` drops the leading blocks the
receiver already holds: only the non-cached SUFFIX travels. The receiver's
admission adopts the cached prefix out of its store (refcounted, CoW on
divergence) and :func:`install_kv` scatters just the shipped tail. If the
store evicted between trim and admission, the receiver detects the gap
(``payload.skip_blocks`` exceeds its adopted span) and falls back to
re-prefill — never a silent hole in the cache.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..checkpoint.core import iter_leaf_paths
from ..checkpoint.sharded import _block_key, _parse_key
from ..serving.kv_cache import _chain_hashes

__all__ = [
    "KVHandoff", "HandoffIncompatible", "pack_kv", "install_kv", "trim_kv",
    "pack_prefix", "adopt_prefix",
]


class HandoffIncompatible(ValueError):
    """The payload cannot be installed into this pool (block size, dtype,
    or layer-structure mismatch) — the caller must re-prefill instead."""


def _block_axis(path: str) -> int:
    """Pool-block axis for the leaf at ``path``: 0 for ordinary per-layer
    pools, 1 for stacked-block pools — a ``stacked`` path segment is the
    ``nn.scan.STACKED_POOL_KEY`` contract marking leaves whose LEADING dim
    is the block-stack (ScannedBlocks / PipelinedBlocks), with pool blocks
    on axis 1. Gathers/scatters and the logical-start key index follow it."""
    return 1 if "stacked" in path.split("/") else 0


def _cache_leaves(caches):
    """(path, leaf) pairs of the paged pools in checkpoint path order,
    plus the flatten structure for rebuilds. iter_leaf_paths (sorted dict
    keys, '#i' list entries) and jax's tree_flatten agree on ordering for
    the cache containers (dicts/lists/tuples), asserted here so a future
    container type cannot silently misalign a scatter."""
    paths = [p for p, _ in iter_leaf_paths(caches)]
    leaves, treedef = jax.tree_util.tree_flatten(caches)
    if len(paths) != len(leaves):
        raise AssertionError(
            f"cache path walk found {len(paths)} leaves but tree_flatten "
            f"found {len(leaves)} — container ordering mismatch"
        )
    return paths, leaves, treedef


@dataclasses.dataclass
class KVHandoff:
    """One sequence's cached KV, detached from any pool.

    ``blocks`` maps ``<leaf-path>@<logical-block-start>@<shape>`` to a host
    array of shape ``(n_blocks, block_size, ...)`` — the sequence's blocks
    for that attention layer, in logical order. ``cached_len`` is the
    number of POSITIONS cached (the prefilled context; the first generated
    token's KV is NOT included — its row is written by the receiver's
    first decode step, mirroring the engine's post-prefill state).

    ``dtype`` is descriptive (the last pool leaf's dtype, for telemetry);
    compatibility is checked per leaf at install time, because a
    quantized int8 pool flattens to MIXED leaves (int8 ``q`` plus float32
    ``scale``) that no single dtype string can gate.

    ``prefix_hashes`` are the prompt's chain hashes (one per FULL block,
    ``serving.kv_cache._chain_hashes``) and ``skip_blocks`` how many
    leading blocks :func:`trim_kv` dropped because the receiver's prefix
    store already held them (0 = full payload).

    ``weights_version`` stamps which weights the KV was computed under
    (gossip payloads; None = unstamped, the prefill→decode path where
    both sides share one fleet clock). :func:`adopt_prefix` refuses a
    stamp mismatch — stale blocks must never outlive ``update_weights``
    by travelling."""

    blocks: Dict[str, np.ndarray]
    cached_len: int
    block_size: int
    dtype: str
    prefix_hashes: tuple = ()
    skip_blocks: int = 0
    weights_version: Optional[int] = None

    @property
    def nbytes(self) -> int:
        return int(sum(a.nbytes for a in self.blocks.values()))


def pack_kv(kv, slot: int, cached_len: int, tokens=None) -> KVHandoff:
    """Gather ``slot``'s first ``blocks_for(cached_len)`` blocks out of
    every layer pool into one host payload. One fancy-index gather per
    layer leaf; block ids never leave the owning pool.

    ``tokens``: the cached context's token ids; when given, the payload
    carries their chain hashes so a prefix-caching receiver can
    :func:`trim_kv` the blocks it already holds."""
    n = kv.blocks_for(cached_len)
    ids = np.asarray(kv._slot_blocks[slot][:n], np.int32)
    if len(ids) < n:
        raise ValueError(
            f"slot {slot} owns {len(ids)} blocks but {n} are needed to "
            f"cover {cached_len} cached positions"
        )
    paths, leaves, _ = _cache_leaves(kv.caches)
    blocks = {}
    dtype = None
    for path, pool in zip(paths, leaves):
        ax = _block_axis(path)
        data = np.asarray(jax.device_get(
            pool[ids] if ax == 0 else pool[:, ids]
        ))
        dtype = str(pool.dtype)
        blocks[_block_key(path, (0,) * data.ndim, data.shape)] = data
    hashes = ()
    if tokens is not None:
        hashes = tuple(_chain_hashes(
            [int(t) for t in tokens[:cached_len]], kv.block_size
        ))
    return KVHandoff(blocks=blocks, cached_len=int(cached_len),
                     block_size=int(kv.block_size), dtype=dtype or "",
                     prefix_hashes=hashes)


def install_kv(kv, slot: int, payload: KVHandoff):
    """Scatter ``payload`` into ``slot``'s already-reserved blocks of this
    pool (reserve first: the engine's admission path grants the blocks).
    Raises :class:`HandoffIncompatible` when the pools disagree — the
    caller then re-prefills. Returns the number of blocks installed."""
    if payload.block_size != kv.block_size:
        raise HandoffIncompatible(
            f"block_size mismatch: payload {payload.block_size} vs pool "
            f"{kv.block_size}"
        )
    need = kv.blocks_for(payload.cached_len)
    ids = kv._slot_blocks[slot]
    if len(ids) < need:
        raise ValueError(
            f"slot {slot} has {len(ids)} reserved blocks but the payload "
            f"covers {need} — reserve the sequence's context first"
        )
    if not payload.blocks:
        # Fully trimmed: every cached block was adopted from the
        # receiver's prefix store; nothing travels, nothing to scatter.
        return 0
    paths, leaves, treedef = _cache_leaves(kv.caches)
    by_path: Dict[str, list] = {}
    for key, data in payload.blocks.items():
        path, starts, _shape = _parse_key(key)
        ax = _block_axis(path)
        by_path.setdefault(path, []).append(
            (starts[ax] if len(starts) > ax else 0, data)
        )
    if set(by_path) != set(paths):
        raise HandoffIncompatible(
            "layer structure mismatch between prefill and decode pools "
            f"(payload layers {sorted(by_path)[:3]}... vs pool "
            f"{sorted(paths)[:3]}...)"
        )
    installed = 0
    new_leaves = []
    for path, pool in zip(paths, leaves):
        ax = _block_axis(path)
        for start, data in sorted(by_path[path]):
            # Per-LEAF dtype gate: an int8 pool's leaves are int8 ``q``
            # plus float32 ``scale`` — each shipped run must match its
            # own destination leaf, not one payload-wide dtype string.
            if str(pool.dtype) != str(data.dtype):
                raise HandoffIncompatible(
                    f"dtype mismatch on {path}: payload {data.dtype} vs "
                    f"pool {pool.dtype}"
                )
            run = np.asarray(ids[start:start + data.shape[ax]], np.int32)
            idx = jnp.asarray(run)
            if ax == 0:
                pool = pool.at[idx].set(jnp.asarray(data, pool.dtype))
            else:
                pool = pool.at[:, idx].set(jnp.asarray(data, pool.dtype))
            installed += int(data.shape[ax])
        new_leaves.append(pool)
    kv.caches = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return installed


def pack_prefix(kv, keys, *,
                weights_version: Optional[int] = None) -> Optional[KVHandoff]:
    """Gather the prefix-store blocks for the leading run of chain
    ``keys`` this pool holds, into a :class:`KVHandoff`-shaped payload —
    the gossip export side. Unlike :func:`pack_kv` the blocks belong to
    the STORE, not a slot: a finished request's warm prompt blocks travel
    to a cold peer without any live sequence being involved. Returns None
    when the store holds none of ``keys`` (nothing to ship).

    The probe uses ``PrefixStore.peek_run`` — exporting is not an
    admission, so hit/miss telemetry and LRU order stay untouched."""
    store = getattr(kv, "prefix", None)
    if store is None or not keys:
        return None
    ids_list = store.peek_run(list(keys))
    if not ids_list:
        return None
    ids = np.asarray(ids_list, np.int32)
    paths, leaves, _ = _cache_leaves(kv.caches)
    blocks = {}
    dtype = None
    for path, pool in zip(paths, leaves):
        ax = _block_axis(path)
        data = np.asarray(jax.device_get(
            pool[ids] if ax == 0 else pool[:, ids]
        ))
        dtype = str(pool.dtype)
        blocks[_block_key(path, (0,) * data.ndim, data.shape)] = data
    return KVHandoff(
        blocks=blocks, cached_len=len(ids_list) * kv.block_size,
        block_size=int(kv.block_size), dtype=dtype or "",
        prefix_hashes=tuple(keys[:len(ids_list)]),
        weights_version=weights_version,
    )


def adopt_prefix(kv, payload: KVHandoff, *,
                 weights_version: Optional[int] = None) -> int:
    """Install a gossiped prefix run into THIS pool's prefix store — the
    import side of :func:`pack_prefix`. Fresh blocks are allocated
    (store-owned: one reference each, exactly a local ``insert_prefix``'s
    accounting), the payload's rows scattered in, and each chain key
    registered; subsequent admissions adopt them through the normal
    ``PrefixStore.lookup`` path, so everything downstream — refcounts,
    CoW, eviction — is indistinguishable from a locally-earned prefix.

    Keys already cached are skipped (first writer wins); the walk stops
    at the first allocation failure, leaving a shorter-but-valid leading
    run (chain keys make any prefix of a run self-consistent). Raises
    :class:`HandoffIncompatible` on pool disagreement — the caller then
    just re-prefills as if no peer had answered. Returns the number of
    blocks newly adopted."""
    store = getattr(kv, "prefix", None)
    if store is None:
        raise HandoffIncompatible(
            "adopt_prefix on a pool without a prefix store"
        )
    if payload.block_size != kv.block_size:
        raise HandoffIncompatible(
            f"block_size mismatch: payload {payload.block_size} vs pool "
            f"{kv.block_size}"
        )
    # The staleness stamp: a payload computed under different weights
    # must never enter the store — an advertisement that raced an
    # update_weights flush dies HERE, not as silently-wrong KV.
    if (weights_version is not None
            and payload.weights_version is not None
            and int(payload.weights_version) != int(weights_version)):
        raise HandoffIncompatible(
            f"stale gossip payload: weights_version "
            f"{payload.weights_version} vs current {weights_version}"
        )
    keys = list(payload.prefix_hashes)
    if not keys or not payload.blocks:
        return 0
    paths, leaves, treedef = _cache_leaves(kv.caches)
    by_path: Dict[str, np.ndarray] = {}
    for bkey, data in payload.blocks.items():
        path, _starts, _shape = _parse_key(bkey)
        by_path[path] = data
    if set(by_path) != set(paths):
        raise HandoffIncompatible(
            "layer structure mismatch between gossip peer and local pool "
            f"(payload layers {sorted(by_path)[:3]}... vs pool "
            f"{sorted(paths)[:3]}...)"
        )
    for path, pool in zip(paths, leaves):
        data = by_path[path]
        if str(pool.dtype) != str(data.dtype):
            raise HandoffIncompatible(
                f"dtype mismatch on {path}: payload {data.dtype} vs "
                f"pool {pool.dtype}"
            )
    # Chain property: only a LEADING run whose predecessors are all
    # cached (locally or by this adoption) is admissible. Walk in chain
    # order, allocating only for the missing keys.
    src_index: list = []
    dst_blocks: list = []
    adopt_keys: list = []
    for i, key in enumerate(keys):
        if key in store:
            continue  # first writer wins; the chain stays contiguous
        grant = kv._allocate(1)
        if grant is None:
            break  # pool dry: keep the shorter leading run
        src_index.append(i)
        dst_blocks.append(grant[0])
        adopt_keys.append(key)
    if not adopt_keys:
        return 0
    src = np.asarray(src_index, np.int32)
    dst = jnp.asarray(np.asarray(dst_blocks, np.int32))
    new_leaves = []
    for path, pool in zip(paths, leaves):
        ax = _block_axis(path)
        data = by_path[path]
        if ax == 0:
            pool = pool.at[dst].set(jnp.asarray(data[src], pool.dtype))
        else:
            pool = pool.at[:, dst].set(
                jnp.asarray(data[:, src], pool.dtype)
            )
        new_leaves.append(pool)
    kv.caches = jax.tree_util.tree_unflatten(treedef, new_leaves)
    for key, block in zip(adopt_keys, dst_blocks):
        if not store.insert(key, block):
            # Lost the race to a concurrent insert: give the block back.
            kv.allocator.decref([block])
    return len(adopt_keys)


def trim_kv(payload: KVHandoff, store) -> Tuple[KVHandoff, int]:
    """Drop the leading blocks the receiving replica's prefix ``store``
    already holds (a contiguous chain-hash hit run), returning
    ``(trimmed payload, blocks dropped)``. The original payload is not
    mutated — a failed placement can be re-offered to a different
    replica, whose store may hold a different prefix.

    The trimmed payload records ``skip_blocks`` so the receiver can
    verify at install time that its store STILL covers the gap (eviction
    may race the transfer) and fall back to re-prefill otherwise."""
    if store is None or not payload.prefix_hashes or not payload.blocks:
        return payload, 0
    skip = 0
    for key in payload.prefix_hashes:
        if key in store:
            skip += 1
        else:
            break
    n_blocks = -(-payload.cached_len // payload.block_size)
    skip = min(skip, n_blocks)
    if skip == 0:
        return payload, 0
    blocks: Dict[str, np.ndarray] = {}
    for key, data in payload.blocks.items():
        path, starts, _shape = _parse_key(key)
        ax = _block_axis(path)
        first = starts[ax] if len(starts) > ax else 0
        if data.shape[ax] + first <= skip:
            continue  # this run is entirely inside the cached prefix
        keep = max(skip - first, 0)
        rest = data[keep:] if ax == 0 else data[:, keep:]
        new_starts = tuple(
            s + keep if i == ax else s for i, s in enumerate(starts)
        )
        blocks[_block_key(path, new_starts, rest.shape)] = rest
    trimmed = dataclasses.replace(
        payload, blocks=blocks, skip_blocks=int(skip)
    )
    return trimmed, int(skip)
