"""Fleet workers: step-driven prefill and decode replicas.

The serving :class:`~distributed_tpu.serving.Engine` is a closed loop —
``run(requests)`` to completion, one engine, one pool. A fleet needs the
same mechanics OPENED UP: a router interleaves many replicas, kills some
mid-request, and spins up new ones, so each replica here advances by ONE
scheduling iteration per ``step()`` call and reports how long its device
work took, leaving the clock and the request lifecycle to the fleet.

Replicas of one fleet share compiled dispatches through
:class:`EnginePrograms` — the prefill/decode jit programs are keyed by
shape, not by replica, so spinning up a decode replica costs pool
allocation, NOT a retrace (and in production the persistent compile cache
bounds even the first trace: BENCH_compile_cache.json, restart-to-first-
step 2.23s→1.22s warm). That is what makes queue-depth autoscaling
(``fleet.autoscale``) cheap enough to react to bursts.

Scheduling semantics inside a decode replica are exactly the engine's
(``serving.scheduler``): FIFO admission when slots + blocks allow, at most
one prefill chunk between decode steps, youngest-first preemption under
pool pressure. What is new is the boundary: sequences arrive through
``submit()`` (optionally carrying a prefill replica's KV payload —
``fleet.handoff``), and ``kill()`` returns every in-flight sequence for
the router to re-queue (generated tokens ride along; re-prefill on the
next replica makes the recovery token-exact under greedy, the preemption
contract generalized across replicas).
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ..serving.engine import (
    _decode_dispatch, _mix_seed, _prefill_dispatch, _token_key,
)
from ..serving.kv_cache import PagedKVCache, _chain_hashes
from ..serving.scheduler import Scheduler, Sequence
from .handoff import HandoffIncompatible, KVHandoff, install_kv, pack_kv

__all__ = ["EnginePrograms", "PrefillReplica", "DecodeReplica"]


class EnginePrograms:
    """The compiled serving dispatches of one model, shared fleet-wide.

    Holds the jitted prefill/decode callables (same construction as
    ``serving.Engine``: jit under the model's strategy/precision scopes,
    caches donated) plus the sampling configuration and the RNG stream.
    Every replica built from the same ``EnginePrograms`` reuses the same
    XLA programs — replica count never multiplies compiles."""

    def __init__(self, model, *, temperature: float = 0.0,
                 top_k: Optional[int] = None, seed: int = 0,
                 decode_kernel: str = "reference"):
        if not model.built:
            raise RuntimeError("Model not built")
        from ..ops import paged_attention as paged_ops
        if decode_kernel not in paged_ops.KINDS:
            raise ValueError(
                f"decode_kernel must be one of {paged_ops.KINDS}, got "
                f"{decode_kernel!r}"
            )
        self.model = model
        self.temperature = float(temperature)
        self.top_k = top_k
        self.seed = int(seed)
        self.decode_kernel = decode_kernel
        self.prefill_fn = model._scoped(jax.jit(
            functools.partial(
                _prefill_dispatch, model.module, self.temperature,
                self.top_k, model.precision, model._dtype_hints,
            ),
            donate_argnums=(2,),
        ))
        decode_fn = model._scoped(jax.jit(
            functools.partial(
                _decode_dispatch, model.module, self.temperature,
                self.top_k, model.precision, model._dtype_hints,
            ),
            donate_argnums=(2,),
        ))
        if decode_kernel == paged_ops.FUSED:
            # Same trace-time selection as Engine._with_kernel: the scope
            # is ambient while the decode dispatch first traces, so every
            # replica sharing these programs rides the fused kernel.
            inner = decode_fn

            @functools.wraps(inner)
            def decode_fn(*args, **kwargs):
                with paged_ops.decode_kernel_scope(paged_ops.FUSED):
                    return inner(*args, **kwargs)
        self.decode_fn = decode_fn

    def token_key(self, seq: Sequence) -> np.ndarray:
        """Per-request, per-token sampling key (the engine's derivation):
        depends only on (fleet seed, request seed, generated-token index),
        so a sampled request decodes the same tokens whichever replica —
        or post-kill re-queue — runs it."""
        r = seq.request
        return _token_key(
            _mix_seed(
                self.seed,
                r.seed if getattr(r, "seed", None) is not None
                else r.request_id,
            ),
            seq.num_generated,
        )


def _bucket(c: int, start: int, max_len: int) -> int:
    """Engine's prefill-length bucketing (multiples of 64, capped at the
    positional table) — shared so fleet prefills hit the same compiles."""
    return min(max(64, -(-c // 64) * 64), max_len - start)


class _ReplicaBase:
    """Pool + program plumbing common to both replica kinds."""

    def __init__(self, name: str, programs: EnginePrograms, *,
                 max_slots: int, block_size: int, max_len: int,
                 num_blocks: Optional[int] = None,
                 prefix_cache: bool = False):
        self.name = name
        self.programs = programs
        model = programs.model
        self.max_slots = int(max_slots)
        self.block_size = int(block_size)
        self.max_len = int(max_len)
        nb_per_seq = -(-self.max_len // self.block_size)
        if num_blocks is None:
            num_blocks = self.max_slots * nb_per_seq + 1
        self.kv = PagedKVCache(
            model.module, model.params,
            max_slots=self.max_slots, block_size=self.block_size,
            max_blocks_per_seq=nb_per_seq, num_blocks=int(num_blocks),
            dtype=model.decode_dtype(), prefix_cache=bool(prefix_cache),
        )
        self.alive = True
        self.busy_until = 0.0  # this replica's own (virtual) timeline
        self.busy_s = 0.0  # cumulative device seconds

    def _run_prefill_chunk(self, seq: Sequence, start: int, c: int,
                           last_idx: int):
        """One prefill dispatch over positions [start, start+c) of
        ``seq``'s context on slot ``seq.slot``; returns (sampled token,
        measured seconds)."""
        model = self.programs.model
        cb = _bucket(c, start, self.max_len)
        buf = np.zeros((1, cb), np.int32)
        buf[0, :c] = seq.tokens[start:start + c]
        t0 = time.perf_counter()
        tok, _logp, self.kv.caches = self.programs.prefill_fn(
            model.params, model.state, self.kv.caches, buf,
            self.kv.block_tables[seq.slot], np.int32(start),
            np.int32(last_idx), self.programs.token_key(seq),
        )
        tok = int(jax.device_get(tok))
        return tok, time.perf_counter() - t0


class PrefillReplica(_ReplicaBase):
    """One-sequence-at-a-time prompt worker: fills its scratch pool,
    samples the first token (the fleet's TTFT moment), packs the blocks
    into a :class:`~distributed_tpu.fleet.handoff.KVHandoff`, and frees
    the pool for the next prompt. ``prefill_chunk`` bounds positions per
    dispatch exactly like the engine's."""

    def __init__(self, name: str, programs: EnginePrograms, *,
                 block_size: int, max_len: int,
                 prefill_chunk: Optional[int] = None):
        super().__init__(name, programs, max_slots=1,
                         block_size=block_size, max_len=max_len)
        self.prefill_chunk = (
            int(prefill_chunk) if prefill_chunk is not None else None
        )
        self.prefills = 0

    def prefill(self, seq: Sequence) -> Tuple[float, KVHandoff]:
        """Prefill ``seq``'s whole current context, append the sampled
        next token, and return (device seconds, payload for the decode
        side). The payload covers the PRE-SAMPLE context; the sampled
        token's KV row is written by the receiver's first decode step."""
        total = seq.context_len
        if not self.kv.reserve(0, total):
            raise RuntimeError(
                f"{self.name}: context of {total} tokens does not fit the "
                f"prefill scratch pool ({self.kv.allocator.num_allocatable}"
                " blocks)"
            )
        seq.slot = 0
        step = self.prefill_chunk or total
        chunks = [(s, min(step, total - s)) for s in range(0, total, step)]
        spent = 0.0
        tok = None
        for i, (start, c) in enumerate(chunks):
            last = (total - 1 - start) if i == len(chunks) - 1 else c - 1
            tok, dt = self._run_prefill_chunk(seq, start, c, last)
            spent += dt
        # Chain hashes ride along so a prefix-caching decode replica can
        # trim the payload to the non-cached suffix (fleet.handoff).
        payload = pack_kv(self.kv, 0, total, tokens=seq.tokens[:total])
        self.kv.release(0)
        seq.slot = None
        seq.tokens.append(int(tok))
        seq.num_generated += 1
        self.prefills += 1
        self.busy_s += spent
        return spent, payload


class DecodeReplica(_ReplicaBase):
    """Continuous-batching decode worker, advanced one iteration per
    ``step()``. Mirrors the engine loop body: admit as many waiting
    sequences as slots+blocks allow (installing handed-off KV when the
    payload is compatible, else queuing a re-prefill job), run at most
    one prefill chunk, then one fixed-shape decode step over every ready
    slot, preempting the youngest under pool pressure."""

    def __init__(self, name: str, programs: EnginePrograms, *,
                 max_slots: int, block_size: int, max_len: int,
                 num_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 prefix_cache: bool = False):
        super().__init__(name, programs, max_slots=max_slots,
                         block_size=block_size, max_len=max_len,
                         num_blocks=num_blocks, prefix_cache=prefix_cache)
        self.prefill_chunk = (
            int(prefill_chunk) if prefill_chunk is not None else None
        )
        self.eos_id = eos_id
        self.sched = Scheduler(self.max_slots)
        self._handoffs: Dict[int, KVHandoff] = {}  # request_id -> payload
        self._prefill_jobs: List[list] = []
        self.decode_steps = 0
        self.prefill_dispatches = 0
        self.preemptions = 0
        self.handoffs_installed = 0
        self.handoffs_fallback = 0
        self.handoffs_trim_stale = 0  # trimmed prefix evicted pre-admit
        self.prefills_full = 0        # prefill jobs that started at pos 0
        self.gossip_adopts = 0        # remote prefix runs installed here
        self.gossip_adopt_blocks = 0  # blocks those runs carried
        self.gossip_serves = 0        # runs packed here for a peer
        self.gossip_advertised = 0    # keys newly advertised (cumulative)

    # ------------------------------------------------------------ signals
    @property
    def queue_depth(self) -> int:
        return len(self.sched.waiting)

    @property
    def running(self) -> int:
        return len(self.sched.running)

    @property
    def in_flight(self) -> int:
        return self.queue_depth + self.running

    @property
    def free_slots(self) -> int:
        return self.max_slots - self.running

    @property
    def free_blocks(self) -> int:
        return self.kv.allocator.num_free

    @property
    def has_work(self) -> bool:
        return not self.sched.idle or bool(self._prefill_jobs)

    def holds_prefix(self, seq: Sequence) -> bool:
        """True when this replica's prefix store already caches the
        sequence's leading prompt block — the router's placement
        affinity signal (a hit means warm-cache admission and, under
        block transfer, a suffix-only payload)."""
        store = self.kv.prefix
        if store is None:
            return False
        keys = _chain_hashes(seq.tokens[:self.block_size],
                             self.block_size)
        return bool(keys) and keys[0] in store

    # ----------------------------------------------------------- lifecycle
    def submit(self, seq: Sequence, now: float,
               payload: Optional[KVHandoff] = None) -> None:
        self.sched.enqueue(seq, now)
        if payload is not None:
            self._handoffs[seq.request.request_id] = payload

    def kill(self, now: float) -> List[Sequence]:
        """Tear the replica down: every in-flight sequence (running,
        oldest first, then queued) is detached — slots cleared, pool
        dropped with the replica — and returned for the router to
        re-queue. Generated tokens ride along; KV (and any pending
        handoff payloads) die here, so the next replica re-prefills."""
        self.alive = False
        lost = list(self.sched.running) + list(self.sched.waiting)
        for seq in lost:
            seq.slot = None
        self.sched.running.clear()
        self.sched.waiting.clear()
        self._prefill_jobs.clear()
        self._handoffs.clear()
        return lost

    # ---------------------------------------------------------------- step
    def _admit(self, now: float):
        while True:
            seq = self.sched.next_admittable(self.kv)
            if seq is None:
                break
            if seq.admitted_at is None:
                seq.admitted_at = now
            payload = self._handoffs.pop(seq.request.request_id, None)
            if (payload is not None and payload.skip_blocks > 0
                    and payload.skip_blocks * self.kv.block_size
                    > seq.cached_len):
                # The payload was trimmed against this store, but the
                # trimmed prefix was evicted before admission could
                # adopt it: the shipped suffix no longer joins up with
                # resident blocks. Re-prefill instead of leaving a hole.
                self.handoffs_trim_stale += 1
                self.handoffs_fallback += 1
                payload = None
            if payload is not None:
                try:
                    install_kv(self.kv, seq.slot, payload)
                    # Post-prefill engine state: positions = cached
                    # context, last token decodes next.
                    self.kv.positions[seq.slot] = payload.cached_len
                    if self.kv.prefix is not None:
                        self.kv.insert_prefix(
                            seq.slot, seq.tokens[:seq.prompt_len]
                        )
                    self.handoffs_installed += 1
                    continue
                except HandoffIncompatible:
                    self.handoffs_fallback += 1
            # No payload (transfer off, replica lost, or preempted here):
            # prefill the current context — prompt plus any tokens
            # generated before the requeue, minus positions the prefix
            # store already adopted (seq.cached_len) — and sample the
            # next token from its last position, exactly the engine's
            # re-admission path. Greedy parity makes the recompute
            # token-exact.
            total = seq.context_len
            begin = min(seq.cached_len, total - 1)
            if begin == 0:
                # Nothing cached at all — the whole context recomputes.
                # This is the counter prefix gossip exists to keep at
                # zero for shared prefixes (fleet telemetry aggregates
                # it as handoffs.prefills_full).
                self.prefills_full += 1
            step = self.prefill_chunk or (total - begin)
            chunks = [
                (s, min(step, total - s)) for s in range(begin, total, step)
            ]
            self._prefill_jobs.append([seq, chunks, 0])

    def step(self, now: float) -> Tuple[float, List[Sequence]]:
        """One scheduling iteration at fleet time ``now``. Returns
        (device seconds spent, sequences finished). Lifecycle timestamps
        are stamped at ``now + spent-so-far`` — the moment the token
        exists on this replica's own timeline."""
        if not self.alive:
            raise RuntimeError(f"{self.name} is dead")
        spent = 0.0
        finished: List[Sequence] = []

        def finish(seq, at):
            self.sched.finish(seq, self.kv)
            seq.finished_at = at
            finished.append(seq)

        self._admit(now)
        if (not self.sched.running and not self._prefill_jobs
                and self.sched.waiting):
            # Nothing running and the queue head cannot be admitted:
            # nothing will ever free a block here — fail loud (the
            # engine's empty-pool guard, per replica).
            head = self.sched.waiting[0]
            raise RuntimeError(
                f"{self.name}: request {head.request.request_id} needs "
                f"{self.kv.blocks_for(head.context_len)} blocks but the "
                f"pool only has {self.kv.allocator.num_allocatable} "
                "allocatable — raise num_blocks or lower max_len"
            )
        # -- one prefill chunk ------------------------------------------
        if self._prefill_jobs:
            job = self._prefill_jobs[0]
            seq, chunks, idx = job
            if seq.slot is None:  # preempted mid-prefill: job is moot
                self._prefill_jobs.pop(0)
            else:
                start, c = chunks[idx]
                is_last = idx == len(chunks) - 1
                total = chunks[-1][0] + chunks[-1][1]
                last = (total - 1 - start) if is_last else c - 1
                tok, dt = self._run_prefill_chunk(seq, start, c, last)
                spent += dt
                self.prefill_dispatches += 1
                job[2] = idx + 1
                if job[2] == len(chunks):
                    self._prefill_jobs.pop(0)
                    self.kv.positions[seq.slot] = total
                    if self.kv.prefix is not None:
                        self.kv.insert_prefix(
                            seq.slot, seq.tokens[:seq.prompt_len]
                        )
                    seq.tokens.append(tok)
                    seq.num_generated += 1
                    if seq.first_token_at is None:
                        seq.first_token_at = now + spent
                    if seq.finished or tok == self.eos_id:
                        finish(seq, now + spent)
        # -- decode: every running, fully-cached slot -------------------
        mid_prefill = {
            id(j[0]) for j in self._prefill_jobs if j[0].slot is not None
        }
        ready = [
            s for s in self.sched.running if id(s) not in mid_prefill
        ]
        for seq in ready:
            if seq.slot is None:
                continue  # evicted by an older peer this pass
            while not self.kv.reserve(seq.slot, seq.context_len):
                victim = self.sched.preempt_youngest(self.kv, protect=seq)
                if victim is None:
                    raise RuntimeError(
                        f"{self.name}: request "
                        f"{seq.request.request_id} cannot back "
                        f"{seq.context_len} positions with "
                        f"{self.kv.num_blocks - 1} pool blocks even alone"
                        " — raise num_blocks"
                    )
                self.preemptions += 1
                victim.enqueued_at = now
                self._handoffs.pop(victim.request.request_id, None)
                self._prefill_jobs[:] = [
                    j for j in self._prefill_jobs if j[0] is not victim
                ]
        ready = [s for s in ready if s.slot is not None]
        if ready:
            model = self.programs.model
            tokens = np.zeros((self.max_slots,), np.int32)
            mask = np.zeros((self.max_slots,), bool)
            keys = np.zeros((self.max_slots, 2), np.uint32)
            for seq in ready:
                tokens[seq.slot] = seq.last_token
                mask[seq.slot] = True
                keys[seq.slot] = self.programs.token_key(seq)
            tables = np.where(
                mask[:, None], self.kv.block_tables, np.int32(0)
            )
            positions = np.where(mask, self.kv.positions, 0).astype(
                np.int32
            )
            t0 = time.perf_counter()
            sampled, _logps, self.kv.caches = self.programs.decode_fn(
                model.params, model.state, self.kv.caches, tokens,
                tables, positions, keys,
            )
            sampled = np.asarray(jax.device_get(sampled))
            spent += time.perf_counter() - t0
            self.decode_steps += 1
            for seq in ready:
                tok = int(sampled[seq.slot])
                self.kv.positions[seq.slot] = seq.context_len
                seq.tokens.append(tok)
                seq.num_generated += 1
                if seq.num_generated == 1 and seq.first_token_at is None:
                    seq.first_token_at = now + spent
                if seq.finished or tok == self.eos_id:
                    finish(seq, now + spent)
        self.busy_s += spent
        return spent, finished
