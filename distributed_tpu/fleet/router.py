"""Fleet front door: bounded queues, SLO-aware admission, tenant fairness.

The router is pure host-side arithmetic (no device work, no threads), the
same discipline as ``serving.Scheduler`` — which makes every decision
testable from a synthetic trace:

- **Bounded queue**: ``max_queue`` caps waiting requests; beyond it,
  arrivals are REJECTED at submit time (reason ``"queue_full"``). An
  unbounded queue turns overload into unbounded latency for everyone;
  a bounded one turns it into fast feedback for the excess.
- **SLO-aware admission**: with ``slo_ttft_s`` set, an arrival is
  rejected (reason ``"slo"``) when the router's own estimate of its
  time-to-first-token — queue ahead of it divided by the fleet's
  observed service rate — already exceeds the SLO. The estimate uses a
  sliding window of recent completions (``observe_finish``); until
  enough completions exist there is no evidence to reject on, so cold
  starts admit freely.
- **Weighted fair queuing**: each tenant owns a FIFO; dequeue order is
  by virtual finish time (arrival's token cost divided by tenant
  weight, accumulated per tenant) — the classic WFQ discipline, so a
  tenant flooding the queue cannot starve the others, and a weight-2
  tenant gets 2x the service of a weight-1 tenant under contention.
- **Requeue**: when a decode replica dies, its in-flight sequences come
  back through ``requeue()`` — they re-enter at their ORIGINAL virtual
  finish time (the work was already charged), so recovered requests go
  to the head of the line rather than paying for the replica's death
  twice.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, NamedTuple, Optional, Tuple

from ..serving.scheduler import Request, Sequence

__all__ = ["Admission", "Router"]


class Admission(NamedTuple):
    accepted: bool
    reason: Optional[str] = None  # "queue_full" | "slo" when rejected


class _Tenant:
    def __init__(self, name: str, weight: float):
        if weight <= 0:
            raise ValueError(f"tenant weight must be > 0, got {weight}")
        self.name = name
        self.weight = float(weight)
        self.queue: Deque[Sequence] = deque()
        self.last_vft = 0.0  # virtual finish time of the newest arrival
        self.submitted = 0
        self.dequeued = 0


class Router:
    """See module docstring. ``tenant_weights`` maps tenant name ->
    weight; unknown tenants default to weight 1.0. ``service_window``
    is how many recent completions the TTFT estimate is averaged over."""

    def __init__(self, *, max_queue: Optional[int] = None,
                 slo_ttft_s: Optional[float] = None,
                 tenant_weights: Optional[Dict[str, float]] = None,
                 service_window: int = 32):
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = max_queue
        self.slo_ttft_s = slo_ttft_s
        self._weights = dict(tenant_weights or {})
        self._tenants: Dict[str, _Tenant] = {}
        self._vt = 0.0  # global virtual time (monotone over dequeues)
        self._finishes: Deque[float] = deque(maxlen=max(2, service_window))
        self.rejected: List[dict] = []
        self.requeues = 0

    # ------------------------------------------------------------- signals
    @property
    def queue_depth(self) -> int:
        return sum(len(t.queue) for t in self._tenants.values())

    def service_rate(self) -> Optional[float]:
        """Observed fleet completions/second over the sliding window;
        None until two completions exist (no evidence, no estimate)."""
        if len(self._finishes) < 2:
            return None
        span = self._finishes[-1] - self._finishes[0]
        if span <= 0:
            return None
        return (len(self._finishes) - 1) / span

    def predicted_ttft(self) -> Optional[float]:
        """What a NEW arrival should expect to wait for its first token:
        the queue it joins behind, drained at the observed service rate.
        None when there is no rate estimate yet."""
        rate = self.service_rate()
        if rate is None:
            return None
        return (self.queue_depth + 1) / rate

    def observe_finish(self, now: float) -> None:
        """Feed the admission estimator: called once per completed
        request with the fleet clock."""
        self._finishes.append(float(now))

    # ------------------------------------------------------------- tenants
    def _tenant(self, name: str) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            t = _Tenant(name, self._weights.get(name, 1.0))
            self._tenants[name] = t
        return t

    # -------------------------------------------------------------- submit
    def submit(self, request: Request, *, tenant: str = "default",
               now: float = 0.0) -> Tuple[Admission, Optional[Sequence]]:
        """Admission-check ``request`` and, if accepted, wrap it in a
        router-owned :class:`Sequence` queued under ``tenant``. Returns
        ``(Admission, Sequence-or-None)``."""
        if self.max_queue is not None and self.queue_depth >= self.max_queue:
            adm = Admission(False, "queue_full")
            self.rejected.append({
                "request_id": request.request_id, "tenant": tenant,
                "reason": "queue_full", "t": float(now),
            })
            return adm, None
        if self.slo_ttft_s is not None:
            pred = self.predicted_ttft()
            if pred is not None and pred > self.slo_ttft_s:
                adm = Admission(False, "slo")
                self.rejected.append({
                    "request_id": request.request_id, "tenant": tenant,
                    "reason": "slo", "t": float(now),
                    "predicted_ttft_s": round(pred, 4),
                })
                return adm, None
        t = self._tenant(tenant)
        seq = Sequence(request)
        seq.submitted_at = float(now)
        seq.enqueued_at = float(now)
        seq.tenant = tenant
        cost = request.prompt.size + request.max_new_tokens  # token work
        seq.vft = max(self._vt, t.last_vft) + cost / t.weight
        t.last_vft = seq.vft
        t.queue.append(seq)
        t.submitted += 1
        return Admission(True), seq

    def requeue(self, seqs, now: float) -> None:
        """Put recovered in-flight sequences back at the head of their
        tenant queues, keeping their original virtual finish times (their
        work is already charged — the replica's death is not billed to
        the tenant)."""
        for seq in reversed(list(seqs)):
            t = self._tenant(getattr(seq, "tenant", "default"))
            seq.enqueued_at = float(now)
            seq.requeues = getattr(seq, "requeues", 0) + 1
            t.queue.appendleft(seq)
            self.requeues += 1

    # ------------------------------------------------------------- dequeue
    def _best_tenant(self) -> Optional[_Tenant]:
        best: Optional[_Tenant] = None
        for name in sorted(self._tenants):
            t = self._tenants[name]
            if not t.queue:
                continue
            if best is None or t.queue[0].vft < best.queue[0].vft:
                best = t
        return best

    def peek(self) -> Optional[Sequence]:
        """The sequence ``next_request`` would pop, without popping — a
        dispatcher can inspect the WFQ head (is it fresh? does capacity
        exist for it?) and leave it queued, preserving WFQ order instead
        of pop/requeue churn."""
        best = self._best_tenant()
        return best.queue[0] if best is not None else None

    def next_request(self) -> Optional[Sequence]:
        """Pop the waiting sequence with the smallest virtual finish time
        (ties break on tenant name, so order is deterministic)."""
        best = self._best_tenant()
        if best is None:
            return None
        seq = best.queue.popleft()
        best.dequeued += 1
        self._vt = max(self._vt, seq.vft)
        return seq

    # ------------------------------------------------------------ placement
    def place(self, seq: Sequence, candidates, *,
              gossip_adoptable: bool = False):
        """Pick the decode replica for ``seq`` from ``candidates``
        (replicas with capacity): PREFIX AFFINITY first — a replica whose
        prefix store already holds the sequence's leading prompt block
        (``DecodeReplica.holds_prefix``) admits it with a warm cache and,
        under block transfer, receives a trimmed suffix-only payload —
        affinity TIES break by lowest queue depth (the replica that will
        ADMIT soonest; two warm replicas are equally warm, but the one
        with the shorter wait wins), then least in-flight, then name
        (deterministic). Without prefix caching every replica scores
        equal affinity and this degrades to shortest-queue/least-loaded.

        ``gossip_adoptable``: the fleet found a PEER advertising this
        sequence's prefix (``fleet.gossip``), so any prefix-caching
        replica can be made warm by adopting the remote run at placement
        — every such replica scores warm affinity and the tie breaks by
        queue depth, instead of the cold pool pinning all shared-prefix
        traffic onto the one replica that prefilled first."""
        pool = list(candidates)
        if not pool:
            return None

        def key(rep):
            holds = getattr(rep, "holds_prefix", None)
            affinity = 1 if holds is not None and holds(seq) else 0
            if not affinity and gossip_adoptable and (
                    getattr(getattr(rep, "kv", None), "prefix", None)
                    is not None):
                affinity = 1
            return (-affinity, getattr(rep, "queue_depth", rep.in_flight),
                    rep.in_flight, rep.name)

        return min(pool, key=key)

    # ----------------------------------------------------------- telemetry
    def telemetry(self) -> dict:
        return {
            "queue_depth": self.queue_depth,
            "rejected": len(self.rejected),
            "rejected_by_reason": {
                r: sum(1 for x in self.rejected if x["reason"] == r)
                for r in sorted({x["reason"] for x in self.rejected})
            },
            "requeues": self.requeues,
            "tenants": {
                name: {
                    "weight": t.weight,
                    "submitted": t.submitted,
                    "dequeued": t.dequeued,
                    "waiting": len(t.queue),
                }
                for name, t in sorted(self._tenants.items())
            },
        }
