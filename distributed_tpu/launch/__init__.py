from .core import (
    LocalLauncher,
    SSHLauncher,
    WorkerResult,
    launch_local,
    report_result,
)

__all__ = [
    "LocalLauncher",
    "SSHLauncher",
    "WorkerResult",
    "launch_local",
    "report_result",
]
