from .core import (
    LocalLauncher,
    SSHLauncher,
    WorkerResult,
    heartbeat,
    launch_local,
    report_result,
    run_with_restart,
)

__all__ = [
    "LocalLauncher",
    "SSHLauncher",
    "WorkerResult",
    "heartbeat",
    "launch_local",
    "report_result",
    "run_with_restart",
]
