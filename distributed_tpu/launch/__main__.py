"""CLI: gang-launch a training script.

    dtpu-launch --num-workers 4 script.py [script args...]
    dtpu-launch --hosts host1,host2,host3 script.py [script args...]

Replaces both of the reference's launch modes — manual per-machine sessions
(/root/reference/README.md:82-114) and the Spark barrier job
(README.md:170-224) — with one command. Prints one result row per worker
(the collect() tibble shape, README.md:226-232) and exits nonzero if any
worker failed.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import core


def main(argv=None):
    ap = argparse.ArgumentParser(prog="dtpu-launch", description=__doc__)
    ap.add_argument("--num-workers", type=int, default=None,
                    help="local processes to spawn (CPU sim / single host)")
    ap.add_argument("--hosts", type=str, default=None,
                    help="comma-separated remote hosts (one worker per host, via ssh)")
    ap.add_argument("--timeout", type=float, default=3600.0)
    ap.add_argument("--base-port", type=int, default=None)
    ap.add_argument("--python", type=str, default=sys.executable)
    ap.add_argument("--results-json", type=str, default=None,
                    help="write the worker result rows to this file")
    ap.add_argument("--liveness-timeout", type=float, default=None,
                    help="kill a worker whose heartbeat (emitted per batch "
                         "by Model.fit) stalls for this many seconds — "
                         "catches hung-but-alive workers (deadlocked "
                         "collective) instead of waiting out --timeout; "
                         "arms per worker after its first beat, so slow "
                         "jit compiles never trip it")
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="on worker failure, relaunch the whole gang up to "
                         "N times; pair with ModelCheckpoint(restore=True) "
                         "in the script so relaunches resume from the "
                         "latest checkpoint")
    ap.add_argument("--supervise", action="store_true",
                    help="run under resilience.Supervisor instead of the "
                         "flat restart loop: exponential backoff between "
                         "relaunches, preemption-aware budget (exit 75 "
                         "restarts for free), structured event log")
    ap.add_argument("--checkpoint-dir", type=str, default=None,
                    help="(with --supervise) the run's checkpoint dir, for "
                         "resume-state events and marker cleanup")
    ap.add_argument("--elastic-min-workers", type=int, default=None,
                    help="(with --supervise) enable elastic gang "
                         "re-formation: on PERMANENT worker loss (the same "
                         "rank initiating the failure on consecutive "
                         "attempts) relaunch at a smaller world size down "
                         "to this floor, budget-free, instead of burning "
                         "the restart budget on a doomed fixed-size "
                         "relaunch (docs/RESILIENCE.md 'Elastic gangs')")
    ap.add_argument("--elastic-max-workers", type=int, default=None,
                    help="(with --elastic-min-workers) ceiling for "
                         "grow-back; default: the launch size")
    ap.add_argument("--elastic-divisor", type=int, default=None,
                    help="(with --elastic-min-workers) snap every resized "
                         "world size down to a divisor of this — set it to "
                         "the global batch size so resizes keep exact "
                         "batch math")
    ap.add_argument("--buddy-store-dir", type=str, default=None,
                    help="(with --supervise) RAM-backed buddy-redundancy "
                         "store dir (tmpfs, e.g. under /dev/shm): exported "
                         "to workers as DTPU_BUDDY_STORE so "
                         "ModelCheckpoint(buddy=True) arms the diskless "
                         "recovery tier; the supervisor invalidates failed "
                         "ranks' segments before each relaunch "
                         "(docs/RESILIENCE.md 'Recovery tiers')")
    ap.add_argument("--event-log", type=str, default=None,
                    help="(with --supervise) JSONL event log path; also "
                         "exported to workers as DTPU_EVENT_LOG")
    ap.add_argument("script", type=str)
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    worker_argv = [args.python, args.script] + list(args.script_args)
    if args.hosts:
        kw = {"port": args.base_port} if args.base_port else {}
        launcher = core.SSHLauncher(args.hosts.split(","), **kw)
        n = len(launcher.hosts)
        run_kw = {"timeout": args.timeout,
                  "liveness_timeout": args.liveness_timeout}
    else:
        launcher = core.LocalLauncher()
        n = args.num_workers or 1
        run_kw = {"timeout": args.timeout, "base_port": args.base_port,
                  "liveness_timeout": args.liveness_timeout}

    if args.supervise:
        from ..resilience import ElasticPolicy, RestartPolicy, Supervisor
        from ..utils.events import EventLog

        elastic = None
        if args.elastic_min_workers is not None:
            elastic = ElasticPolicy(
                min_workers=args.elastic_min_workers,
                max_workers=args.elastic_max_workers,
                divisor_of=args.elastic_divisor,
            )
        sup = Supervisor(
            worker_argv, n, launcher=launcher,
            policy=RestartPolicy(max_restarts=args.max_restarts or 3),
            elastic=elastic,
            checkpoint_dir=args.checkpoint_dir,
            buddy_store_dir=args.buddy_store_dir,
            event_log=EventLog(args.event_log) if args.event_log else None,
            liveness_timeout=args.liveness_timeout,
        )
        base_port = run_kw.pop("base_port", None)
        run_kw.pop("liveness_timeout", None)  # the Supervisor injects it
        if base_port is not None:
            run_kw["base_port"] = base_port
        sup_result = sup.run(**run_kw)
        results = sup_result.results
        print(f"supervisor: attempts={sup_result.attempts} "
              f"restarts={sup_result.restarts_used} "
              f"preemptions={sup_result.preemptions}"
              + (f" resizes={sup_result.resizes} "
                 f"world_size={sup_result.world_size}"
                 if elastic is not None else ""))
    elif args.hosts:
        results = core.run_with_restart(
            launcher, worker_argv, max_restarts=args.max_restarts, **run_kw
        )
    else:
        results = core.run_with_restart(
            launcher, worker_argv, n, max_restarts=args.max_restarts,
            **run_kw
        )

    rows = [
        {
            "index": r.index,
            "ok": r.ok,
            "value": r.value,
            "error": r.error,
            "exit_code": r.exit_code,
        }
        for r in results
    ]
    for r in results:
        status = "ok" if r.ok else f"FAILED ({r.error})"
        print(f"worker {r.index}: {status}  value={r.value!r}")
        if not r.ok and r.log_tail:
            print("  --- log tail ---")
            for line in r.log_tail.splitlines()[-15:]:
                print(f"  {line}")
    if args.results_json:
        with open(args.results_json, "w") as f:
            json.dump(rows, f, indent=2)
    return 0 if all(r.ok for r in results) else 1


if __name__ == "__main__":
    sys.exit(main())
