"""Gang launcher: run the same program on every worker, gather results.

Parity target: the reference's three launchers (SURVEY.md §1 L6) —
(a) manual per-machine sessions differing only in task.index
    (/root/reference/README.md:82-114, 318-358),
(b) ``sparklyr::spark_apply(closure, barrier = TRUE)`` gang-scheduling with
    per-worker rank + peer list injection (/root/reference/README.md:170-224),
(c) per-worker error capture: the closure's ``tryCatch`` turns a worker
    exception into a result row instead of hanging the job
    (/root/reference/README.md:176, 221).

TPU-native redesign: one OS process per TPU host (each owning its local
chips), config injected via DTPU_CONFIG (the TF_CONFIG descendant), results
and errors returned through a per-worker JSON file — the launcher's
``collect()``-like return is a list of WorkerResult, one per worker, errors
included as data (never a hang).
"""

from __future__ import annotations

import dataclasses
import json
import os
import shlex
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from ..cluster import config as config_lib
from ..cluster import net
from ..utils import logging as dlog

RESULT_ENV = "DTPU_RESULT_FILE"
RESULT_STDOUT_ENV = "DTPU_RESULT_STDOUT"  # ssh mode: frame result on stdout
STDOUT_MARK = "___DTPU_RESULT___"
HEARTBEAT_ENV = "DTPU_HEARTBEAT_FILE"  # local mode: touch this file
HEARTBEAT_STDOUT_ENV = "DTPU_HEARTBEAT_STDOUT"  # ssh mode: tick on stdout
HEARTBEAT_MARK = "___DTPU_HB___"
PID_MARK = "___DTPU_PID___"  # ssh mode: remote worker announces its pid

_last_heartbeat = 0.0


def heartbeat(min_interval: float = 0.5) -> None:
    """Publish worker liveness to the launcher (no-op outside a gang).

    The training loop calls this every batch (training/model.py), so a
    worker that is *computing* keeps beating while one stuck at a
    collective, deadlocked, or SIGSTOPped goes silent — the launcher's
    ``liveness_timeout`` then treats it like a crashed peer (gang-kill +
    restart) instead of burning the full run ``timeout``
    (/root/reference/README.md:400's "restart if any fails", extended to
    hung-but-alive workers). Custom loops can call it directly.

    Transport matches the launcher: an mtime touch on ``$DTPU_HEARTBEAT_FILE``
    for local gangs, a marker line on stdout for ssh workers. Throttled to
    one beat per ``min_interval`` seconds so a fast step loop costs nothing.
    """
    global _last_heartbeat
    now = time.monotonic()
    if now - _last_heartbeat < min_interval:
        return
    path = os.environ.get(HEARTBEAT_ENV)
    tick_stdout = os.environ.get(HEARTBEAT_STDOUT_ENV) == "1"
    if not path and not tick_stdout:
        return
    _last_heartbeat = now
    if path:
        try:
            with open(path, "a"):
                pass
            os.utime(path, None)
        except OSError:
            pass
    if tick_stdout:
        print(HEARTBEAT_MARK, flush=True)


@dataclasses.dataclass
class WorkerResult:
    """One row per worker — the shape of the reference's Spark collect()
    (/root/reference/README.md:223-232).

    ``disposition`` records HOW the row ended, structurally — the launcher
    knows whether it killed the worker and why, and downstream policy
    (the supervisor's preemption/failure classification, the elastic
    ledger's per-rank attribution) must not re-derive that from error
    strings. Values: ``"exited"`` (the worker's own exit, code in
    ``exit_code``), ``"gang_killed"`` (killed because a PEER failed —
    collateral, never an independent fault), ``"liveness_killed"``
    (heartbeat went silent: hung, an initiated fault), ``"timeout"``
    (the whole run deadline expired — unattributable), ``"launch_error"``
    (the gang never started). ``None`` on rows from launchers predating
    the field; consumers then fall back to exit_code/error heuristics.
    """

    index: int
    ok: bool
    value: Optional[object] = None  # worker-reported result (report_result)
    error: Optional[str] = None  # exception text, tryCatch-style
    exit_code: Optional[int] = None
    log_tail: str = ""
    disposition: Optional[str] = None


def report_result(value):
    """Called by worker code to return a value to the launcher (the
    equivalent of the Spark closure's return value, README.md:220).

    Transport depends on how the worker was launched: a result file for
    local gangs, stdout framing for ssh workers."""
    path = os.environ.get(RESULT_ENV)
    if path:
        with open(path, "w") as f:
            json.dump({"value": value}, f)
    elif os.environ.get(RESULT_STDOUT_ENV) == "1":
        print(STDOUT_MARK + json.dumps(value), flush=True)


def _read_result(path: Path):
    try:
        with open(path) as f:
            return json.load(f).get("value")
    except (OSError, json.JSONDecodeError):
        return None


def _tail(path: Path, max_bytes: int = 4096) -> str:
    try:
        data = path.read_bytes()
        return data[-max_bytes:].decode(errors="replace")
    except OSError:
        return ""


class LocalLauncher:
    """Spawn N worker processes on this machine (CPU-sim CI and single-host
    multi-chip runs). Gang semantics: all start together; on any worker's
    crash the rest are killed after `grace` rather than hanging at the next
    collective — the failure surfaces as that worker's result row."""

    def __init__(self, env_extra: Optional[Dict[str, str]] = None):
        self.env_extra = dict(env_extra or {})

    def run(
        self,
        argv: Sequence[str],
        num_workers: int,
        *,
        timeout: float = 600.0,
        grace: float = 10.0,
        workdir: Optional[str] = None,
        base_port: Optional[int] = None,
        liveness_timeout: Optional[float] = None,
    ) -> List[WorkerResult]:
        """``liveness_timeout``: seconds a worker may go without a heartbeat
        (``launch.heartbeat()``, called per batch by Model.fit and its
        eval/epoch-boundary loops) before it is treated as hung — killed
        and recorded as failed, which then gang-kills its peers after
        ``grace`` exactly like a crash. ``None`` (default) disables the
        probe. The probe arms per worker only after its FIRST beat, so
        slow startup/compile never trips it — but later SINGLE blocking
        operations (the eval graph's first jit compile, a large checkpoint
        write) emit no beats while they run, so choose a liveness_timeout
        comfortably above the longest such operation, not above a step
        time."""
        if base_port is not None:
            ports = [base_port + i for i in range(num_workers)]
        else:
            ports = net.free_ports(num_workers)
        workers = [f"127.0.0.1:{p}" for p in ports]
        tmp = Path(tempfile.mkdtemp(prefix="dtpu_launch_"))
        procs = []
        hb_paths = [tmp / f"heartbeat-{i}" for i in range(num_workers)]
        for i in range(num_workers):
            spec = config_lib.ClusterSpec(workers=workers, index=i)
            env = dict(os.environ)
            env.update(self.env_extra)
            env[config_lib.ENV_VAR] = spec.to_json()
            env[RESULT_ENV] = str(tmp / f"result-{i}.json")
            env[HEARTBEAT_ENV] = str(hb_paths[i])
            log = open(tmp / f"worker-{i}.log", "wb")
            procs.append(
                (
                    subprocess.Popen(
                        list(argv),
                        env=env,
                        stdout=log,
                        stderr=subprocess.STDOUT,
                        cwd=workdir,
                    ),
                    log,
                )
            )
        deadline = time.time() + timeout
        results: List[Optional[WorkerResult]] = [None] * num_workers
        pending = set(range(num_workers))
        first_failure: Optional[float] = None

        def kill_and_record(i: int, reason: str, disposition: str):
            proc, _ = procs[i]
            proc.kill()
            proc.wait()
            pending.discard(i)
            results[i] = WorkerResult(
                index=i,
                ok=False,
                value=_read_result(tmp / f"result-{i}.json"),
                error=reason,
                exit_code=None,
                log_tail=_tail(tmp / f"worker-{i}.log"),
                disposition=disposition,
            )

        while pending:
            now = time.time()
            for i in list(pending):
                proc, _ = procs[i]
                rc = proc.poll()
                if rc is not None:
                    pending.discard(i)
                    log_path = tmp / f"worker-{i}.log"
                    value = _read_result(tmp / f"result-{i}.json")
                    err = None if rc == 0 else f"exit code {rc}"
                    results[i] = WorkerResult(
                        index=i,
                        ok=rc == 0,
                        value=value,
                        error=err,
                        exit_code=rc,
                        log_tail=_tail(log_path) if rc != 0 else "",
                        disposition="exited",
                    )
                    if rc != 0 and first_failure is None:
                        first_failure = now
            if liveness_timeout is not None:
                for i in list(pending):
                    try:
                        last = os.path.getmtime(hb_paths[i])
                    except OSError:
                        continue  # not armed until the first beat
                    if now - last <= liveness_timeout:
                        continue
                    kill_and_record(
                        i,
                        f"liveness timeout (no heartbeat for "
                        f"{liveness_timeout:.0f}s; worker hung?)",
                        "liveness_killed",
                    )
                    if first_failure is None:
                        first_failure = now
            if pending and (
                now > deadline
                or (first_failure is not None and now > first_failure + grace)
            ):
                timed_out = now > deadline
                reason = (
                    "timeout"
                    if timed_out
                    else "killed after peer failure (gang semantics)"
                )
                for i in list(pending):
                    kill_and_record(
                        i, reason, "timeout" if timed_out else "gang_killed"
                    )
                pending.clear()
            time.sleep(0.05)
        for proc, log in procs:
            log.close()
        return [r for r in results if r is not None]


class SSHLauncher:
    """Spawn one worker per remote host over ssh (TPU pod-style deployments
    where each host runs the same program against its local chips — the
    reference's per-machine manual sessions, README.md:82-114, automated).

    Assumes passwordless ssh and a shared filesystem or pre-synced code, the
    same operational posture as the reference's EC2 recipe (README.md:9-19).
    Results come back over stdout framing rather than files.
    """

    MARK = STDOUT_MARK

    def __init__(self, hosts: Sequence[str], *, ssh_cmd: str = "ssh", port: int = 8476):
        self.hosts = list(hosts)
        self.ssh_cmd = ssh_cmd
        self.port = port

    def run(
        self,
        argv: Sequence[str],
        *,
        timeout: float = 3600.0,
        grace: float = 10.0,
        env_extra: Optional[Dict[str, str]] = None,
        liveness_timeout: Optional[float] = None,
    ) -> List[WorkerResult]:
        """``liveness_timeout``: see LocalLauncher.run — same contract, but
        liveness rides stdout (``heartbeat()`` prints a marker line when
        ``DTPU_HEARTBEAT_STDOUT=1``; any later output also counts as a
        beat). Armed per worker only after its first marker, so compile
        time and ssh startup never trip it."""
        workers = [f"{h}:{self.port}" for h in self.hosts]
        unreachable = [w for w, ok in net.preflight(workers).items() if not ok]
        if unreachable:
            raise RuntimeError(f"Preflight failed for: {unreachable}")
        procs = []
        for i, host in enumerate(self.hosts):
            spec = config_lib.ClusterSpec(workers=workers, index=i)
            exports = {
                config_lib.ENV_VAR: spec.to_json(),
                RESULT_STDOUT_ENV: "1",
                HEARTBEAT_STDOUT_ENV: "1",
                **(env_extra or {}),
            }
            # shlex.quote everything: env values hold JSON and argv may hold
            # paths with spaces; unquoted, the remote shell would word-split
            # and expand $/backtick metacharacters. The worker announces its
            # remote pid first and `exec`s so $$ IS the worker process —
            # that pid is what a liveness kill must target (killing only
            # the local ssh client leaves a hung remote worker holding the
            # host's TPU chips, and the relaunched gang can't acquire them).
            export_str = "; ".join(
                f"export {k}={shlex.quote(v)}" for k, v in exports.items()
            )
            cmd = " ".join(shlex.quote(a) for a in argv)
            remote = f"echo {PID_MARK}$$; {export_str}; exec {cmd}"
            procs.append(
                subprocess.Popen(
                    [self.ssh_cmd, host, remote],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        # Drain all stdout pipes concurrently, line by line: one log-heavy
        # worker must not fill its pipe and stall the gang at a collective
        # while we block on a different worker (the "never a hang"
        # contract). Heartbeat marker lines update last_beat and are
        # filtered out of the captured output.
        # Per-worker line buffers are shared with the drain threads: the
        # main thread can assemble partial output WITHOUT joining a thread
        # that may be blocked forever on a pipe an orphaned remote child
        # still holds open (closing our read end cannot unblock a reader
        # parked inside the stream's lock — it would deadlock the closer).
        bufs: List[List[str]] = [[] for _ in procs]
        last_beat: List[Optional[float]] = [None] * len(procs)
        pids: List[Optional[int]] = [None] * len(procs)

        def _drain(i, proc):
            for line in proc.stdout:
                if line.startswith(PID_MARK):
                    try:
                        pids[i] = int(line[len(PID_MARK):].strip())
                    except ValueError:
                        pass
                    continue
                if line.startswith(HEARTBEAT_MARK):
                    last_beat[i] = time.time()
                    continue
                bufs[i].append(line)
                if last_beat[i] is not None:
                    # Once armed, any output counts as liveness: a
                    # worker busy printing logs is not hung.
                    last_beat[i] = time.time()

        def _remote_kill(i):
            """Best-effort SIGKILL of the remote worker process itself:
            killing only the local ssh client cannot stop a SIGSTOPped or
            deadlocked remote (sshd's HUP is not deliverable to a stopped
            process), which would keep holding the host's TPU chips."""
            if pids[i] is None:
                return
            try:
                subprocess.Popen(
                    [self.ssh_cmd, self.hosts[i], f"kill -9 {pids[i]}"],
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                )
            except Exception:
                pass

        drains = [
            threading.Thread(target=_drain, args=(i, p), daemon=True,
                             name=f"dtpu-ssh-drain-{i}")
            for i, p in enumerate(procs)
        ]
        for t in drains:
            t.start()
        # Gang semantics (same as LocalLauncher): when one worker dies, its
        # peers are blocked at their next collective waiting for it — kill
        # them after `grace` instead of letting them burn the full timeout.
        killed: set = set()
        hung: set = set()
        first_failure: Optional[float] = None
        deadline = time.time() + timeout
        while any(p.poll() is None for p in procs):
            now = time.time()
            if first_failure is None and any(
                p.poll() not in (None, 0) for p in procs
            ):
                first_failure = now
            if liveness_timeout is not None:
                for i, p in enumerate(procs):
                    if (
                        p.poll() is None
                        and i not in hung
                        and last_beat[i] is not None
                        and now - last_beat[i] > liveness_timeout
                    ):
                        hung.add(i)
                        _remote_kill(i)
                        p.kill()
                        if first_failure is None:
                            first_failure = now
            if now > deadline or (
                first_failure is not None and now > first_failure + grace
            ):
                killed_timeout = now > deadline
                kill_reason = (
                    "timeout" if killed_timeout
                    else "killed after peer failure (gang semantics)"
                )
                for i, p in enumerate(procs):
                    if p.poll() is None:
                        killed.add(i)
                        _remote_kill(i)
                        p.kill()
                break
            time.sleep(0.2)
        # Bounded drain joins ("never a hang"): a wrapper script or remote
        # child that inherited stdout can hold the pipe open past the kill.
        # After the deadline the daemon drain threads are simply ABANDONED —
        # every line they read so far is already in bufs, and they die with
        # the process. (Closing the read end from here cannot unblock a
        # reader and can deadlock on the stream lock instead.)
        join_deadline = time.time() + 30.0
        for t in drains:
            t.join(max(0.0, join_deadline - time.time()))
        results = []
        for i, proc in enumerate(procs):
            out = "".join(bufs[i])
            value = None
            for line in (out or "").splitlines():
                if line.startswith(self.MARK):
                    try:
                        value = json.loads(line[len(self.MARK):])
                    except json.JSONDecodeError:
                        pass
            if proc.returncode == 0 and i not in hung:
                err, disposition = None, "exited"
            elif i in hung:
                err = (
                    f"liveness timeout (no heartbeat for "
                    f"{liveness_timeout:.0f}s; worker hung?)"
                )
                disposition = "liveness_killed"
            elif i in killed:
                err = kill_reason
                disposition = "timeout" if killed_timeout else "gang_killed"
            else:
                err = f"exit code {proc.returncode}"
                disposition = "exited"
            ok = proc.returncode == 0 and i not in hung
            results.append(
                WorkerResult(
                    index=i,
                    ok=ok,
                    value=value,
                    error=err,
                    # A launcher-killed worker's returncode is the kill
                    # signal, not its own exit — report None so exit-
                    # disposition consumers never mistake it for a fault.
                    exit_code=(proc.returncode
                               if disposition == "exited" else None),
                    log_tail="" if ok else (out or "")[-4096:],
                    disposition=disposition,
                )
            )
        return results


def launch_local(argv: Sequence[str], num_workers: int, **kw) -> List[WorkerResult]:
    return LocalLauncher().run(argv, num_workers, **kw)


def run_with_restart(
    launcher,
    argv: Sequence[str],
    *run_args,
    max_restarts: int = 2,
    restart_backoff: float = 2.0,
    **run_kw,
) -> List[WorkerResult]:
    """Gang-run with automatic full-gang restart on worker failure.

    The reference documents its own gap here: "Workers will need to restart
    training if any fails" (/root/reference/README.md:400) — an operator
    action. This automates it: on any failed attempt the WHOLE gang is
    relaunched (the launcher's gang-kill already tore down the survivors),
    up to ``max_restarts`` times, with ``restart_backoff`` seconds between
    attempts.

    Recovery-without-rework is the training script's side of the contract:
    run with ``ModelCheckpoint(dir, restore=True)`` and a fixed seed, and a
    relaunch of the identical command restores the latest complete
    checkpoint and fast-forwards the batch stream to the exact next batch
    (training/model.py resume math) — the restarted run matches an
    uninterrupted one batch-for-batch (tests/test_launch.py).

    Returns the final attempt's results (per-worker rows, errors as data).
    """
    attempt = 0
    while True:
        try:
            results = launcher.run(argv, *run_args, **run_kw)
        except RuntimeError as e:
            # Keep the errors-as-data contract across attempts: an SSH
            # relaunch whose preflight finds the dead host unreachable
            # raises — synthesize one failed row PER EXPECTED WORKER
            # instead of propagating, so callers indexing results by rank
            # see a stable shape across attempts (ADVICE r4).
            n = run_kw.get("num_workers")
            if n is None and run_args and isinstance(run_args[0], int):
                n = run_args[0]
            if n is None:
                n = len(getattr(launcher, "hosts", None) or []) or 1
            results = [
                WorkerResult(index=i, ok=False, error=str(e))
                for i in range(n)
            ]
        if all(r.ok for r in results):
            return results
        if attempt >= max_restarts:
            dlog.warning(
                f"gang failed and restart budget exhausted "
                f"({max_restarts} restarts); returning failed results"
            )
            return results
        attempt += 1
        failed = [r.index for r in results if not r.ok]
        dlog.warning(
            f"gang failure on worker(s) {failed}; restart "
            f"{attempt}/{max_restarts} in {restart_backoff:.0f}s "
            "(resume from latest checkpoint is the script's "
            "ModelCheckpoint(restore=True) contract)"
        )
        time.sleep(restart_backoff)
