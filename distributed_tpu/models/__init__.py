from .cnn import cifar_cnn, mnist_cnn
from .resnet import resnet, resnet18, resnet34, resnet50

__all__ = [
    "mnist_cnn",
    "cifar_cnn",
    "resnet",
    "resnet18",
    "resnet34",
    "resnet50",
]
