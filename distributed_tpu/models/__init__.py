from .cnn import cifar_cnn, mnist_cnn
from .resnet import resnet, resnet18, resnet34, resnet50
from .transformer import transformer_block, transformer_lm
from .vit import vit, vit_base, vit_large, vit_small, vit_tiny

__all__ = [
    "mnist_cnn",
    "cifar_cnn",
    "resnet",
    "resnet18",
    "resnet34",
    "resnet50",
    "transformer_lm",
    "transformer_block",
    "vit",
    "vit_tiny",
    "vit_small",
    "vit_base",
    "vit_large",
]
