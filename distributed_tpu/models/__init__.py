from .cnn import cifar_cnn, mnist_cnn
from .resnet import resnet, resnet18, resnet34, resnet50
from .transformer import transformer_block, transformer_lm

__all__ = [
    "mnist_cnn",
    "cifar_cnn",
    "resnet",
    "resnet18",
    "resnet34",
    "resnet50",
    "transformer_lm",
    "transformer_block",
]
