from .cnn import cifar_cnn, mnist_cnn

__all__ = ["mnist_cnn", "cifar_cnn"]
