"""The reference model zoo: small CNNs.

``mnist_cnn`` is the exact architecture of the reference's trainers
(/root/reference/README.md:58-68 R form, 292-298 Python form):
Conv2D(32, 3x3, relu) -> MaxPool2D -> Flatten -> Dense(64, relu) -> Dense(10)
= 347,146 params in 6 tensors (BASELINE.md model-size row).
"""

from __future__ import annotations

from .. import nn


def mnist_cnn(num_classes: int = 10, dtype=None) -> nn.Sequential:
    return nn.Sequential(
        [
            nn.Conv2D(32, (3, 3), activation="relu", dtype=dtype),
            nn.MaxPool2D(2),
            nn.Flatten(),
            nn.Dense(64, activation="relu", dtype=dtype),
            nn.Dense(num_classes, dtype=dtype),
        ]
    )


def cifar_cnn(num_classes: int = 10, dtype=None) -> nn.Sequential:
    """A deeper small CNN for CIFAR-10 / Fashion-MNIST scale (BASELINE.json
    configs[2]); VGG-ish 3-block stack sized to train quickly on one chip."""
    return nn.Sequential(
        [
            nn.Conv2D(64, (3, 3), padding="same", activation="relu", dtype=dtype),
            nn.Conv2D(64, (3, 3), padding="same", activation="relu", dtype=dtype),
            nn.MaxPool2D(2),
            nn.Conv2D(128, (3, 3), padding="same", activation="relu", dtype=dtype),
            nn.Conv2D(128, (3, 3), padding="same", activation="relu", dtype=dtype),
            nn.MaxPool2D(2),
            nn.Conv2D(256, (3, 3), padding="same", activation="relu", dtype=dtype),
            nn.GlobalAvgPool2D(),
            nn.Dense(256, activation="relu", dtype=dtype),
            nn.Dense(num_classes, dtype=dtype),
        ]
    )
