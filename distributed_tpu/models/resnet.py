"""ResNet family (v1.5), NHWC, TPU-first.

Build target from BASELINE.json configs[3]: "ResNet-50 ImageNet data-parallel
... on v4-32". The reference itself has no ResNet (its only model is the
2-conv MNIST CNN, /root/reference/README.md:58-68); this is the scale-out
model the survey's build plan schedules after CNN parity (SURVEY.md §7 build
order step 8).

TPU notes:
- All convs are bias-free + BatchNorm, NHWC/HWIO so XLA tiles them onto the
  MXU; pass ``dtype=jnp.bfloat16`` for bf16 compute with float32 params.
- v1.5 puts the stride on each bottleneck's 3x3 (not the 1x1), the variant
  every published ImageNet baseline uses.
- BatchNorm here is sync-BN by construction under data parallelism (the
  batch-stat reductions become cross-replica collectives inside the jitted
  step — see nn.layers.BatchNorm).
- ``small_inputs=True`` swaps the 7x7/2+maxpool stem for a 3x3/1 stem, the
  standard CIFAR adaptation.
"""

from __future__ import annotations

import functools

from typing import Optional, Sequence

from .. import nn

# depth -> (block kind, blocks per stage)
_CONFIGS = {
    18: ("basic", (2, 2, 2, 2)),
    34: ("basic", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
    101: ("bottleneck", (3, 4, 23, 3)),
    152: ("bottleneck", (3, 8, 36, 3)),
}
_STAGE_WIDTHS = (64, 128, 256, 512)


def _conv_bn(filters, kernel, strides=1, activation=None, dtype=None,
             bn_shift="data"):
    layers = [
        nn.Conv2D(filters, kernel, strides=strides, padding="same",
                  use_bias=False, dtype=dtype),
        nn.BatchNorm(stats_shift=bn_shift),
    ]
    if activation is not None:
        layers.append(nn.Activation(activation))
    return layers


def _projection(filters, strides, dtype, bn_shift):
    return nn.Sequential(
        _conv_bn(filters, 1, strides=strides, dtype=dtype, bn_shift=bn_shift),
        name="shortcut",
    )


def _basic_block(filters, strides, project, dtype, bn_shift):
    main = nn.Sequential(
        _conv_bn(filters, 3, strides=strides, activation="relu", dtype=dtype,
                 bn_shift=bn_shift)
        + _conv_bn(filters, 3, dtype=dtype, bn_shift=bn_shift),
        name="main",
    )
    shortcut = (_projection(filters, strides, dtype, bn_shift)
                if project else None)
    return nn.Residual(main, shortcut, activation="relu")


def _bottleneck_block(filters, strides, project, dtype, bn_shift):
    out = filters * 4
    main = nn.Sequential(
        _conv_bn(filters, 1, activation="relu", dtype=dtype,
                 bn_shift=bn_shift)
        + _conv_bn(filters, 3, strides=strides, activation="relu",
                   dtype=dtype, bn_shift=bn_shift)  # v1.5
        + _conv_bn(out, 1, dtype=dtype, bn_shift=bn_shift),
        name="main",
    )
    shortcut = _projection(out, strides, dtype, bn_shift) if project else None
    return nn.Residual(main, shortcut, activation="relu")


def resnet(
    depth: int = 50,
    num_classes: int = 1000,
    *,
    small_inputs: bool = False,
    stage_blocks: Optional[Sequence[int]] = None,
    width: int = 64,
    stem: str = "conv7",
    scan_stages: bool = False,
    bn_shift: str = "running",
    dtype=None,
) -> nn.Sequential:
    if depth not in _CONFIGS:
        raise ValueError(f"Unsupported depth {depth}; known: {sorted(_CONFIGS)}")
    kind, default_blocks = _CONFIGS[depth]
    blocks = tuple(stage_blocks) if stage_blocks is not None else default_blocks
    base = _basic_block if kind == "basic" else _bottleneck_block
    make = functools.partial(base, bn_shift=bn_shift)
    expansion = 1 if kind == "basic" else 4

    if stem not in ("conv7", "space_to_depth"):
        raise ValueError(
            f"Unknown stem {stem!r}; choose 'conv7' or 'space_to_depth'"
        )
    if small_inputs:  # CIFAR-style stem
        if stem != "conv7":
            raise ValueError(
                "small_inputs=True uses the CIFAR 3x3 stem; it is "
                f"incompatible with stem={stem!r}"
            )
        layers = _conv_bn(width, 3, activation="relu", dtype=dtype,
                          bn_shift=bn_shift)
    elif stem == "space_to_depth":
        # TPU stem: space-to-depth(2) then a 4x4/1 conv on 12 channels.
        # Same downsampling and output shape as conv7 (112x112xW before the
        # pool), but the conv packs 12 input channels onto the MXU's lanes
        # instead of 3 — the 7x7/2 RGB conv is the classic layout-hostile
        # TPU stem. An unconstrained 4x4x12 kernel spans an 8x8 RGB
        # receptive field (superset of the padded 7x7), so this is a
        # reparametrization, not an approximation.
        layers = [nn.SpaceToDepth(2)]
        layers += _conv_bn(width, 4, activation="relu", dtype=dtype,
                           bn_shift=bn_shift)
        layers.append(nn.MaxPool2D(3, strides=2, padding="same"))
    else:  # "conv7": the reference-style ImageNet stem
        layers = _conv_bn(width, 7, strides=2, activation="relu",
                          dtype=dtype, bn_shift=bn_shift)
        layers.append(nn.MaxPool2D(3, strides=2, padding="same"))

    in_ch = width
    for stage, n_blocks in enumerate(blocks):
        filters = _STAGE_WIDTHS[stage] * width // 64
        first_strides = 2 if stage > 0 else 1
        project = first_strides != 1 or in_ch != filters * expansion
        layers.append(make(filters, first_strides, project, dtype))
        in_ch = filters * expansion
        tail = n_blocks - 1
        if tail > 0 and scan_stages:
            # The tail blocks of a stage are structurally identical and
            # shape-preserving: run them as ONE weight-stacked lax.scan so
            # static op count (and the optimizer's per-tensor update ops)
            # stay depth-independent — the unrolled form is op-dispatch-
            # bound on TPU before it is FLOP-bound.
            layers.append(nn.ScannedBlocks(
                lambda f=filters: make(f, 1, False, dtype), tail,
            ))
        else:
            for _ in range(tail):
                layers.append(make(filters, 1, False, dtype))

    layers += [nn.GlobalAvgPool2D(), nn.Dense(num_classes, dtype=dtype)]
    return nn.Sequential(layers, name=f"resnet{depth}")


def resnet18(num_classes: int = 1000, **kw) -> nn.Sequential:
    return resnet(18, num_classes, **kw)


def resnet34(num_classes: int = 1000, **kw) -> nn.Sequential:
    return resnet(34, num_classes, **kw)


def resnet50(num_classes: int = 1000, **kw) -> nn.Sequential:
    return resnet(50, num_classes, **kw)
