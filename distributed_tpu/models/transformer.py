"""Decoder-only Transformer language model.

Not present in the reference (no attention of any kind, SURVEY.md §2c); this
is the model family that exercises the framework's long-context/TP design:
pre-LN blocks built from the same Residual/Sequential primitives as ResNet,
MultiHeadAttention + MLP carrying Megatron tensor-parallel sharding hints
(q/k/v + MLP-in column-sharded over the 'model' mesh axis, projections
row-sharded), so ``DataTensorParallel`` distributes it with zero
model-side changes. Pairs with the Pallas fused cross-entropy for the
large-vocab LM head.
"""

from __future__ import annotations

from typing import Optional

from .. import nn


def transformer_block(
    d_model: int,
    num_heads: int,
    d_ff: int,
    *,
    causal: bool = True,
    moe_experts: int = 0,
    flash="auto",
    dtype=None,
) -> list:
    """Pre-LN block as two Residuals: [LN -> MHA] + [LN -> MLP-or-MoE].

    ``moe_experts > 0`` swaps the dense MLP for an nn.MoE with that many
    experts (expert-parallel under DataExpertParallel). ``flash`` passes
    through to MultiHeadAttention (True/False/'auto')."""
    attn = nn.Residual(
        nn.Sequential(
            [
                nn.LayerNorm(),
                nn.MultiHeadAttention(num_heads, causal=causal, flash=flash,
                                      dtype=dtype),
            ],
            name="main",
        )
    )
    if moe_experts:
        ffn_layers = [nn.LayerNorm(), nn.MoE(moe_experts, d_ff, dtype=dtype)]
    else:
        # Flat layer list (not nested in a named container): the param tree
        # paths residual_N/main/{dense,dense_1} are a checkpoint format.
        ffn_layers = [
            nn.LayerNorm(),
            nn.Dense(d_ff, activation="gelu", shard="col", dtype=dtype),
            nn.Dense(d_model, shard="row", dtype=dtype),
        ]
    mlp = nn.Residual(nn.Sequential(ffn_layers, name="main"))
    return [attn, mlp]


def transformer_lm(
    vocab_size: int,
    *,
    num_layers: int = 2,
    d_model: int = 128,
    num_heads: int = 4,
    d_ff: Optional[int] = None,
    max_len: int = 512,
    causal: bool = True,
    moe_experts: int = 0,
    moe_every: int = 2,
    pipeline: bool = False,
    pipeline_schedule: str = "gpipe",
    pipeline_interleave: int = 1,
    scan: bool = False,
    scan_overlap: str = "auto",
    remat: bool = False,
    remat_policy=None,
    flash="auto",
    dtype=None,
) -> nn.Sequential:
    """Token-in, logits-out LM: (B, T) int32 -> (B, T, vocab).

    Train with ``loss="sparse_categorical_crossentropy"`` (or the fused
    ``"pallas_sparse_categorical_crossentropy"``) on next-token labels.
    ``moe_experts > 0`` makes every ``moe_every``-th block's FFN a MoE.
    ``pipeline=True`` stacks the blocks in an ``nn.PipelinedBlocks`` so they
    pipeline over the 'pipe' mesh axis under ``DataPipelineParallel`` (and
    run as a weight-stacked scan otherwise); incompatible with MoE blocks
    (aux-loss state can't ride the microbatch schedule).
    ``pipeline_schedule``/``pipeline_interleave`` forward
    ``nn.PipelinedBlocks(schedule=, interleave=)`` — ``"interleaved"``
    with ``interleave=v`` gives each pipe rank ``v`` non-contiguous stage
    chunks, shrinking the bubble from (n-1)/(M+n-1) to (n-1)/(vM+n-1).
    ``scan=True`` stacks them in an ``nn.ScannedBlocks`` — one lax.scan over
    weight-stacked blocks, keeping static op count and compile time
    depth-independent; generation works through stacked KV caches
    (ScannedBlocks.decode scans the cached one-token step over the stack).
    ``scan_overlap`` forwards ``ScannedBlocks(overlap=)`` ('auto' | 'off' |
    'require'): under an FSDP-family strategy the scan prefetches layer
    i+1's parameter all-gather behind layer i's compute.
    ``remat=True`` wraps every attention/FFN residual in ``nn.Remat`` —
    backward recomputes block activations instead of holding them in HBM
    (identical numerics and checkpoint paths, O(1)-blocks activation
    memory). ``remat_policy`` forwards a ``jax.checkpoint_policies`` entry
    (e.g. ``dots_with_no_batch_dims_saveable`` keeps matmul outputs and
    recomputes only the elementwise chains).
    """
    d_ff = d_ff or 4 * d_model
    layers = [
        nn.Embedding(vocab_size, d_model, dtype=dtype),
        nn.PositionalEmbedding(max_len),
    ]
    if pipeline or scan:
        if moe_experts:
            raise ValueError(
                "pipeline/scan block stacking does not support MoE blocks"
            )
        if pipeline and scan:
            raise ValueError("pipeline and scan are mutually exclusive")

        def make_block():
            block = nn.Sequential(
                transformer_block(
                    d_model, num_heads, d_ff, causal=causal, flash=flash,
                    dtype=dtype,
                )
            )
            return nn.Remat(block, policy=remat_policy) if remat else block

        if pipeline:
            layers.append(nn.PipelinedBlocks(
                make_block, num_layers,
                schedule=pipeline_schedule, interleave=pipeline_interleave,
            ))
        else:
            layers.append(nn.ScannedBlocks(
                make_block, num_layers, overlap=scan_overlap,
            ))
    else:
        for i in range(num_layers):
            moe = moe_experts if (moe_experts and i % moe_every == moe_every - 1) else 0
            block = transformer_block(
                d_model, num_heads, d_ff, causal=causal, moe_experts=moe,
                flash=flash, dtype=dtype,
            )
            if remat:
                block = [nn.Remat(residual, policy=remat_policy)
                         for residual in block]
            layers += block
    layers += [nn.LayerNorm(), nn.Dense(vocab_size, dtype=dtype)]
    return nn.Sequential(layers, name="transformer_lm")
