"""Vision Transformer (ViT) family, NHWC, TPU-first.

Not in the reference (its only model is the 2-conv MNIST CNN,
/root/reference/README.md:58-68); this composes the framework's existing
pieces — the strided-conv patchifier rides the MXU like any conv, the
encoder reuses models.transformer.transformer_block, so Megatron TP hints
(q/k/v + MLP-in column-sharded, projections row-sharded) and flash
attention come along for free.

Design notes:
- Patch embedding = Conv2D(d_model, patch, strides=patch): one big matmul
  per image, no gather/reshape gymnastics before the MXU.
- Global-average-pool head (the ViT paper's GAP variant) instead of a CLS
  token: no ragged concat, token count stays a clean H/p * W/p for the
  sequence axis, and accuracy is equivalent at this scale.
- Encoder blocks are non-causal; ``remat=True`` wraps each residual in
  nn.Remat for O(1)-blocks activation memory.
"""

from __future__ import annotations

from typing import Optional

from .. import nn
from .transformer import transformer_block

_CONFIGS = {
    # name: (num_layers, d_model, num_heads)
    "tiny": (12, 192, 3),
    "small": (12, 384, 6),
    "base": (12, 768, 12),
    "large": (24, 1024, 16),
}


def vit(
    num_classes: int = 1000,
    *,
    image_size: int = 224,
    patch_size: int = 16,
    num_layers: int = 12,
    d_model: int = 768,
    num_heads: int = 12,
    d_ff: Optional[int] = None,
    remat: bool = False,
    scan: bool = False,
    dtype=None,
) -> nn.Sequential:
    """(B, H, W, C) images -> (B, num_classes) logits."""
    if image_size % patch_size:
        raise ValueError(
            f"image_size {image_size} not divisible by patch_size {patch_size}"
        )
    side = image_size // patch_size
    n_tokens = side * side
    d_ff = d_ff or 4 * d_model

    layers = [
        nn.Conv2D(d_model, patch_size, strides=patch_size, padding="valid",
                  dtype=dtype, name="patch_embed"),
        # No explicit output_shape: Lambda.init infers the token count from
        # the real input shape, so building with images that don't match
        # image_size fails loudly in PositionalEmbedding instead of
        # producing a mis-sized positional table.
        nn.Lambda(
            lambda x: x.reshape(x.shape[0], -1, x.shape[-1]),
            name="patches_to_tokens",
        ),
        nn.PositionalEmbedding(n_tokens),
    ]
    if scan:
        # Weight-stacked encoder: one lax.scan over the blocks keeps static
        # op count and compile time depth-independent (see nn.ScannedBlocks)
        # — ViT has no autoregressive decode, so nothing is given up.
        def make_block():
            block = nn.Sequential(transformer_block(
                d_model, num_heads, d_ff, causal=False, dtype=dtype
            ))
            return nn.Remat(block) if remat else block

        layers.append(nn.ScannedBlocks(make_block, num_layers))
    else:
        for _ in range(num_layers):
            block = transformer_block(
                d_model, num_heads, d_ff, causal=False, dtype=dtype
            )
            if remat:
                block = [nn.Remat(residual) for residual in block]
            layers += block
    layers += [
        nn.LayerNorm(),
        nn.Lambda(
            lambda x: x.mean(axis=1), output_shape=(d_model,), name="gap"
        ),
        nn.Dense(num_classes, dtype=dtype),
    ]
    return nn.Sequential(layers, name="vit")


def _named(size: str):
    def make(num_classes: int = 1000, **kw) -> nn.Sequential:
        num_layers, d_model, num_heads = _CONFIGS[size]
        kw.setdefault("num_layers", num_layers)
        kw.setdefault("d_model", d_model)
        kw.setdefault("num_heads", num_heads)
        return vit(num_classes, **kw)

    make.__name__ = f"vit_{size}"
    make.__doc__ = f"ViT-{size.capitalize()} ({_CONFIGS[size]})."
    return make


vit_tiny = _named("tiny")
vit_small = _named("small")
vit_base = _named("base")
vit_large = _named("large")
