from .attention import MultiHeadAttention, PositionalEmbedding
from .augment import RandomCrop, RandomFlip
from .moe import MoE
from .pipeline import PipelinedBlocks
from .scan import ScannedBlocks
from .remat import Remat
from .core import Lambda, Layer, Residual, Sequential
from .layers import (
    Activation,
    AvgPool2D,
    BatchNorm,
    Conv2D,
    Dense,
    Dropout,
    Embedding,
    Flatten,
    GlobalAvgPool2D,
    LayerNorm,
    MaxPool2D,
    SpaceToDepth,
)

__all__ = [
    "Layer",
    "Sequential",
    "Residual",
    "Lambda",
    "Conv2D",
    "Dense",
    "Flatten",
    "Activation",
    "MaxPool2D",
    "AvgPool2D",
    "GlobalAvgPool2D",
    "BatchNorm",
    "LayerNorm",
    "Dropout",
    "Embedding",
    "SpaceToDepth",
    "RandomFlip",
    "RandomCrop",
    "MultiHeadAttention",
    "MoE",
    "PipelinedBlocks",
    "ScannedBlocks",
    "PositionalEmbedding",
    "Remat",
]
