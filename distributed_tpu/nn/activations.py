"""Activation registry (Keras-style names -> jax.nn functions)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

_REGISTRY = {
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "softmax": jax.nn.softmax,
    "linear": lambda x: x,
    None: lambda x: x,
}


def get(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    try:
        return _REGISTRY[name_or_fn]
    except KeyError:
        raise ValueError(
            f"Unknown activation {name_or_fn!r}; known: {sorted(k for k in _REGISTRY if k)}"
        ) from None
