"""Attention and positional embedding layers.

The reference has no attention anywhere (SURVEY.md §2c: inputs are 28x28
images); these layers exist so long-context/distributed training is shaped
into the core design (mesh axes 'seq'/'model' in parallel.mesh.AXES) rather
than bolted on. TPU notes:

- Scores/softmax compute in float32 regardless of activation dtype; the
  einsums lower to MXU matmuls.
- QKV projections are stored as 2D (D, heads*head_dim) kernels so Megatron
  TP is a plain PartitionSpec: q/k/v column-sharded over the 'model' axis
  (splitting heads), output projection row-sharded — XLA inserts the
  all-reduce after the row matmul.
- The causal mask is built from static shapes (no dynamic control flow), so
  the whole layer jits into one XLA program.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from . import initializers
from .core import Layer, Shape
from ..precision import resolve_dtype
from ..quant import _QMAX, QKEY, SKEY, dequantize, maybe_dequantize, shape_of


def _kv_block_size(pool) -> int:
    """Block size of one paged layer pool — plain K/V array or an int8
    ``{"q","scale"}`` quantized pair (quant.py's plain-dict idiom)."""
    return (pool[QKEY] if isinstance(pool, dict) else pool).shape[1]


def _kv_scatter(pool, blk, off, rows):
    """Scatter freshly-computed K/V ``rows`` (..., H, hd) into
    ``pool[blk, off]`` (index arrays share the rows' leading shape).

    Plain pools write the rows as-is (cast to the pool dtype). int8 pools
    quantize ON SCATTER, row-wise: unlike weight quantization (one static
    scale per output channel — ``quant.quantize_leaf``), KV rows are
    data-dependent per position, so each (position, head) row gets its own
    dynamic scale ``amax(|row|)/127`` stored alongside the int8 payload.
    All-zero rows get scale 1 so the dequant stays finite — and the trash
    block, which is never written by a live slot, dequantizes to exact
    zeros."""
    if not isinstance(pool, dict):
        return pool.at[blk, off].set(rows.astype(pool.dtype))
    r = rows.astype(jnp.float32)
    amax = jnp.max(jnp.abs(r), axis=-1, keepdims=True)  # (..., H, 1)
    scale = jnp.where(amax > 0, amax / _QMAX, 1.0)
    q = jnp.clip(jnp.round(r / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return {
        QKEY: pool[QKEY].at[blk, off].set(q),
        SKEY: pool[SKEY].at[blk, off].set(scale),
    }


class MultiHeadAttention(Layer):
    """Multi-head self-attention over (B, T, D) inputs."""

    def __init__(
        self,
        num_heads: int,
        head_dim: Optional[int] = None,
        *,
        causal: bool = False,
        use_bias: bool = True,
        dtype=None,
        ring_axis: Optional[str] = "seq",
        flash="auto",
        name: Optional[str] = None,
    ):
        """``ring_axis``: when the ambient strategy's mesh has this axis with
        size > 1 (sequence parallelism), attention runs as ring attention
        over it (ops.ring_attention) — K/V rotate between sequence shards
        instead of being all-gathered. Irrelevant (dense path) otherwise;
        set None to force dense attention even under a seq mesh.

        ``flash``: True runs the Pallas flash-attention kernel
        (ops.flash_attention — O(T*D) HBM instead of the (T, T) score
        tensor); False keeps the dense einsum path; "auto" (default) uses
        flash on TPU for sequences >= 512. Under a sharded mesh the kernel
        runs per-shard via shard_map (parallel.auto_shard) so GSPMD never
        replicates it; ring attention still takes precedence under a seq
        mesh."""
        super().__init__(name)
        self.num_heads = int(num_heads)
        self.head_dim = head_dim
        self.causal = bool(causal)
        self.use_bias = use_bias
        self.dtype = dtype
        self.ring_axis = ring_axis
        self.flash = flash

    def init(self, key, input_shape: Shape):
        d = input_shape[-1]
        hd = self.head_dim or d // self.num_heads
        if self.head_dim is None and d % self.num_heads:
            raise ValueError(
                f"d_model {d} not divisible by num_heads {self.num_heads}"
            )
        inner = self.num_heads * hd
        keys = jax.random.split(key, 4)
        init = initializers.get("glorot_uniform")
        params = {
            "wq": init(keys[0], (d, inner), jnp.float32),
            "wk": init(keys[1], (d, inner), jnp.float32),
            "wv": init(keys[2], (d, inner), jnp.float32),
            "wo": init(keys[3], (inner, d), jnp.float32),
        }
        if self.use_bias:
            params.update(
                bq=jnp.zeros((inner,), jnp.float32),
                bk=jnp.zeros((inner,), jnp.float32),
                bv=jnp.zeros((inner,), jnp.float32),
                bo=jnp.zeros((d,), jnp.float32),
            )
        return params, {}, tuple(input_shape)

    def sharding_hints(self):
        hints = {"wq": "col", "wk": "col", "wv": "col", "wo": "row"}
        if self.use_bias:
            hints.update(bq="col", bk="col", bv="col")
        return hints

    def _ring_config(self):
        """(mesh, batch_axis, mode) when sequence-parallel attention should
        run ('ring' or 'ulysses' per the strategy), else None. Reads the
        ambient strategy at trace time (Model enters its strategy scope
        around step tracing)."""
        if self.ring_axis is None:
            return None
        from ..parallel.strategy import current_strategy

        strat = current_strategy()
        mesh = getattr(strat, "mesh", None)
        if mesh is None or self.ring_axis not in mesh.axis_names:
            return None
        if int(mesh.shape[self.ring_axis]) <= 1:
            return None
        batch_axis = getattr(strat, "axis", None)
        if batch_axis not in mesh.axis_names:
            batch_axis = None
        mode = getattr(strat, "seq_attention", "ring")
        return mesh, batch_axis, mode

    def _ulysses_attention(self, q, k, v, mesh, batch_axis):
        """All-to-all (DeepSpeed-Ulysses-style) sequence parallelism: two
        sharding constraints reshard (B, T/s, H, d) -> (B, T, H/s, d) and
        back — GSPMD lowers each to one all-to-all over the seq axis — so
        every device runs full-sequence attention for its head slice. One
        collective pair per layer vs ring's n-1 ppermutes; requires
        num_heads divisible by the seq-axis size.

        Per head shard the attention runs the flash (blockwise) kernel via
        shard_map, so device memory is O(T*d) — at the long contexts
        Ulysses exists for, a dense per-shard (T, T) score matrix would
        reintroduce exactly the O(T^2) the seq axis removed. ``flash=False``
        on the layer keeps the dense path (debug/tiny-T escape hatch)."""
        import functools

        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..ops.flash_attention import flash_attention
        from ..parallel.auto_shard import shard_rows

        seq_axis = self.ring_axis
        n_seq = int(mesh.shape[seq_axis])
        h = self.num_heads
        if h % n_seq:
            raise ValueError(
                f"Ulysses attention shards heads over the {seq_axis!r} "
                f"axis: num_heads {h} not divisible by its size {n_seq}"
            )
        head_sh = NamedSharding(mesh, P(batch_axis, None, seq_axis, None))
        seq_sh = NamedSharding(mesh, P(batch_axis, seq_axis, None, None))
        wsc = jax.lax.with_sharding_constraint
        q, k, v = (wsc(a, head_sh) for a in (q, k, v))
        # Same gating as the main path (_use_flash): 'auto' takes the
        # blockwise kernel only at long T on a TPU backend — on CPU/GPU the
        # Pallas interpret/fallback path would be far slower than dense.
        if not self._use_flash(q.shape[1]):
            from ..ops.flash_attention import dense_attention

            ctx = dense_attention(q, k, v, self.causal)
        else:
            fn = functools.partial(flash_attention, causal=self.causal)
            spec = P(batch_axis, None, seq_axis, None)
            ctx = shard_rows(
                fn, (q, k, v), (spec, spec, spec), spec,
                allowed_axes={batch_axis, seq_axis},
            )
        return wsc(ctx, seq_sh)

    def _use_flash(self, t: int) -> bool:
        if self.flash is True:
            return True
        if self.flash == "auto":
            return t >= 512 and jax.default_backend() == "tpu"
        return False

    def _flash_call(self, q, k, v):
        """Flash attention, per-shard under the ambient mesh (batch on the
        strategy's data axis, heads on the Megatron 'model' axis)."""
        import functools

        from jax.sharding import PartitionSpec as P

        from ..ops.flash_attention import flash_attention
        from ..parallel.auto_shard import ambient_mesh, shard_rows

        fn = functools.partial(flash_attention, causal=self.causal)
        mesh, batch_axis, model_axis = ambient_mesh()
        if mesh is None:
            return fn(q, k, v)
        spec = P(batch_axis, None, model_axis, None)
        return shard_rows(fn, (q, k, v), (spec, spec, spec), spec)

    def _proj(self, params, x, w, b):
        # Weight-only int8 (quant.py): dequantize in-trace; compute dtype
        # handling below is unchanged.
        kernel = maybe_dequantize(params[w])
        dt = resolve_dtype(self.dtype)
        if dt is not None:
            kernel = kernel.astype(dt)
        y = jnp.dot(x, kernel)
        if self.use_bias:
            y = y + params[b].astype(y.dtype)
        return y

    # ------------------------------------------------- incremental decode --
    decode_safe = True  # via the cached override below

    def init_cache(self, params, batch, max_len, dtype):
        inner = shape_of(params["wq"])[1]
        hd = inner // self.num_heads
        shape = (batch, max_len, self.num_heads, hd)
        cdtype = self.dtype or dtype
        return {
            "k": jnp.zeros(shape, cdtype),
            "v": jnp.zeros(shape, cdtype),
        }

    def decode(self, params, state, cache, x, *, pos):
        """One-token attention over the KV cache: x (B, 1, D), the new K/V
        row written at ``pos``, scores masked to positions <= pos."""
        if not self.causal:
            # Cached decode is causal by construction (future rows are
            # zeros); a bidirectional model was trained attending both ways
            # and would silently get different logits here.
            raise NotImplementedError(
                "incremental decode requires causal attention "
                "(MultiHeadAttention(causal=True)); bidirectional models "
                "have no autoregressive decode"
            )
        dt = resolve_dtype(self.dtype)
        if dt is not None:
            x = x.astype(dt)
        b = x.shape[0]
        h = self.num_heads
        hd = shape_of(params["wq"])[1] // h
        q = self._proj(params, x, "wq", "bq").reshape(b, 1, h, hd)
        k = self._proj(params, x, "wk", "bk").reshape(b, 1, h, hd)
        v = self._proj(params, x, "wv", "bv").reshape(b, 1, h, hd)
        ck = jax.lax.dynamic_update_slice(
            cache["k"], k.astype(cache["k"].dtype), (0, pos, 0, 0)
        )
        cv = jax.lax.dynamic_update_slice(
            cache["v"], v.astype(cache["v"].dtype), (0, pos, 0, 0)
        )
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, ck, preferred_element_type=jnp.float32
        ) / jnp.sqrt(jnp.float32(hd))  # (B, H, 1, Tmax)
        t_max = ck.shape[1]
        visible = jnp.arange(t_max) <= pos
        scores = jnp.where(
            visible[None, None, None, :], scores, jnp.float32(-1e30)
        )
        attn = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", attn, cv).reshape(b, 1, h * hd)
        out = jnp.dot(ctx, maybe_dequantize(params["wo"]).astype(ctx.dtype))
        if self.use_bias:
            out = out + params["bo"].astype(out.dtype)
        return out, {"k": ck, "v": cv}

    # ------------------------------------------- paged (block) KV cache --
    # Serving-engine cache layout (serving.Engine / docs/SERVING.md): one
    # pool of fixed-size blocks shared by every running sequence, indexed
    # through per-slot block tables — HBM is allocated per block on
    # demand instead of max_len per sequence, so heterogeneous lengths
    # share the pool (vLLM-style PagedAttention). Reads gather the slot's
    # blocks into a contiguous view and mask by the slot's position; the
    # gather is plain XLA (no custom kernel), which is exact everywhere
    # and leaves a Pallas gather-attention kernel as a later perf lever
    # (ROADMAP item 4).

    def init_paged_cache(self, params, num_blocks, block_size, dtype):
        inner = shape_of(params["wq"])[1]
        hd = inner // self.num_heads
        shape = (num_blocks, block_size, self.num_heads, hd)
        if dtype is not None and jnp.dtype(dtype) == jnp.dtype("int8"):
            # int8 KV: ~4x fewer pool bytes than f32 (scale adds 1/hd
            # overhead). Same {"q","scale"} plain-dict idiom as quantized
            # weights, but with per-(position, head) DYNAMIC scales
            # (_kv_scatter) — KV values are data-dependent per step, so a
            # static per-channel scale cannot serve them.
            return {
                "k": {QKEY: jnp.zeros(shape, jnp.int8),
                      SKEY: jnp.ones(shape[:-1] + (1,), jnp.float32)},
                "v": {QKEY: jnp.zeros(shape, jnp.int8),
                      SKEY: jnp.ones(shape[:-1] + (1,), jnp.float32)},
            }
        cdtype = self.dtype or dtype
        return {
            "k": jnp.zeros(shape, cdtype),
            "v": jnp.zeros(shape, cdtype),
        }

    def _paged_view(self, pool, block_tables, out_dtype=None, *,
                    visible=None):
        """Gather per-slot blocks into a contiguous (S, nb*bs, H, hd) view
        (logical position j of slot s lives at block_tables[s, j // bs],
        offset j % bs). Plain pools return their own dtype (``out_dtype``
        ignored — the f32/bf16 program is unchanged); int8 pools gather
        q + scale and dequantize IN-TRACE to ``out_dtype``.

        ``visible`` ((S, L) bool, L = nb*bs): rows the caller's causal
        mask can ever expose. On the int8 path masked rows are zeroed
        BEFORE the dequantize multiply (payload -> 0, scale -> 1), so
        trash-block / stale rows dequantize to exact zeros instead of
        ``garbage * scale`` — the reference view then agrees bit-for-bit
        with the fused kernel (ops.paged_attention), which never weights
        those rows, and the dequantize does no work the mask would
        discard. Plain pools ignore it (their masked rows are never
        multiplied un-masked either way)."""
        if isinstance(pool, dict):
            qv = self._paged_view(pool[QKEY], block_tables)
            sv = self._paged_view(pool[SKEY], block_tables)
            if visible is not None:
                vis = visible[:, :, None, None]
                qv = jnp.where(vis, qv, jnp.zeros_like(qv))
                sv = jnp.where(vis, sv, jnp.ones_like(sv))
            return dequantize({QKEY: qv, SKEY: sv}, out_dtype)
        gathered = pool[block_tables]  # (S, nb, bs, H, hd)
        s, nb, bs, h, hd = gathered.shape
        return gathered.reshape(s, nb * bs, h, hd)

    def paged_decode(self, params, state, cache, x, *, block_tables,
                     positions):
        """One-token attention for S independent slots at per-slot
        positions: x (S, 1, D); each slot's new K/V row is scattered into
        the pool block its position maps to, scores masked to that slot's
        positions <= positions[s]. Inactive slots point their whole block
        table at the engine's trash block, so their writes land harmlessly
        outside every live sequence."""
        if not self.causal:
            raise NotImplementedError(
                "incremental decode requires causal attention "
                "(MultiHeadAttention(causal=True)); bidirectional models "
                "have no autoregressive decode"
            )
        dt = resolve_dtype(self.dtype)
        if dt is not None:
            x = x.astype(dt)
        s = x.shape[0]
        h = self.num_heads
        hd = shape_of(params["wq"])[1] // h
        bs = _kv_block_size(cache["k"])
        q = self._proj(params, x, "wq", "bq").reshape(s, 1, h, hd)
        k = self._proj(params, x, "wk", "bk").reshape(s, h, hd)
        v = self._proj(params, x, "wv", "bv").reshape(s, h, hd)
        blk = jnp.take_along_axis(
            block_tables, (positions // bs)[:, None], axis=1
        )[:, 0]  # (S,) pool block holding each slot's write position
        off = positions % bs
        ck = _kv_scatter(cache["k"], blk, off, k)
        cv = _kv_scatter(cache["v"], blk, off, v)
        from ..ops import paged_attention as paged_ops
        if paged_ops.current_decode_kernel() == paged_ops.FUSED:
            # Fused gather + attention: the block table rides into the
            # kernel as a scalar-prefetch operand; no (S, L, H, hd) view
            # is ever materialized. Scatter stays plain XLA above.
            ctx = paged_ops.paged_attention(
                q, ck, cv, block_tables, positions
            ).reshape(s, 1, h * hd)
        else:
            visible = (
                jnp.arange(block_tables.shape[1] * bs)[None]
                <= positions[:, None]
            )  # (S, L)
            view_k = self._paged_view(
                ck, block_tables, q.dtype, visible=visible
            )  # (S, L, H, hd)
            view_v = self._paged_view(
                cv, block_tables, q.dtype, visible=visible
            )
            scores = jnp.einsum(
                "bqhd,bkhd->bhqk", q, view_k,
                preferred_element_type=jnp.float32,
            ) / jnp.sqrt(jnp.float32(hd))  # (S, H, 1, L)
            scores = jnp.where(
                visible[:, None, None, :], scores, jnp.float32(-1e30)
            )
            attn = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
            ctx = jnp.einsum(
                "bhqk,bkhd->bqhd", attn, view_v
            ).reshape(s, 1, h * hd)
        out = jnp.dot(ctx, maybe_dequantize(params["wo"]).astype(ctx.dtype))
        if self.use_bias:
            out = out + params["bo"].astype(out.dtype)
        return out, {"k": ck, "v": cv}

    def paged_verify(self, params, state, cache, x, *, block_tables,
                     positions):
        """Speculative-verification attention: x (S, K, D) holds, per
        slot, K CANDIDATE tokens occupying consecutive absolute positions
        [positions[s], positions[s] + K). All K are scored in ONE
        fixed-shape dispatch — the K-wide generalization of paged_decode
        (K=1 degenerates to it): each candidate's K/V row is scattered at
        its own position and its scores are masked causally to
        positions <= its own, so column j's logits equal what K=1 decode
        would produce after accepting candidates 0..j-1. Rejected
        candidates leave stale rows behind; the engine masks them (every
        later read attends only below its own position, and the rows are
        overwritten before ever becoming visible). Non-speculating slots
        ride the trash block exactly as in decode."""
        if not self.causal:
            raise NotImplementedError(
                "incremental decode requires causal attention "
                "(MultiHeadAttention(causal=True)); bidirectional models "
                "have no autoregressive decode"
            )
        dt = resolve_dtype(self.dtype)
        if dt is not None:
            x = x.astype(dt)
        s, kw, _ = x.shape
        h = self.num_heads
        hd = shape_of(params["wq"])[1] // h
        bs = _kv_block_size(cache["k"])
        q = self._proj(params, x, "wq", "bq").reshape(s, kw, h, hd)
        k = self._proj(params, x, "wk", "bk").reshape(s, kw, h, hd)
        v = self._proj(params, x, "wv", "bv").reshape(s, kw, h, hd)
        abs_pos = positions[:, None] + jnp.arange(kw)[None]  # (S, K)
        blk = jnp.take_along_axis(block_tables, abs_pos // bs, axis=1)
        off = abs_pos % bs  # (S, K)
        ck = _kv_scatter(cache["k"], blk, off, k)
        cv = _kv_scatter(cache["v"], blk, off, v)
        from ..ops import paged_attention as paged_ops
        if paged_ops.current_decode_kernel() == paged_ops.FUSED:
            # Same fused kernel as decode: candidate row k of slot s
            # masks itself to positions <= positions[s] + k in-kernel.
            ctx = paged_ops.paged_attention(
                q, ck, cv, block_tables, positions
            ).reshape(s, kw, h * hd)
            out = jnp.dot(
                ctx, maybe_dequantize(params["wo"]).astype(ctx.dtype)
            )
            if self.use_bias:
                out = out + params["bo"].astype(out.dtype)
            return out, {"k": ck, "v": cv}
        ll = block_tables.shape[1] * bs
        # Per-slot union of the K candidates' causal windows — what any
        # row of this dispatch can ever expose (the view-level mask).
        row_vis = (
            jnp.arange(ll)[None, :] <= (positions + kw - 1)[:, None]
        )  # (S, L)
        view_k = self._paged_view(
            ck, block_tables, q.dtype, visible=row_vis
        )  # (S, L, H, hd)
        view_v = self._paged_view(
            cv, block_tables, q.dtype, visible=row_vis
        )
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, view_k,
            preferred_element_type=jnp.float32,
        ) / jnp.sqrt(jnp.float32(hd))  # (S, H, K, L)
        visible = (
            jnp.arange(view_k.shape[1])[None, None, :] <= abs_pos[:, :, None]
        )  # (S, K, L): candidate j attends through its own position
        scores = jnp.where(
            visible[:, None, :, :], scores, jnp.float32(-1e30)
        )
        attn = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", attn, view_v).reshape(s, kw,
                                                                  h * hd)
        out = jnp.dot(ctx, maybe_dequantize(params["wo"]).astype(ctx.dtype))
        if self.use_bias:
            out = out + params["bo"].astype(out.dtype)
        return out, {"k": ck, "v": cv}

    def paged_prefill(self, params, state, cache, x, *, block_table, start):
        """Prompt-chunk prefill for one sequence: x (1, C, D) covers
        absolute positions [start, start+C). The whole chunk's K/V is
        computed in ONE parallel pass (this is the prefill/decode split —
        prompts never crawl through the one-token decode path), scattered
        into the sequence's blocks, and attention runs against the full
        cached prefix + chunk (so chunked prefill composes: chunk i
        attends to chunks < i through the pool)."""
        if not self.causal:
            raise NotImplementedError(
                "incremental decode requires causal attention "
                "(MultiHeadAttention(causal=True)); bidirectional models "
                "have no autoregressive decode"
            )
        dt = resolve_dtype(self.dtype)
        if dt is not None:
            x = x.astype(dt)
        c = x.shape[1]
        h = self.num_heads
        hd = shape_of(params["wq"])[1] // h
        bs = _kv_block_size(cache["k"])
        q = self._proj(params, x, "wq", "bq").reshape(1, c, h, hd)
        k = self._proj(params, x, "wk", "bk").reshape(c, h, hd)
        v = self._proj(params, x, "wv", "bv").reshape(c, h, hd)
        abs_pos = start + jnp.arange(c)  # (C,)
        blk = block_table[abs_pos // bs]  # (C,)
        off = abs_pos % bs
        ck = _kv_scatter(cache["k"], blk, off, k)
        cv = _kv_scatter(cache["v"], blk, off, v)
        ll = block_table.shape[0] * bs
        chunk_vis = (jnp.arange(ll) <= start + c - 1)[None]  # (1, L)
        view_k = self._paged_view(
            ck, block_table[None], q.dtype, visible=chunk_vis
        )[0]
        view_v = self._paged_view(
            cv, block_table[None], q.dtype, visible=chunk_vis
        )[0]
        scores = jnp.einsum(
            "bqhd,khd->bhqk", q, view_k,
            preferred_element_type=jnp.float32,
        ) / jnp.sqrt(jnp.float32(hd))  # (1, H, C, L)
        visible = (
            jnp.arange(view_k.shape[0])[None, :] <= abs_pos[:, None]
        )  # (C, L): causal against the absolute position of each query
        scores = jnp.where(
            visible[None, None, :, :], scores, jnp.float32(-1e30)
        )
        attn = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        ctx = jnp.einsum("bhqk,khd->bqhd", attn, view_v).reshape(1, c,
                                                                 h * hd)
        out = jnp.dot(ctx, maybe_dequantize(params["wo"]).astype(ctx.dtype))
        if self.use_bias:
            out = out + params["bo"].astype(out.dtype)
        return out, {"k": ck, "v": cv}

    def apply(self, params, state, x, *, train=False, rng=None):
        dt = resolve_dtype(self.dtype)
        if dt is not None:
            x = x.astype(dt)
        b, t, _ = x.shape
        h = self.num_heads
        hd = shape_of(params["wq"])[1] // h  # robust if apply runs on a fresh instance
        q = self._proj(params, x, "wq", "bq").reshape(b, t, h, hd)
        k = self._proj(params, x, "wk", "bk").reshape(b, t, h, hd)
        v = self._proj(params, x, "wv", "bv").reshape(b, t, h, hd)
        ring = self._ring_config()
        if ring is not None:
            mesh, batch_axis, mode = ring
            if mode == "ulysses":
                ctx = self._ulysses_attention(q, k, v, mesh, batch_axis)
            else:
                from ..ops.ring_attention import ring_attention

                ctx = ring_attention(
                    q, k, v,
                    mesh=mesh,
                    seq_axis=self.ring_axis,
                    batch_axis=batch_axis,
                    causal=self.causal,
                )
        elif self._use_flash(t):
            ctx = self._flash_call(q, k, v)
        else:
            from ..ops.flash_attention import dense_attention

            ctx = dense_attention(q, k, v, self.causal)
        ctx = ctx.reshape(b, t, h * hd)
        out = jnp.dot(ctx, maybe_dequantize(params["wo"]).astype(ctx.dtype))
        if self.use_bias:
            out = out + params["bo"].astype(out.dtype)
        return out, {}


class PositionalEmbedding(Layer):
    """Learned absolute positions, added to (B, T, D) activations."""

    def __init__(self, max_len: int, name: Optional[str] = None):
        super().__init__(name)
        self.max_len = int(max_len)

    def init(self, key, input_shape: Shape):
        t, d = input_shape
        if t > self.max_len:
            raise ValueError(
                f"sequence length {t} exceeds max_len {self.max_len}"
            )
        table = initializers.normal(0.02)(key, (self.max_len, d), jnp.float32)
        return {"table": table}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None):
        t = x.shape[1]
        table = maybe_dequantize(params["table"])
        return x + table[:t][None].astype(x.dtype), {}

    decode_safe = True  # positional rows picked by ``pos``, not x.shape

    def init_cache(self, params, batch, max_len, dtype):
        if max_len > self.max_len:
            raise ValueError(
                f"generation length {max_len} exceeds positional table "
                f"max_len {self.max_len}"
            )
        return {}

    def decode(self, params, state, cache, x, *, pos):
        row = jax.lax.dynamic_slice_in_dim(
            maybe_dequantize(params["table"]), pos, 1, axis=0
        )  # (1, D)
        return x + row[None].astype(x.dtype), cache

    def paged_decode(self, params, state, cache, x, *, block_tables,
                     positions):
        # Per-SLOT positions: slot s reads table row positions[s] — the
        # vectorized form of decode()'s single dynamic row.
        rows = jnp.take(
            maybe_dequantize(params["table"]), positions, axis=0
        )  # (S, D)
        return x + rows[:, None].astype(x.dtype), cache

    def paged_verify(self, params, state, cache, x, *, block_tables,
                     positions):
        # Slot s's K candidates sit at positions[s] + 0..K-1.
        kw = x.shape[1]
        abs_pos = positions[:, None] + jnp.arange(kw)[None]  # (S, K)
        rows = jnp.take(
            maybe_dequantize(params["table"]), abs_pos, axis=0
        )  # (S, K, D)
        return x + rows.astype(x.dtype), cache

    def paged_prefill(self, params, state, cache, x, *, block_table, start):
        c = x.shape[1]
        rows = jax.lax.dynamic_slice_in_dim(
            maybe_dequantize(params["table"]), start, c, axis=0
        )  # (C, D)
        return x + rows[None].astype(x.dtype), cache
