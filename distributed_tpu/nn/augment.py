"""Device-side image augmentation layers (train-only, eval = identity).

The reference's pipelines feed raw /255-scaled arrays with no augmentation
(/root/reference/README.md:51-56); an ImageNet-scale flow (BASELINE.json
configs[3]) needs the standard random-crop + horizontal-flip recipe. The
TPU-first place for it is INSIDE the jitted train step, as layers: the
flips/crops are elementwise/gather work XLA fuses with the input cast, the
per-sample randomness comes from the step rng (so augmentation is
deterministic given (seed, step) — crash-restart resume replays the same
batches AND the same crops), and the host input pipeline stays a dumb
byte-mover. This mirrors Keras's preprocessing layers
(``tf.keras.layers.RandomFlip`` / ``RandomCrop``), so the migration story
stays "same model code, TPU underneath".
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .core import Layer, Shape


class RandomFlip(Layer):
    """Per-sample horizontal (and/or vertical) flip with probability 0.5.

    Train-only; eval mode is the identity. Expects NHWC inputs.
    """

    needs_rng = True
    decode_safe = False  # mixes spatial positions

    def __init__(self, mode: str = "horizontal", name: Optional[str] = None):
        super().__init__(name)
        if mode not in ("horizontal", "vertical", "horizontal_and_vertical"):
            raise ValueError(
                f"mode must be 'horizontal', 'vertical', or "
                f"'horizontal_and_vertical', got {mode!r}"
            )
        self.mode = mode

    def init(self, key, input_shape: Shape):
        if len(input_shape) != 3:
            raise ValueError(
                f"RandomFlip expects (H, W, C) inputs, got {input_shape}"
            )
        return {}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None):
        if not train:
            return x, {}
        if rng is None:
            raise ValueError("RandomFlip needs an rng when train=True")
        b = x.shape[0]
        k_h, k_v = jax.random.split(rng)
        if self.mode in ("horizontal", "horizontal_and_vertical"):
            coin = jax.random.bernoulli(k_h, 0.5, (b, 1, 1, 1))
            x = jnp.where(coin, x[:, :, ::-1, :], x)
        if self.mode in ("vertical", "horizontal_and_vertical"):
            coin = jax.random.bernoulli(k_v, 0.5, (b, 1, 1, 1))
            x = jnp.where(coin, x[:, ::-1, :, :], x)
        return x, {}


class RandomCrop(Layer):
    """Per-sample random crop to (height, width), optionally zero-padding
    first (the CIFAR pad-4-crop-32 recipe). Eval mode center-crops.

    Expects NHWC inputs; output is (height, width, C).
    """

    needs_rng = True
    decode_safe = False  # mixes spatial positions

    def __init__(self, height: int, width: int, *, padding: int = 0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.height = int(height)
        self.width = int(width)
        self.padding = int(padding)
        if self.height < 1 or self.width < 1 or self.padding < 0:
            raise ValueError(
                f"Invalid crop ({height}x{width}, padding={padding})"
            )

    def init(self, key, input_shape: Shape):
        if len(input_shape) != 3:
            raise ValueError(
                f"RandomCrop expects (H, W, C) inputs, got {input_shape}"
            )
        h, w, c = input_shape
        p = self.padding
        if self.height > h + 2 * p or self.width > w + 2 * p:
            raise ValueError(
                f"Crop {self.height}x{self.width} larger than padded input "
                f"{h + 2 * p}x{w + 2 * p}"
            )
        return {}, {}, (self.height, self.width, c)

    def _pad(self, x):
        p = self.padding
        if p == 0:
            return x
        return jnp.pad(x, ((0, 0), (p, p), (p, p), (0, 0)))

    def apply(self, params, state, x, *, train=False, rng=None):
        xp = self._pad(x)
        _, h, w, _ = xp.shape
        max_y = h - self.height
        max_x = w - self.width
        if not train:
            # Deterministic center crop.
            y0, x0 = max_y // 2, max_x // 2
            return xp[:, y0:y0 + self.height, x0:x0 + self.width, :], {}
        if rng is None:
            raise ValueError("RandomCrop needs an rng when train=True")
        b = xp.shape[0]
        k_y, k_x = jax.random.split(rng)
        ys = jax.random.randint(k_y, (b,), 0, max_y + 1)
        xs = jax.random.randint(k_x, (b,), 0, max_x + 1)

        def crop_one(img, y0, x0):
            return jax.lax.dynamic_slice(
                img, (y0, x0, 0),
                (self.height, self.width, img.shape[-1]),
            )

        return jax.vmap(crop_one)(xp, ys, xs), {}
