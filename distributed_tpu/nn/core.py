"""Functional layer system.

Design notes (TPU-first):

- A Layer is a *pure description*: construction stores hyperparameters only.
  Parameters live in plain nested-dict pytrees created by ``init`` and are
  threaded explicitly through ``apply``. This is the JAX idiom (init/apply)
  rather than the reference's object-holding-variables Keras idiom
  (/root/reference/README.md:292-298), and is what makes a whole train step
  jit-compilable and shardable with ``NamedSharding`` over a device mesh.
- ``apply`` is side-effect free: mutable layer state (e.g. BatchNorm running
  stats) is returned, never written in place, so XLA sees static dataflow.
- Shapes are static: ``init`` takes the (batch-free) input shape and performs
  shape inference once, in Python, outside any trace.

The public surface still *reads* like the reference's Keras Sequential UX
(/root/reference/README.md:58-68): ``Sequential([Conv2D(...), Flatten(),
Dense(...)])``.
"""

from __future__ import annotations

import contextlib
import re
import threading
from typing import Any, Dict, Optional, Sequence, Tuple

import jax

Params = Dict[str, Any]
State = Dict[str, Any]
Shape = Tuple[int, ...]


def _camel_to_snake(name: str) -> str:
    # Conv2D -> conv2d, MaxPool2D -> max_pool2d (split only at lower->Upper).
    return re.sub(r"(?<=[a-z])(?=[A-Z])", "_", name).lower()


class Layer:
    """Base class: hyperparameters in, pure init/apply out."""

    def __init__(self, name: Optional[str] = None):
        self.name = name  # finalized by the enclosing container (or init())
        self._name_explicit = name is not None

    # -- to be overridden ---------------------------------------------------
    def init(self, key: jax.Array, input_shape: Shape) -> Tuple[Params, State, Shape]:
        """Create (params, state, output_shape) for a given unbatched input shape."""
        raise NotImplementedError

    def apply(
        self,
        params: Params,
        state: State,
        x,
        *,
        train: bool = False,
        rng: Optional[jax.Array] = None,
    ):
        """Run the layer on a batched input. Returns (output, new_state)."""
        raise NotImplementedError

    # -- incremental decode (KV-cache generation) ---------------------------
    # True means apply() treats every (batch of) position(s) independently,
    # so the default one-token decode below is exact. Layers that mix
    # positions (attention, positional embeddings, scanned block stacks)
    # either override decode() with a cached implementation or set this
    # False to fail loudly.
    decode_safe = True

    def init_cache(self, params: Params, batch: int, max_len: int, dtype):
        """Create this layer's decode cache (empty for stateless layers)."""
        return {}

    def decode(self, params: Params, state: State, cache, x, *, pos):
        """One autoregressive step: x is (B, 1, ...), pos the (traced)
        position index. Returns (output, new_cache)."""
        if not self.decode_safe:
            raise NotImplementedError(
                f"{type(self).__name__} does not support incremental "
                "decode (generation)"
            )
        out, _ = self.apply(params, state, x, train=False)
        return out, cache

    # -- paged decode (block KV cache, serving.Engine) ----------------------
    # The paged counterparts of init_cache/decode: instead of one dense
    # (B, max_len, ...) cache per sequence, attention layers write into a
    # shared pool of fixed-size blocks, addressed through per-slot block
    # tables — sequences of different lengths share one HBM pool
    # (vLLM-style PagedAttention). Slots also carry PER-SLOT positions
    # (a (S,) vector, not one scalar), which is what lets the serving
    # engine decode sequences at different depths in one fixed-shape
    # dispatch. Position-independent layers ride their existing decode()
    # (which ignores pos); position-dependent layers (attention,
    # positional embeddings) override.

    def init_paged_cache(self, params: Params, num_blocks: int,
                         block_size: int, dtype):
        """Create this layer's share of the paged KV pool (empty for
        layers that cache nothing)."""
        return {}

    def paged_decode(self, params: Params, state: State, cache, x, *,
                     block_tables, positions):
        """One decode step for a batch of SLOTS: x is (S, 1, ...),
        ``block_tables`` (S, max_blocks) int32 pool indices,
        ``positions`` (S,) int32 per-slot write/attend positions.
        Returns (output, new_cache)."""
        out, _ = self.decode(params, state, {}, x, pos=positions)
        return out, cache

    def paged_verify(self, params: Params, state: State, cache, x, *,
                     block_tables, positions):
        """Speculative verification for a batch of SLOTS: x is
        (S, K, ...) — K draft-proposed candidate tokens per slot at
        consecutive absolute positions [positions[s], positions[s]+K) —
        scored in one fixed-shape dispatch (K=1 is exactly paged_decode).
        Default: position-independent layers apply tokenwise (the K
        candidates are just more positions); position-dependent layers
        (attention, positional embeddings) override."""
        if not self.decode_safe:
            raise NotImplementedError(
                f"{type(self).__name__} does not support incremental "
                "decode (generation)"
            )
        out, _ = self.apply(params, state, x, train=False)
        return out, cache

    def paged_prefill(self, params: Params, state: State, cache, x, *,
                      block_table, start):
        """Prompt-chunk prefill for ONE sequence: x is (1, C, ...) covering
        absolute positions [start, start+C); writes this chunk's KV into
        the blocks named by ``block_table`` (max_blocks,) and returns
        (output, new_cache). Default: position-independent layers apply
        tokenwise and cache nothing."""
        if not self.decode_safe:
            raise NotImplementedError(
                f"{type(self).__name__} does not support incremental "
                "decode (generation)"
            )
        out, _ = self.apply(params, state, x, train=False)
        return out, cache

    # -- shared helpers -----------------------------------------------------
    def sharding_hints(self) -> Dict[str, str]:
        """Tensor-parallel roles for this layer's params: param name ->
        'col' (shard output dim over the model axis) or 'row' (shard input
        dim). Containers nest these to mirror the params tree; strategies
        translate roles into PartitionSpecs. Empty = fully replicated."""
        return {}

    def dtype_hints(self):
        """Explicit per-layer compute-dtype overrides, mirroring the params
        tree the way ``sharding_hints`` does: a layer constructed with
        ``dtype=...`` reports that dtype; containers nest children under
        their names. ``Policy.cast_to_compute`` skips the marked subtrees,
        so an explicitly-dtyped layer keeps master-precision params and
        performs its own cast — per-layer ``dtype=`` overrides the policy
        exactly. None/{} = no override (the policy's compute dtype
        applies)."""
        return getattr(self, "dtype", None)

    def default_name(self) -> str:
        return _camel_to_snake(type(self).__name__)

    def param_spec(self, input_shape: Shape) -> Dict[str, Shape]:
        """Shapes of this layer's parameters (used for sharding rules); optional."""
        return {}

    def __repr__(self):
        return f"{type(self).__name__}(name={self.name!r})"


class NameScope:
    """Assigns unique keras-style names ('conv2d', 'conv2d_1', ...) within a container."""

    def __init__(self):
        self._counts: Dict[str, int] = {}
        self._used = set()

    def assign(self, layer: Layer) -> str:
        if layer._name_explicit and layer.name:
            if layer.name in self._used:
                raise ValueError(f"Duplicate layer name {layer.name!r}")
            self._used.add(layer.name)
            return layer.name
        base = layer.default_name()
        n = self._counts.get(base, 0)
        self._counts[base] = n + 1
        name = base if n == 0 else f"{base}_{n}"
        self._used.add(name)
        return name


_AMBIENT_WEIGHTS = threading.local()


@contextlib.contextmanager
def eval_sample_weights(weights):
    """Trace-time ambient per-EXAMPLE validity weights (shape (B,)).

    The eval step pads its final batch to keep shapes static; layers whose
    statistics span the batch (MoE routing: load-balance aux loss,
    capacity competition) would otherwise count the pad rows. The eval
    steps wrap ``module.apply`` in this context and such layers read
    ``current_sample_weights()`` during tracing — the weights are a traced
    array, so they become a real input of the compiled step. Training
    never sets this (fit never pads), so the train graph is unchanged."""
    prev = getattr(_AMBIENT_WEIGHTS, "value", None)
    _AMBIENT_WEIGHTS.value = weights
    try:
        yield
    finally:
        _AMBIENT_WEIGHTS.value = prev


def current_sample_weights():
    return getattr(_AMBIENT_WEIGHTS, "value", None)


def apply_layers(layers, params, state, x, *, train=False, rng=None):
    """Apply a sequence of layers with Sequential's rng-split and state-
    collection discipline. The SINGLE implementation of that discipline:
    Sequential.apply delegates here, and the chunked-head training path
    (training/model.py) applies a Sequential's body (all layers but the
    head) through the same function, so the two can't drift."""
    new_state: State = {}
    n_rng = sum(1 for l in layers if getattr(l, "needs_rng", False))
    rngs = iter(jax.random.split(rng, n_rng)) if (rng is not None and n_rng) else iter(())
    for layer in layers:
        layer_rng = next(rngs, None) if getattr(layer, "needs_rng", False) else None
        x, s = layer.apply(
            params.get(layer.name, {}),
            state.get(layer.name, {}),
            x,
            train=train,
            rng=layer_rng,
        )
        if s:
            new_state[layer.name] = s
    return x, new_state


class Sequential(Layer):
    """Linear stack of layers; itself a Layer, so stacks compose.

    Parity target: ``keras_model_sequential() %>% layer_conv_2d(...) %>% ...``
    (/root/reference/README.md:58-68) and ``tf.keras.Sequential([...])``
    (/root/reference/README.md:292-298).
    """

    def __init__(self, layers: Sequence[Layer], name: Optional[str] = None):
        super().__init__(name)
        self.layers = list(layers)
        scope = NameScope()
        for layer in self.layers:
            layer.name = scope.assign(layer)

    def add(self, layer: Layer):
        scope = NameScope()
        for existing in self.layers:
            scope._used.add(existing.name)
            m = re.fullmatch(r"(.+?)(?:_(\d+))?", existing.name)
            base = m.group(1) if m else existing.name
            idx = int(m.group(2)) + 1 if m and m.group(2) else 1
            scope._counts[base] = max(scope._counts.get(base, 0), idx)
        layer.name = scope.assign(layer)
        self.layers.append(layer)

    @property
    def needs_rng(self) -> bool:
        # Containers need an rng iff any child does (nested Dropout etc.).
        return any(getattr(l, "needs_rng", False) for l in self.layers)

    def init(self, key, input_shape):
        params: Params = {}
        state: State = {}
        shape = tuple(input_shape)
        keys = jax.random.split(key, max(len(self.layers), 1))
        for layer, k in zip(self.layers, keys):
            p, s, shape = layer.init(k, shape)
            if p:
                params[layer.name] = p
            if s:
                state[layer.name] = s
        return params, state, shape

    def sharding_hints(self):
        hints = {}
        for layer in self.layers:
            h = layer.sharding_hints()
            if h:
                hints[layer.name] = h
        return hints

    def dtype_hints(self):
        hints = {}
        for layer in self.layers:
            h = layer.dtype_hints()
            if h is not None and h != {}:
                hints[layer.name] = h
        return hints

    def apply(self, params, state, x, *, train=False, rng=None):
        return apply_layers(
            self.layers, params, state, x, train=train, rng=rng
        )

    def init_cache(self, params, batch, max_len, dtype):
        caches = {}
        for layer in self.layers:
            c = layer.init_cache(
                params.get(layer.name, {}), batch, max_len, dtype
            )
            if c:
                caches[layer.name] = c
        return caches

    def decode(self, params, state, cache, x, *, pos):
        new_cache = dict(cache)
        for layer in self.layers:
            x, c = layer.decode(
                params.get(layer.name, {}),
                state.get(layer.name, {}),
                cache.get(layer.name, {}),
                x,
                pos=pos,
            )
            if c:
                new_cache[layer.name] = c
        return x, new_cache

    def init_paged_cache(self, params, num_blocks, block_size, dtype):
        caches = {}
        for layer in self.layers:
            c = layer.init_paged_cache(
                params.get(layer.name, {}), num_blocks, block_size, dtype
            )
            if c:
                caches[layer.name] = c
        return caches

    def paged_decode(self, params, state, cache, x, *, block_tables,
                     positions):
        new_cache = dict(cache)
        for layer in self.layers:
            x, c = layer.paged_decode(
                params.get(layer.name, {}),
                state.get(layer.name, {}),
                cache.get(layer.name, {}),
                x,
                block_tables=block_tables,
                positions=positions,
            )
            if c:
                new_cache[layer.name] = c
        return x, new_cache

    def paged_verify(self, params, state, cache, x, *, block_tables,
                     positions):
        new_cache = dict(cache)
        for layer in self.layers:
            x, c = layer.paged_verify(
                params.get(layer.name, {}),
                state.get(layer.name, {}),
                cache.get(layer.name, {}),
                x,
                block_tables=block_tables,
                positions=positions,
            )
            if c:
                new_cache[layer.name] = c
        return x, new_cache

    def paged_prefill(self, params, state, cache, x, *, block_table, start):
        new_cache = dict(cache)
        for layer in self.layers:
            x, c = layer.paged_prefill(
                params.get(layer.name, {}),
                state.get(layer.name, {}),
                cache.get(layer.name, {}),
                x,
                block_table=block_table,
                start=start,
            )
            if c:
                new_cache[layer.name] = c
        return x, new_cache

    def summary_lines(self, input_shape: Shape):
        """Keras-style summary rows: (name, output_shape, param_count)."""
        from ..utils.tree import tree_size

        rows = []
        key = jax.random.PRNGKey(0)
        shape = tuple(input_shape)
        for layer in self.layers:
            p, _, shape = layer.init(key, shape)
            rows.append((layer.name, (None,) + shape, tree_size(p)))
        return rows

    def __repr__(self):
        inner = ", ".join(repr(l) for l in self.layers)
        return f"Sequential([{inner}])"


class Residual(Layer):
    """Skip connection: ``y = activation(main(x) + shortcut(x))``.

    ``shortcut`` defaults to the identity. This is the non-sequential
    composition primitive the ResNet family needs; both branches are ordinary
    Layers (usually Sequentials), so the whole block still jits into one XLA
    program with static dataflow — the add fuses into the preceding conv's
    epilogue on TPU.
    """

    def __init__(self, main: Layer, shortcut: Optional[Layer] = None,
                 activation=None, name: Optional[str] = None):
        super().__init__(name)
        from . import activations  # local import: core must not cycle

        self.main = main
        self.shortcut = shortcut
        self.activation = activations.get(activation)
        for branch, default in ((main, "main"), (shortcut, "shortcut")):
            if branch is not None and branch.name is None:
                branch.name = default

    @property
    def needs_rng(self) -> bool:
        return any(
            getattr(b, "needs_rng", False)
            for b in (self.main, self.shortcut)
            if b is not None
        )

    def init(self, key, input_shape):
        k1, k2 = jax.random.split(key)
        pm, sm, out_main = self.main.init(k1, tuple(input_shape))
        if self.shortcut is not None:
            ps, ss, out_sc = self.shortcut.init(k2, tuple(input_shape))
        else:
            ps, ss, out_sc = {}, {}, tuple(input_shape)
        if out_main != out_sc:
            raise ValueError(
                f"Residual branch shapes differ: main {out_main} vs "
                f"shortcut {out_sc} (add a projection shortcut)"
            )
        params = {"main": pm}
        state = {"main": sm} if sm else {}
        if ps:
            params["shortcut"] = ps
        if ss:
            state["shortcut"] = ss
        return params, state, out_main

    def sharding_hints(self):
        hints = {}
        h = self.main.sharding_hints()
        if h:
            hints["main"] = h
        if self.shortcut is not None:
            h = self.shortcut.sharding_hints()
            if h:
                hints["shortcut"] = h
        return hints

    def dtype_hints(self):
        hints = {}
        h = self.main.dtype_hints()
        if h is not None and h != {}:
            hints["main"] = h
        if self.shortcut is not None:
            h = self.shortcut.dtype_hints()
            if h is not None and h != {}:
                hints["shortcut"] = h
        return hints

    def apply(self, params, state, x, *, train=False, rng=None):
        rngs = (
            jax.random.split(rng, 2) if rng is not None else (None, None)
        )
        main_rng = rngs[0] if getattr(self.main, "needs_rng", False) else None
        y, sm = self.main.apply(
            params.get("main", {}), state.get("main", {}), x,
            train=train, rng=main_rng,
        )
        if self.shortcut is not None:
            sc_rng = rngs[1] if getattr(self.shortcut, "needs_rng", False) else None
            sc, ss = self.shortcut.apply(
                params.get("shortcut", {}), state.get("shortcut", {}), x,
                train=train, rng=sc_rng,
            )
        else:
            sc, ss = x, {}
        new_state = {}
        if sm:
            new_state["main"] = sm
        if ss:
            new_state["shortcut"] = ss
        return self.activation(y + sc), new_state

    def init_cache(self, params, batch, max_len, dtype):
        caches = {}
        c = self.main.init_cache(params.get("main", {}), batch, max_len, dtype)
        if c:
            caches["main"] = c
        if self.shortcut is not None:
            c = self.shortcut.init_cache(
                params.get("shortcut", {}), batch, max_len, dtype
            )
            if c:
                caches["shortcut"] = c
        return caches

    def decode(self, params, state, cache, x, *, pos):
        y, cm = self.main.decode(
            params.get("main", {}), state.get("main", {}),
            cache.get("main", {}), x, pos=pos,
        )
        new_cache = dict(cache)
        if cm:
            new_cache["main"] = cm
        if self.shortcut is not None:
            sc, cs = self.shortcut.decode(
                params.get("shortcut", {}), state.get("shortcut", {}),
                cache.get("shortcut", {}), x, pos=pos,
            )
            if cs:
                new_cache["shortcut"] = cs
        else:
            sc = x
        return self.activation(y + sc), new_cache

    def init_paged_cache(self, params, num_blocks, block_size, dtype):
        caches = {}
        c = self.main.init_paged_cache(
            params.get("main", {}), num_blocks, block_size, dtype
        )
        if c:
            caches["main"] = c
        if self.shortcut is not None:
            c = self.shortcut.init_paged_cache(
                params.get("shortcut", {}), num_blocks, block_size, dtype
            )
            if c:
                caches["shortcut"] = c
        return caches

    def paged_decode(self, params, state, cache, x, *, block_tables,
                     positions):
        y, cm = self.main.paged_decode(
            params.get("main", {}), state.get("main", {}),
            cache.get("main", {}), x,
            block_tables=block_tables, positions=positions,
        )
        new_cache = dict(cache)
        if cm:
            new_cache["main"] = cm
        if self.shortcut is not None:
            sc, cs = self.shortcut.paged_decode(
                params.get("shortcut", {}), state.get("shortcut", {}),
                cache.get("shortcut", {}), x,
                block_tables=block_tables, positions=positions,
            )
            if cs:
                new_cache["shortcut"] = cs
        else:
            sc = x
        return self.activation(y + sc), new_cache

    def paged_verify(self, params, state, cache, x, *, block_tables,
                     positions):
        y, cm = self.main.paged_verify(
            params.get("main", {}), state.get("main", {}),
            cache.get("main", {}), x,
            block_tables=block_tables, positions=positions,
        )
        new_cache = dict(cache)
        if cm:
            new_cache["main"] = cm
        if self.shortcut is not None:
            sc, cs = self.shortcut.paged_verify(
                params.get("shortcut", {}), state.get("shortcut", {}),
                cache.get("shortcut", {}), x,
                block_tables=block_tables, positions=positions,
            )
            if cs:
                new_cache["shortcut"] = cs
        else:
            sc = x
        return self.activation(y + sc), new_cache

    def paged_prefill(self, params, state, cache, x, *, block_table, start):
        y, cm = self.main.paged_prefill(
            params.get("main", {}), state.get("main", {}),
            cache.get("main", {}), x, block_table=block_table, start=start,
        )
        new_cache = dict(cache)
        if cm:
            new_cache["main"] = cm
        if self.shortcut is not None:
            sc, cs = self.shortcut.paged_prefill(
                params.get("shortcut", {}), state.get("shortcut", {}),
                cache.get("shortcut", {}), x,
                block_table=block_table, start=start,
            )
            if cs:
                new_cache["shortcut"] = cs
        else:
            sc = x
        return self.activation(y + sc), new_cache

    def __repr__(self):
        return (
            f"Residual(main={self.main!r}, shortcut={self.shortcut!r})"
        )


class Lambda(Layer):
    """Wrap an arbitrary stateless function ``fn(x) -> y``."""

    # The wrapped fn is opaque — it may mix positions (e.g. a reduction
    # over the time axis), so one-token decode cannot be assumed exact.
    decode_safe = False

    def __init__(self, fn, output_shape=None, name=None):
        super().__init__(name)
        self.fn = fn
        self._output_shape = output_shape

    def init(self, key, input_shape):
        if self._output_shape is not None:
            out = tuple(self._output_shape)
        else:
            out = jax.eval_shape(self.fn, jax.ShapeDtypeStruct((1,) + tuple(input_shape), "float32")).shape[1:]
        return {}, {}, out

    def apply(self, params, state, x, *, train=False, rng=None):
        return self.fn(x), {}
