"""Weight initializers (TPU-friendly: everything is a pure function of a PRNG key).

Mirrors the initializer surface the reference's Keras layers rely on
(glorot_uniform default for Conv2D/Dense — /root/reference/README.md:292-298),
implemented as thin wrappers over jax.nn.initializers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_REGISTRY = {
    "glorot_uniform": jax.nn.initializers.glorot_uniform,
    "glorot_normal": jax.nn.initializers.glorot_normal,
    "he_uniform": jax.nn.initializers.he_uniform,
    "he_normal": jax.nn.initializers.he_normal,
    "lecun_normal": jax.nn.initializers.lecun_normal,
    "zeros": lambda: jax.nn.initializers.zeros,
    "ones": lambda: jax.nn.initializers.ones,
}


def get(name_or_fn, dtype=jnp.float32):
    """Resolve an initializer by Keras-style name or pass a callable through."""
    if callable(name_or_fn):
        return name_or_fn
    try:
        factory = _REGISTRY[name_or_fn]
    except KeyError:
        raise ValueError(
            f"Unknown initializer {name_or_fn!r}; known: {sorted(_REGISTRY)}"
        ) from None
    return factory()


def normal(stddev=0.01):
    return jax.nn.initializers.normal(stddev)
