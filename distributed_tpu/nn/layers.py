"""Standard layers, NHWC, MXU-friendly.

Covers the layer surface the reference's scripts use — Conv2D / Flatten /
Dense with relu (/root/reference/README.md:58-68, 292-298) — plus the layers
the wider model zoo (ResNet-50, Transformer) needs.

TPU notes:
- Convs/matmuls go through ``lax.conv_general_dilated`` / ``jnp.dot`` so XLA
  tiles them onto the MXU; ``dtype`` selects the compute precision (bfloat16
  recommended) while parameters stay float32.
- All layers are shape-static and trace-free of Python control flow, so the
  whole model jits into one XLA program.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import functools

import jax
import jax.numpy as jnp
from jax import lax

from . import activations, initializers
from .core import Layer, Shape
from ..precision import resolve_dtype
from ..quant import is_quantized_leaf, maybe_dequantize

IntOr2 = Union[int, Tuple[int, int]]


def _pair(v: IntOr2) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def _conv_out(size: int, k: int, s: int, padding: str) -> int:
    if padding.upper() == "SAME":
        return -(-size // s)
    return (size - k) // s + 1


class Conv2D(Layer):
    """2-D convolution over NHWC inputs (kernel laid out HWIO for XLA)."""

    # Convolution mixes neighbouring positions, so the inherited one-token
    # decode would be silently wrong for a sequence model that routes time
    # through a spatial axis; fail loudly instead.
    decode_safe = False

    def __init__(
        self,
        filters: int,
        kernel_size: IntOr2,
        strides: IntOr2 = 1,
        padding: str = "valid",
        activation=None,
        use_bias: bool = True,
        kernel_initializer="glorot_uniform",
        dtype=None,
        name: Optional[str] = None,
    ):
        super().__init__(name)
        self.filters = int(filters)
        self.kernel_size = _pair(kernel_size)
        self.strides = _pair(strides)
        self.padding = padding.upper()
        self.activation = activations.get(activation)
        self.use_bias = use_bias
        self.kernel_initializer = kernel_initializer
        self.dtype = dtype

    def init(self, key, input_shape: Shape):
        h, w, cin = input_shape
        kh, kw = self.kernel_size
        kernel = initializers.get(self.kernel_initializer)(
            key, (kh, kw, cin, self.filters), jnp.float32
        )
        params = {"kernel": kernel}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.filters,), jnp.float32)
        out = (
            _conv_out(h, kh, self.strides[0], self.padding),
            _conv_out(w, kw, self.strides[1], self.padding),
            self.filters,
        )
        return params, {}, out

    def apply(self, params, state, x, *, train=False, rng=None):
        # Weight-only int8 (quant.py): dequantize in-trace, then the
        # layer's own dtype handling applies as if the kernel were f32.
        kernel = maybe_dequantize(params["kernel"])
        dt = resolve_dtype(self.dtype)
        if dt is not None:
            x = x.astype(dt)
            kernel = kernel.astype(dt)
        y = lax.conv_general_dilated(
            x,
            kernel,
            window_strides=self.strides,
            padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return self.activation(y), {}


class Dense(Layer):
    """Affine map on the trailing axis; works for (B, D) and (B, T, D) alike."""

    def __init__(
        self,
        units: int,
        activation=None,
        use_bias: bool = True,
        kernel_initializer="glorot_uniform",
        dtype=None,
        shard: Optional[str] = None,
        name: Optional[str] = None,
    ):
        super().__init__(name)
        self.units = int(units)
        self.activation = activations.get(activation)
        self.use_bias = use_bias
        self.kernel_initializer = kernel_initializer
        self.dtype = dtype
        if shard not in (None, "col", "row"):
            raise ValueError(f"shard must be None/'col'/'row', got {shard!r}")
        self.shard = shard

    def sharding_hints(self):
        # Megatron-style TP: 'col' splits the output features over the model
        # axis (bias splits with them); 'row' splits the input features (the
        # partial products are summed by an XLA-inserted all-reduce, so the
        # bias stays replicated).
        if self.shard is None:
            return {}
        hints = {"kernel": self.shard}
        if self.use_bias and self.shard == "col":
            hints["bias"] = "col"
        return hints

    def init(self, key, input_shape: Shape):
        din = input_shape[-1]
        kernel = initializers.get(self.kernel_initializer)(
            key, (din, self.units), jnp.float32
        )
        params = {"kernel": kernel}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.units,), jnp.float32)
        return params, {}, tuple(input_shape[:-1]) + (self.units,)

    def apply(self, params, state, x, *, train=False, rng=None):
        # Weight-only int8 (quant.py): dequantize in-trace before the
        # matmul; storage stays int8 in HBM, compute dtype is unchanged.
        kernel = maybe_dequantize(params["kernel"])
        dt = resolve_dtype(self.dtype)
        if dt is not None:
            x = x.astype(dt)
            kernel = kernel.astype(dt)
        y = jnp.dot(x, kernel)
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return self.activation(y), {}


class SpaceToDepth(Layer):
    """Rearrange (B, H, W, C) -> (B, H/b, W/b, C*b*b) spatial blocks.

    The TPU stem trick: a 7x7/2 conv on 3-channel input packs only 3 of the
    MXU's 128 input lanes; space-to-depth by 2 turns the same arithmetic
    into a 4x4/1 conv on 12 channels (4x the lane packing), which XLA tiles
    far better. Pure data movement — one fused reshape/transpose pass."""

    decode_safe = False  # mixes spatial positions

    def __init__(self, block_size: int = 2, name: Optional[str] = None):
        super().__init__(name)
        self.block_size = int(block_size)

    def init(self, key, input_shape: Shape):
        h, w, c = input_shape
        b = self.block_size
        if h % b or w % b:
            raise ValueError(
                f"SpaceToDepth({b}) needs spatial dims divisible by {b}; "
                f"got {(h, w)}"
            )
        return {}, {}, (h // b, w // b, c * b * b)

    def apply(self, params, state, x, *, train=False, rng=None):
        n, h, w, c = x.shape
        b = self.block_size
        x = x.reshape(n, h // b, b, w // b, b, c)
        x = x.transpose(0, 1, 3, 2, 4, 5)
        return x.reshape(n, h // b, w // b, c * b * b), {}


class Flatten(Layer):
    decode_safe = False  # collapses all non-batch axes, including time

    def init(self, key, input_shape: Shape):
        out = 1
        for d in input_shape:
            out *= d
        return {}, {}, (out,)

    def apply(self, params, state, x, *, train=False, rng=None):
        return x.reshape((x.shape[0], -1)), {}


class Activation(Layer):
    def __init__(self, activation, name=None):
        super().__init__(name)
        self.fn = activations.get(activation)

    def init(self, key, input_shape):
        return {}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None):
        return self.fn(x), {}


class _Pool2D(Layer):
    decode_safe = False  # pooling windows span positions

    def __init__(self, pool_size: IntOr2 = 2, strides: Optional[IntOr2] = None, padding="valid", name=None):
        super().__init__(name)
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None else self.pool_size
        self.padding = padding.upper()

    def init(self, key, input_shape: Shape):
        h, w, c = input_shape
        out = (
            _conv_out(h, self.pool_size[0], self.strides[0], self.padding),
            _conv_out(w, self.pool_size[1], self.strides[1], self.padding),
            c,
        )
        return {}, {}, out

    def _reduce(self, x):
        raise NotImplementedError

    def apply(self, params, state, x, *, train=False, rng=None):
        return self._reduce(x), {}


class MaxPool2D(_Pool2D):
    def _reduce(self, x):
        return lax.reduce_window(
            x,
            -jnp.inf,
            lax.max,
            window_dimensions=(1,) + self.pool_size + (1,),
            window_strides=(1,) + self.strides + (1,),
            padding=self.padding,
        )


class AvgPool2D(_Pool2D):
    def _reduce(self, x):
        ones = lax.reduce_window(
            jnp.ones_like(x),
            0.0,
            lax.add,
            window_dimensions=(1,) + self.pool_size + (1,),
            window_strides=(1,) + self.strides + (1,),
            padding=self.padding,
        )
        summed = lax.reduce_window(
            x,
            0.0,
            lax.add,
            window_dimensions=(1,) + self.pool_size + (1,),
            window_strides=(1,) + self.strides + (1,),
            padding=self.padding,
        )
        return summed / ones


class GlobalAvgPool2D(Layer):
    decode_safe = False  # reduces over spatial/temporal axes

    def init(self, key, input_shape: Shape):
        return {}, {}, (input_shape[-1],)

    def apply(self, params, state, x, *, train=False, rng=None):
        return jnp.mean(x, axis=(1, 2)), {}


class Dropout(Layer):
    needs_rng = True

    def __init__(self, rate: float, name=None):
        super().__init__(name)
        self.rate = float(rate)

    def init(self, key, input_shape):
        return {}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None):
        if not train or self.rate == 0.0:
            return x, {}
        if rng is None:
            raise ValueError("Dropout needs an rng when train=True")
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype), {}


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _bn_norm(x, mean, var, scale, bias, epsilon):
    """Normalize with given batch stats; fused-BN custom VJP.

    The custom backward (the standard fused-BN formula: dx = inv * (dy -
    mean(dy) - xhat * mean(dy*xhat))) folds the stats' gradient
    contributions into dx and returns ZERO cotangents for mean/var, so the
    stats computation upstream keeps no autodiff residuals — in particular
    no float32 copy of a bf16 activation is ever saved; backward
    recomputes xhat from the (storage-dtype) input."""
    inv = lax.rsqrt(var + epsilon) * scale
    return (x - mean.astype(x.dtype)) * inv.astype(x.dtype) + bias.astype(
        x.dtype
    )


def _bn_norm_fwd(x, mean, var, scale, bias, epsilon):
    return _bn_norm(x, mean, var, scale, bias, epsilon), (x, mean, var, scale)


def _bn_norm_bwd(epsilon, res, dy):
    x, mean, var, scale = res
    reduce_axes = tuple(range(x.ndim - 1))
    n = 1
    for a in reduce_axes:
        n *= x.shape[a]
    inv0 = lax.rsqrt(var + epsilon)  # f32 (C,)
    xhat = (x.astype(jnp.float32) - mean) * inv0
    dyf = dy.astype(jnp.float32)
    dbias = jnp.sum(dyf, axis=reduce_axes)
    dscale = jnp.sum(dyf * xhat, axis=reduce_axes)
    dx = (scale * inv0) * (dyf - dbias / n - xhat * (dscale / n))
    return (
        dx.astype(x.dtype),
        jnp.zeros_like(mean),
        jnp.zeros_like(var),
        dscale,
        dbias,
    )


_bn_norm.defvjp(_bn_norm_fwd, _bn_norm_bwd)


class BatchNorm(Layer):
    """Batch normalization over all but the channel (last) axis.

    Under data parallelism the batch axis is sharded across the mesh; because
    the stats are plain ``jnp.mean`` reductions inside the jitted step, XLA
    lowers them to cross-replica collectives automatically — i.e. this is
    sync-BN by construction, no separate "SyncBatchNorm" needed.
    """

    # Class-level default for the batch-stats reduction strategy:
    # "reduce" (jnp.mean) or "dot" (matmul against ones — see apply()).
    stats_impl = "reduce"
    # Where the conditioning shift for the single-pass moments comes from:
    # "data" (per-channel mean of the first batch element — valid on any
    # input but SERIALIZES conv -> slice-reduce -> stats, so XLA cannot fuse
    # the stat reductions into the producing conv's epilogue) or "running"
    # (the running mean from state — a constant w.r.t. this batch, so the
    # stats become epilogue siblings of the producer and the activation is
    # never re-read from HBM for statistics; measured ~26% off a
    # conv+BN site's device time, examples/profile_resnet_xplane.py).
    stats_shift = "data"

    def __init__(self, momentum: float = 0.9, epsilon: float = 1e-5,
                 stats_impl: Optional[str] = None,
                 stats_shift: Optional[str] = None, name=None):
        super().__init__(name)
        self.momentum = float(momentum)
        self.epsilon = float(epsilon)
        if stats_impl is not None:
            if stats_impl not in ("reduce", "dot"):
                raise ValueError(
                    f"stats_impl must be 'reduce' or 'dot', got {stats_impl!r}"
                )
            self.stats_impl = stats_impl
        if stats_shift is not None:
            if stats_shift not in ("data", "running"):
                raise ValueError(
                    f"stats_shift must be 'data' or 'running', got "
                    f"{stats_shift!r}"
                )
            self.stats_shift = stats_shift

    def init(self, key, input_shape: Shape):
        c = input_shape[-1]
        params = {"scale": jnp.ones((c,), jnp.float32), "bias": jnp.zeros((c,), jnp.float32)}
        state = {"mean": jnp.zeros((c,), jnp.float32), "var": jnp.ones((c,), jnp.float32)}
        return params, state, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None):
        reduce_axes = tuple(range(x.ndim - 1))
        if train:
            # Single-pass shifted-moment statistics: reduce (x - shift) and
            # (x - shift)^2 in one fused read of the full activation, where
            # shift is a per-channel estimate of the batch mean taken from
            # the FIRST batch element only (~H*W samples per channel — mean
            # error O(std/sqrt(HW)), a cheap serialized pre-reduce over 1/B
            # of the data). Both full reductions are then siblings over the
            # same fusion producer, so XLA emits ONE pass over HBM (the
            # naive two-pass form serializes mean -> var and reads the
            # activation twice; measured ~13ms/step extra on ResNet-50 @
            # 256). Shifting keeps E[xc^2] - E[xc]^2 well-conditioned (xc
            # is near zero-mean even when |mean| >> std, where the raw
            # E[x^2] - mu^2 form cancels catastrophically — and unlike a
            # running-mean shift, a data-derived shift is valid on the very
            # first step, when the running mean is still 0).
            # _bn_norm's custom VJP returns zero cotangents for the stats,
            # so autodiff keeps no residual of these reductions.
            # stats_shift="running" uses the running mean instead of a
            # data-derived shift: exact-arithmetic-identical (mean =
            # shift + mean(x - shift) for ANY shift), and because it is
            # constant w.r.t. the batch the reductions fuse into the
            # producing conv's epilogue instead of re-reading x. The
            # conditioning guarantee is weaker only while the running mean
            # is far from the batch mean (i.e. the first few steps, where
            # activations are near zero-mean anyway).
            if self.stats_shift == "running":
                shift = lax.stop_gradient(state["mean"])
            else:
                shift = lax.stop_gradient(
                    jnp.mean(x[:1].astype(jnp.float32), axis=reduce_axes)
                )
            if self.stats_impl == "dot":
                # Reduce via a dot against ones: XLA's reduce of a large
                # NHWC activation runs well below HBM bandwidth on some
                # TPU runtimes, while a (1, N) x (N, C) matmul streams the
                # operand at full speed through the MXU.
                n = x.size // x.shape[-1]
                x2 = x.reshape(n, x.shape[-1])
                ones = jnp.ones((1, n), jnp.float32)
                xc = x2.astype(jnp.float32) - shift
                m1 = lax.stop_gradient(jnp.dot(ones, xc)[0] / n)
                m2 = lax.stop_gradient(
                    jnp.dot(ones, jnp.square(xc))[0] / n
                )
            else:
                xc = x.astype(jnp.float32) - shift
                m1 = lax.stop_gradient(jnp.mean(xc, axis=reduce_axes))
                m2 = lax.stop_gradient(
                    jnp.mean(jnp.square(xc), axis=reduce_axes)
                )
            mean = shift + m1
            var = jnp.maximum(m2 - jnp.square(m1), 0.0)
            m = self.momentum
            new_state = {
                "mean": m * state["mean"] + (1 - m) * mean,
                "var": m * state["var"] + (1 - m) * var,
            }
            y = _bn_norm(x, mean, var, params["scale"], params["bias"],
                         self.epsilon)
            return y, new_state
        mean, var = state["mean"], state["var"]
        inv = lax.rsqrt(var + self.epsilon) * params["scale"]
        y = (x - mean.astype(x.dtype)) * inv.astype(x.dtype) + params["bias"].astype(x.dtype)
        return y, {}


class LayerNorm(Layer):
    def __init__(self, epsilon: float = 1e-6, name=None):
        super().__init__(name)
        self.epsilon = float(epsilon)

    def init(self, key, input_shape: Shape):
        d = input_shape[-1]
        params = {"scale": jnp.ones((d,), jnp.float32), "bias": jnp.zeros((d,), jnp.float32)}
        return params, {}, tuple(input_shape)

    def apply(self, params, state, x, *, train=False, rng=None):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * lax.rsqrt(var + self.epsilon)
        y = y * params["scale"] + params["bias"]
        return y.astype(x.dtype), {}


class Embedding(Layer):
    def __init__(self, vocab_size: int, dim: int, dtype=None, name=None):
        super().__init__(name)
        self.vocab_size = int(vocab_size)
        self.dim = int(dim)
        self.dtype = dtype

    def init(self, key, input_shape: Shape):
        table = initializers.normal(0.02)(key, (self.vocab_size, self.dim), jnp.float32)
        return {"table": table}, {}, tuple(input_shape) + (self.dim,)

    def apply(self, params, state, x, *, train=False, rng=None):
        table = params["table"]
        dt = resolve_dtype(self.dtype)
        if is_quantized_leaf(table):
            # Gather int8 rows FIRST, dequantize only the gathered rows
            # (per-channel scales broadcast over the trailing dim) — the
            # full f32 table never materializes on the decode path.
            rows = jnp.take(table["q"], x, axis=0).astype(jnp.float32)
            rows = rows * table["scale"]
            return rows if dt is None else rows.astype(dt), {}
        if dt is not None:
            table = table.astype(dt)
        return jnp.take(table, x, axis=0), {}
