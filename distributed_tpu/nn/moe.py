"""Mixture-of-Experts layer with expert parallelism.

Not in the reference (dense CNN only — SURVEY.md §2c "Expert parallelism
(EP / MoE): NO"); built so the 'expert' mesh axis (parallel.mesh.AXES) is a
working capability, not a reserved name.

TPU-first design choices:
- **Dense dispatch** (Shazeer-style einsum with one-hot combine tensors):
  no sorting, no dynamic shapes, no scatter — everything is static-shape
  einsums that tile onto the MXU and jit into one XLA program.
- **Capacity factor**: each expert processes a fixed ``capacity`` tokens per
  batch; overflow tokens are dropped from that expert (their combine weight
  is zero, so they pass through the residual unchanged in a transformer
  block). Static capacity is what makes the computation shape-static.
- **Expert parallelism**: expert weight stacks are (E, din, dout); the
  sharding hint 'expert' splits dim 0 across the 'expert' mesh axis, and
  GSPMD turns the dispatch/combine einsums into all-to-alls over ICI.
- Router computes in float32; a load-balancing auxiliary loss (Switch
  Transformer's fraction*probability form) is returned in state under
  ``"aux_loss"`` so training can add it to the objective.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from . import core, initializers
from .core import Layer, Shape
from ..quant import maybe_dequantize
from ..precision import resolve_dtype


class MoE(Layer):
    """Token-choice top-k MoE over (B, T, D) or (B, D) inputs.

    Output shape == input shape (experts are D -> hidden -> D MLPs).
    """

    def __init__(
        self,
        num_experts: int,
        hidden_dim: int,
        *,
        top_k: int = 2,
        capacity_factor: float = 1.25,
        group_size: int = 1024,
        activation: str = "gelu",
        aux_loss_weight: float = 0.01,
        dtype=None,
        name: Optional[str] = None,
    ):
        """``group_size``: tokens are routed within fixed-size groups (the
        Mesh-TF/Switch formulation) so the dispatch/combine one-hots are
        O(tokens * group * k), linear in batch tokens — global routing would
        be quadratic. Capacity is per group."""
        super().__init__(name)
        if top_k < 1 or top_k > num_experts:
            raise ValueError(
                f"top_k must be in [1, num_experts={num_experts}], got {top_k}"
            )
        self.num_experts = int(num_experts)
        self.hidden_dim = int(hidden_dim)
        self.top_k = int(top_k)
        self.capacity_factor = float(capacity_factor)
        self.group_size = int(group_size)
        self.activation = activation
        self.aux_loss_weight = float(aux_loss_weight)
        self.dtype = dtype

    def default_name(self) -> str:
        return "moe"  # the camel-case splitter would produce "mo_e"

    def init(self, key, input_shape: Shape):
        d = input_shape[-1]
        e, h = self.num_experts, self.hidden_dim
        k_router, k_in, k_out = jax.random.split(key, 3)
        glorot = initializers.get("glorot_uniform")
        params = {
            "router": glorot(k_router, (d, e), jnp.float32),
            "w_in": glorot(k_in, (e, d, h), jnp.float32),
            "b_in": jnp.zeros((e, h), jnp.float32),
            "w_out": glorot(k_out, (e, h, d), jnp.float32),
            "b_out": jnp.zeros((e, d), jnp.float32),
        }
        # aux_loss lives in state from init so the state STRUCTURE never
        # changes between a fresh model and one that has stepped (checkpoint
        # restore compares structures).
        return params, {"aux_loss": jnp.float32(0.0)}, tuple(input_shape)

    def sharding_hints(self):
        # dim 0 (the expert stack) splits across the 'expert' mesh axis.
        return {
            "w_in": "expert",
            "b_in": "expert",
            "w_out": "expert",
            "b_out": "expert",
        }

    def _group_size(self, n_tokens: int) -> int:
        # Groups are always full-width: awkward token counts (primes, odd
        # batch*seq products) are PADDED up to a group boundary rather than
        # shrinking the group — a tiny group would collapse capacity to ~1
        # and silently drop most routing choices.
        return min(self.group_size, n_tokens)

    def _capacity(self, group: int) -> int:
        c = int(self.capacity_factor * self.top_k * group
                / self.num_experts) or 1
        return min(c, group)

    def _route(self, tokens_f32, router):
        """Shared routing math for apply() and decode(): softmax router
        probs -> top-k choice -> renormalized gates. tokens_f32 is
        (..., d) float32; returns (probs, gate_vals, gate_idx)."""
        logits = jnp.einsum(
            "...d,de->...e", tokens_f32, router,
            preferred_element_type=jnp.float32,
        )
        probs = jax.nn.softmax(logits, axis=-1)
        gate_vals, gate_idx = jax.lax.top_k(probs, self.top_k)
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
        )
        return probs, gate_vals, gate_idx

    def apply(self, params, state, x, *, train=False, rng=None):
        from . import activations

        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None, :]
        b, t, d = x.shape
        n = b * t
        e, k = self.num_experts, self.top_k
        g = self._group_size(n)
        ng = -(-n // g)  # number of routing groups (ceil)
        n_pad = ng * g
        cap = self._capacity(g)
        act = activations.get(self.activation)

        flat = x.reshape(n, d)
        if n_pad != n:
            flat = jnp.concatenate(
                [flat, jnp.zeros((n_pad - n, d), flat.dtype)], axis=0
            )
        tokens = flat.reshape(ng, g, d)
        # (G, g) validity mask; pad tokens are excluded from dispatch (they
        # consume no capacity) and from the aux loss statistics.
        token_valid = (jnp.arange(n_pad) < n).astype(jnp.float32)
        # Evaluation pads its final BATCH too (training/model.py keeps the
        # step shape static): the eval step publishes per-example validity
        # weights, and those rows must not route. For eval's own pads
        # (always appended AFTER real rows, so cumsum dispatch priority
        # already favors the real ones) the effect is on the load-balance
        # aux statistics, which were biased exactly on the models whose
        # eval loss is watched (VERDICT r4 weak #6); for zero-weighted
        # rows in arbitrary positions the exclusion also keeps them from
        # consuming expert capacity ahead of later valid rows.
        sample_w = core.current_sample_weights()
        if sample_w is not None:
            # Binarize: the dispatch position math (cumsum over one-hot
            # choices) requires 0/1 validity — a fractional weight would
            # make slot positions non-integral and alias buffer slots.
            per_tok = jnp.broadcast_to(
                (sample_w > 0).astype(jnp.float32)[:, None], (b, t)
            ).reshape(n)
            if n_pad != n:
                per_tok = jnp.concatenate(
                    [per_tok, jnp.zeros((n_pad - n,), jnp.float32)]
                )
            token_valid = token_valid * per_tok
        valid = token_valid.reshape(ng, g)
        n_valid = jnp.maximum(jnp.sum(valid), 1.0)
        # Router probs + top-k choice + renormalized gates (shared with
        # decode()). probs: (G, g, e); gate_vals/gate_idx: (G, g, k).
        probs, gate_vals, gate_idx = self._route(
            tokens.astype(jnp.float32), maybe_dequantize(params["router"])
        )

        # Position of each (token, choice) in its expert's per-group buffer;
        # tokens beyond capacity are dropped (combine weight zeroed).
        choice_onehot = (
            jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)
            * valid[:, :, None, None]
        )  # (G,g,k,e)
        pos = (
            jnp.cumsum(choice_onehot.reshape(ng, g * k, e), axis=1) - 1.0
        ).reshape(ng, g, k, e)
        within = pos < cap
        dispatch_w = choice_onehot * within  # (G, g, k, e)
        pos_onehot = jax.nn.one_hot(
            (pos * choice_onehot).sum(-1).astype(jnp.int32), cap,
            dtype=jnp.float32,
        )  # (G, g, k, cap)
        # dispatch[G, n, e, c] = 1 iff group-G token n sits in slot c of
        # expert e's buffer for that group.
        dispatch = jnp.einsum("Gnke,Gnkc->Gnec", dispatch_w, pos_onehot)
        combine = jnp.einsum("Gnk,Gnke,Gnkc->Gnec", gate_vals, dispatch_w,
                             pos_onehot)

        # Expert buffers: (G, e, cap, d) -> MLP -> back. All MXU einsums.
        compute_dtype = resolve_dtype(self.dtype) or tokens.dtype
        buf = jnp.einsum(
            "Gnec,Gnd->Gecd", dispatch.astype(compute_dtype),
            tokens.astype(compute_dtype),
        )
        hid = act(
            jnp.einsum("Gecd,edh->Gech", buf,
                       maybe_dequantize(params["w_in"]).astype(compute_dtype))
            + params["b_in"][None, :, None].astype(compute_dtype)
        )
        out_buf = (
            jnp.einsum("Gech,ehd->Gecd", hid,
                       maybe_dequantize(params["w_out"]).astype(compute_dtype))
            + params["b_out"][None, :, None].astype(compute_dtype)
        )
        out = jnp.einsum(
            "Gnec,Gecd->Gnd", combine.astype(compute_dtype), out_buf
        )

        # Switch-style load-balance loss: E * sum_e fraction_e * prob_e,
        # averaged over *valid* tokens only (batch-pad rows excluded when
        # the eval step publishes sample weights).
        frac = jnp.sum(choice_onehot[:, :, 0], axis=(0, 1)) / n_valid
        mean_prob = (
            jnp.sum(probs * valid[:, :, None], axis=(0, 1)) / n_valid
        )
        aux = self.aux_loss_weight * e * jnp.sum(frac * mean_prob)

        out = out.reshape(n_pad, d)[:n].reshape(b, t, d).astype(x.dtype)
        if squeeze:
            out = out[:, 0]
        return out, {"aux_loss": aux}

    # ------------------------------------------------- incremental decode --
    # apply() mixes positions through group capacity (tokens compete for
    # expert slots), so the inherited default decode would be silently
    # wrong. This override routes each token droplessly: capacity never
    # binds for one token at inference, which matches apply() exactly
    # whenever apply() dropped nothing, and is the standard serving
    # behavior when it did.
    decode_safe = True

    def decode(self, params, state, cache, x, *, pos):
        from . import activations

        act = activations.get(self.activation)
        b, t, d = x.shape  # t == 1
        e, k = self.num_experts, self.top_k
        flat = x.reshape(b * t, d)
        _, gate_vals, gate_idx = self._route(
            flat.astype(jnp.float32), maybe_dequantize(params["router"])
        )  # (N, k)
        # Per-expert combine weight: sum of the gates that chose it.
        weight = jnp.einsum(
            "nk,nke->ne", gate_vals,
            jax.nn.one_hot(gate_idx, e, dtype=jnp.float32),
        )  # (N, e)
        compute_dtype = resolve_dtype(self.dtype) or x.dtype
        h = act(
            jnp.einsum("nd,edh->neh", flat.astype(compute_dtype),
                       maybe_dequantize(params["w_in"]).astype(compute_dtype))
            + params["b_in"][None].astype(compute_dtype)
        )
        out_e = (
            jnp.einsum("neh,ehd->ned", h,
                       maybe_dequantize(params["w_out"]).astype(compute_dtype))
            + params["b_out"][None].astype(compute_dtype)
        )
        out = jnp.einsum(
            "ne,ned->nd", weight.astype(compute_dtype), out_e
        )
        return out.reshape(b, t, d).astype(x.dtype), cache
