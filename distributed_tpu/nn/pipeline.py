"""Pipeline parallelism: GPipe-microbatched stage execution over 'pipe'.

Absent from the reference (single model replica per worker, SURVEY.md §2c
"Pipeline parallelism: NO"); built because the mesh promises `pipe` as a
composable axis (parallel.mesh.AXES) and the strategy hint machinery
anticipates a stacked-blocks layer.

TPU-first design:

- **Stacked stage parameters**: ``PipelinedBlocks`` holds S structurally
  identical blocks as ONE pytree whose leaves have a leading (S, ...) stage
  dimension — a single NamedSharding (dim 0 over 'pipe') places every stage's
  weights on its device; there is no per-stage program or weight exchange.
- **Schedule as data flow, not control flow**: the GPipe schedule is a
  ``lax.scan`` over M + n - 1 ticks inside one ``shard_map``. Each tick every
  device runs its resident stage(s) on the activation it holds and the
  activations hop one rank along the 'pipe' axis via ``lax.ppermute`` — a
  neighbor ICI transfer on a TPU torus. XLA sees one static program; no
  host-side scheduler exists (contrast GPipe/PipeDream's runtime schedulers).
- **Backward for free**: the schedule is reverse-mode differentiable
  (scan + ppermute + psum all have transposes), so ``jax.grad`` of the jitted
  train step yields the reverse pipeline schedule without any hand-written
  backward pass.
- Bubble fraction is the standard GPipe (n-1)/(M+n-1); raise
  ``num_microbatches`` on the strategy to amortize — or switch to
  ``schedule="interleaved"``: each rank holds ``v`` non-contiguous chunks of
  the stack (Megatron's virtual stages; Narayanan et al., 2021) and the tick
  scan circulates every microbatch ``v`` laps around the full ring, cutting
  the bubble to (n-1)/(vM+n-1) — the same n-1 idle ticks amortized over v
  laps of useful ones, each tick now 1/v of a GPipe stage's compute.

Single-device (no 'pipe' axis in the ambient strategy) the same layer runs
its blocks as a weight-stacked ``lax.scan`` — one trace of the block instead
of S inlined copies, which keeps compile time flat in depth.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec

from .core import Layer, Shape

try:  # modern location (jax>=0.8)
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

import inspect

_sig = inspect.signature(shard_map).parameters
if "check_vma" in _sig:
    _CHECK_KWARGS = {"check_vma": False}
elif "check_rep" in _sig:  # pragma: no cover — older jax
    _CHECK_KWARGS = {"check_rep": False}
else:  # pragma: no cover
    _CHECK_KWARGS = {}
del _sig


# Trace-time record of the most recent pipelined apply on this thread:
# which schedule ran, over how many stages/microbatches/ticks, and the
# resulting bubble fraction. Model.fit's telemetry exit reads it
# (training/model.py) the same way it reads scan.last_overlap_trace —
# best-effort by design, like the threadlocal strategy scope it mirrors.
_pipeline_trace = threading.local()


def last_pipeline_trace() -> Optional[dict]:
    """``{"schedule", "interleave", "num_stages", "num_microbatches",
    "ticks", "bubble_fraction"}`` from the most recent pipelined apply
    traced on this thread, or None before any (including the sequential
    single-device path, which has no schedule to report)."""
    return getattr(_pipeline_trace, "record", None)


def _live_pipe_mesh(strategy):
    """(mesh, pipe_axis) when the ambient strategy carries a >1-rank pipe
    axis, else (None, None) — the single dispatch used by BOTH the training
    schedule and the ring decode, so they cannot diverge."""
    pipe_axis = getattr(strategy, "pipe_axis", None)
    mesh = getattr(strategy, "mesh", None)
    if (
        pipe_axis is None
        or mesh is None
        or pipe_axis not in mesh.axis_names
        or int(mesh.shape[pipe_axis]) == 1
    ):
        return None, None
    return mesh, pipe_axis


def _stage_spec(pipe_axis):
    return lambda a: PartitionSpec(pipe_axis, *((None,) * (a.ndim - 1)))


class PipelinedBlocks(Layer):
    """S structurally identical shape-preserving blocks, stacked for
    pipeline parallelism.

    ``block_fn()`` must return a fresh ``Layer`` with the same structure each
    call (e.g. ``lambda: nn.Sequential(transformer_block(...))``). Blocks
    must be shape-preserving (input shape == output shape) and stateless
    (BatchNorm-style running stats can't ride a microbatch schedule).

    Under a strategy with a 'pipe' mesh axis (``DataPipelineParallel``) the
    stacked params shard one-stage-per-rank and apply() runs the GPipe
    schedule; under any other strategy the same params run as a sequential
    ``lax.scan`` — identical numerics, which is what the parity tests assert.
    """

    # Incremental decode IS supported (same stacked-cache recipe as
    # ScannedBlocks): caches are stacked with a leading (S, ...) stage dim
    # like the params. Off a pipe mesh, decode() scans the template
    # block's cached one-token step over the full stack. On a LIVE 'pipe'
    # mesh it runs the memory-sharded ring decode instead: each rank keeps
    # only its (S/n)-block param/cache slices resident and the activation
    # hops rank-to-rank via ppermute (generation is inherently sequential
    # through the stack, so every rank executing each hop costs the same
    # total block-compute as the gather-everything form — but no rank ever
    # materializes the full weight stack, which is the reason PP exists).
    # decode_safe stays False so a template whose own decode would silently
    # be wrong still fails loudly inside the scan body.
    decode_safe = False

    def __init__(
        self,
        block_fn: Callable[[], Layer],
        num_blocks: int,
        *,
        schedule: str = "gpipe",
        interleave: int = 1,
        name: Optional[str] = None,
    ):
        """``schedule``: 'gpipe' (default) runs each rank's contiguous
        stage once per microbatch; 'interleaved' splits each rank's stage
        into ``interleave`` virtual chunks and circulates every microbatch
        that many laps around the ring (module docstring) — same numerics,
        smaller bubble, needs ``num_microbatches >= stages`` and
        ``num_blocks % (stages * interleave) == 0``."""
        super().__init__(name)
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if schedule not in ("gpipe", "interleaved"):
            raise ValueError(
                f"schedule must be 'gpipe' or 'interleaved', got {schedule!r}"
            )
        v = int(interleave)
        if schedule == "gpipe" and v != 1:
            raise ValueError(
                "interleave only applies to schedule='interleaved' "
                f"(got interleave={v} with schedule='gpipe')"
            )
        if schedule == "interleaved" and v < 2:
            raise ValueError(
                "schedule='interleaved' needs interleave >= 2 "
                f"(interleave=1 IS the GPipe schedule), got {v}"
            )
        self.num_blocks = int(num_blocks)
        self.schedule = schedule
        self.interleave = v
        self.block_fn = block_fn
        self.block = block_fn()  # template: defines structure + names

    def default_name(self) -> str:
        return "pipelined_blocks"

    @property
    def needs_rng(self) -> bool:
        return getattr(self.block, "needs_rng", False)

    def init(self, key, input_shape: Shape):
        from .scan import init_stacked_blocks

        shape = tuple(input_shape)
        params, _ = init_stacked_blocks(
            self.block_fn, self.block, self.num_blocks, key, shape,
            require_stateless=True, container="PipelinedBlocks",
        )
        return {"blocks": params}, {}, shape

    def sharding_hints(self):
        # Container-level role string: the whole stacked subtree shards its
        # leading (stage) dim over the 'pipe' mesh axis.
        return {"blocks": "pipe"}

    def dtype_hints(self):
        # Same pass-through as ScannedBlocks: stacked params mirror the
        # template block's tree one level down.
        h = self.block.dtype_hints()
        return {"blocks": h} if h is not None and h != {} else {}

    # ------------------------------------------------------------------ apply
    def _stage_rngs(self, rng):
        if rng is None:
            return None
        return jax.random.split(rng, self.num_blocks)

    def _scan_blocks(self, stacked, x, *, train, rngs):
        """Run a stack of block params over x: scan over the stage dim.
        Shared by the sequential path (whole stack) and each pipeline rank's
        stage (its local slice); the scan body itself lives in
        scan.scan_stacked so ScannedBlocks and this layer can't diverge."""
        from .scan import scan_stacked

        # Blocks are validated stateless at init: the state stack is empty.
        out, _ = scan_stacked(self.block, stacked, {}, x,
                              train=train, rngs=rngs)
        return out

    def apply(self, params, state, x, *, train=False, rng=None):
        from ..obs import spans as obs_spans
        from ..parallel.strategy import current_strategy

        stacked = params["blocks"]
        rngs = self._stage_rngs(rng)
        strategy = current_strategy()
        mesh, pipe_axis = _live_pipe_mesh(strategy)
        if mesh is None:
            return self._scan_blocks(stacked, x, train=train, rngs=rngs), {}

        n = int(mesh.shape[pipe_axis])
        v = self.interleave
        if self.num_blocks % (n * v):
            raise ValueError(
                f"{self.num_blocks} blocks not divisible by "
                f"{pipe_axis}={n} stages"
                + (f" x interleave={v} virtual chunks" if v > 1 else "")
            )
        # Batch rows may shard over several axes (CompositeParallel rows
        # over ('data','fsdp')); honor them all so the schedule's shard_map
        # doesn't silently all-gather the extra folds and recompute the
        # pipeline per-slice.
        row_axes = tuple(
            a for a in getattr(strategy, "row_axes", ())
            if a in mesh.axis_names
        ) or (getattr(strategy, "axis", "data"),)
        n_data = 1
        for a in row_axes:
            n_data *= int(mesh.shape.get(a, 1))
        m = int(getattr(strategy, "num_microbatches", n))
        b_global = x.shape[0]
        if b_global % (n_data * m):
            raise ValueError(
                f"batch {b_global} not divisible by data shards ({n_data}) "
                f"x microbatches ({m})"
            )
        if v > 1 and m < n:
            raise ValueError(
                f"interleaved schedule needs num_microbatches >= stages "
                f"(got M={m} < n={n}): a microbatch re-enters rank 0 for "
                f"its next lap M-n ticks after it left, which must not be "
                f"in the past"
            )
        b_local = b_global // n_data
        mb = b_local // m
        ticks = v * m + n - 1
        _pipeline_trace.record = {
            "schedule": self.schedule,
            "interleave": v,
            "num_stages": n,
            "num_microbatches": m,
            "ticks": ticks,
            "bubble_fraction": round((n - 1) / ticks, 6),
        }
        feat_none = (None,) * (x.ndim - 1)
        rows = row_axes if len(row_axes) > 1 else row_axes[0]
        x_spec = PartitionSpec(rows, *feat_none)
        if v > 1:
            # Static reindex for the virtual-stage layout: rank r's
            # contiguous pipe shard, read as v sub-chunks of cs blocks,
            # must hold original chunks j*n + r for laps j = 0..v-1 (each
            # lap advances the microbatch one chunk on every rank, and a
            # full ring pass advances it n chunks). The stacked leading
            # dim stays one pytree; only the block order changes, and the
            # perm is a compile-time constant, so XLA lays the shuffle
            # into the weights' placement rather than a per-tick gather.
            cs = self.num_blocks // (n * v)
            perm = np.concatenate([
                np.arange((j * n + r) * cs, (j * n + r + 1) * cs)
                for r in range(n) for j in range(v)
            ])
            stacked = jax.tree_util.tree_map(lambda l: l[perm], stacked)
            if rngs is not None:
                rngs = rngs[perm]
        p_specs = jax.tree_util.tree_map(_stage_spec(pipe_axis), stacked)
        in_specs = [p_specs, x_spec]
        args = [stacked, x]
        if rngs is not None:
            in_specs.append(PartitionSpec(pipe_axis))
            args.append(rngs)

        scan_blocks = self._scan_blocks

        def gpipe_fn(p_local, x_local, *maybe_rngs):
            r_local = maybe_rngs[0] if maybe_rngs else None
            rank = lax.axis_index(pipe_axis)
            mbs = x_local.reshape((m, mb) + x_local.shape[1:])
            shift = [(j, j + 1) for j in range(n - 1)]

            def tick(recv, t):
                # Rank 0 injects microbatch t (clamped past the end: those
                # ticks' outputs fall in the bubble and are discarded);
                # other ranks consume what arrived from rank-1 last tick.
                inj = lax.dynamic_index_in_dim(
                    mbs, jnp.minimum(t, m - 1), axis=0, keepdims=False
                )
                h = jnp.where(rank == 0, inj, recv)
                # Per-tick rng fold: each microbatch must draw fresh
                # dropout masks, not reuse the stage key M times.
                rngs_t = (
                    None if r_local is None
                    else jax.vmap(jax.random.fold_in, (0, None))(r_local, t)
                )
                y = scan_blocks(p_local, h, train=train, rngs=rngs_t)
                return lax.ppermute(y, pipe_axis, shift), y

            zeros = jnp.zeros((mb,) + x_local.shape[1:], x_local.dtype)
            _, ys = lax.scan(tick, zeros, jnp.arange(m + n - 1))
            # Last rank's ticks n-1 .. m+n-2 hold microbatch outputs 0..m-1.
            outs = ys[n - 1:].reshape((b_local,) + x_local.shape[1:])
            # Publish to every pipe rank (loss/head run replicated on pipe).
            return lax.psum(
                jnp.where(rank == n - 1, outs, jnp.zeros_like(outs)),
                pipe_axis,
            )

        def interleaved_fn(p_local, x_local, *maybe_rngs):
            # v laps over the FULL ring (rank n-1 wraps to rank 0). At
            # tick t rank r runs lap j = (t-r)//M on microbatch (t-r)%M
            # using its j-th resident chunk; a microbatch leaves rank n-1
            # at lap j and re-enters rank 0 for lap j+1 exactly M-n ticks
            # later, so rank 0 banks every wrap-around arrival in an
            # (M, mb, ...) buffer keyed by microbatch index (M >= n makes
            # the write land no later than the tick that reads it; ticks
            # outside a rank's active window compute on garbage that the
            # bubble discards, same as GPipe's clamped injections).
            r_local = maybe_rngs[0] if maybe_rngs else None
            rank = lax.axis_index(pipe_axis)
            mbs = x_local.reshape((m, mb) + x_local.shape[1:])
            ring = [(j, (j + 1) % n) for j in range(n)]

            def tick(carry, t):
                recv, buf = carry
                # Incoming recv at tick t is rank n-1's tick t-1 output:
                # microbatch (t-n) mod M, banked for its next lap.
                buf = lax.dynamic_update_index_in_dim(
                    buf, recv, jnp.mod(t - n, m), axis=0
                )
                u = t - rank
                lap = jnp.clip(u // m, 0, v - 1)
                mbi = jnp.mod(u, m)
                inj = lax.dynamic_index_in_dim(
                    mbs, mbi, axis=0, keepdims=False
                )
                re_entry = lax.dynamic_index_in_dim(
                    buf, mbi, axis=0, keepdims=False
                )
                h = jnp.where(
                    rank == 0, jnp.where(lap == 0, inj, re_entry), recv
                )
                chunk = jax.tree_util.tree_map(
                    lambda l: lax.dynamic_slice_in_dim(
                        l, lap * cs, cs, axis=0
                    ),
                    p_local,
                )
                rngs_t = (
                    None if r_local is None
                    else jax.vmap(jax.random.fold_in, (0, None))(
                        lax.dynamic_slice_in_dim(
                            r_local, lap * cs, cs, axis=0
                        ),
                        t,
                    )
                )
                y = scan_blocks(chunk, h, train=train, rngs=rngs_t)
                return (lax.ppermute(y, pipe_axis, ring), buf), y

            zeros = jnp.zeros((mb,) + x_local.shape[1:], x_local.dtype)
            buf0 = jnp.zeros((m, mb) + x_local.shape[1:], x_local.dtype)
            (_, _), ys = lax.scan(tick, (zeros, buf0), jnp.arange(ticks))
            # Rank n-1's final-lap ticks (v-1)M+n-1 .. vM+n-2 hold
            # microbatch outputs 0..M-1 in order.
            outs = ys[(v - 1) * m + n - 1:].reshape(
                (b_local,) + x_local.shape[1:]
            )
            return lax.psum(
                jnp.where(rank == n - 1, outs, jnp.zeros_like(outs)),
                pipe_axis,
            )

        local_fn = interleaved_fn if v > 1 else gpipe_fn
        with obs_spans.span("pipeline_schedule"):
            out = shard_map(
                local_fn,
                mesh=mesh,
                in_specs=tuple(in_specs),
                out_specs=x_spec,
                **_CHECK_KWARGS,
            )(*args)
        return out, {}

    # ---------------------------------------------------- incremental decode
    def init_cache(self, params, batch, max_len, dtype):
        from .scan import stacked_init_cache

        return stacked_init_cache(
            self.block, self.num_blocks, params["blocks"], batch, max_len,
            dtype,
        )

    # Paged (block KV) serving works on the sequential single-device path:
    # the pools stack with a leading (S, ...) stage dim (scan.py's
    # stacked-pool layout) and each hook scans the template block's paged
    # step over the stack. On a LIVE pipe mesh it stays a loud raise: the
    # serving engine's block allocator, prefix store, and copy-on-write
    # are host-side state over ONE pool address space, and a pipe-sharded
    # stack would give every rank a different pool — serve off the pipe
    # mesh (where PP's memory argument doesn't apply: decode holds one
    # token of activations, not a training batch).
    def _no_paged_on_pipe_mesh(self):
        from ..parallel.strategy import current_strategy

        mesh, pipe_axis = _live_pipe_mesh(current_strategy())
        if mesh is not None:
            raise NotImplementedError(
                "PipelinedBlocks paged serving is single-device only: the "
                "paged pool's allocator/prefix/copy-on-write state is "
                "host-side and assumes one pool address space, which a "
                f"{pipe_axis}-sharded stack would split across ranks — "
                "serve this model OFF the pipe mesh (the sequential path "
                "supports the full paged engine)"
            )

    def init_paged_cache(self, params, num_blocks, block_size, dtype):
        from .scan import stacked_init_paged_cache

        self._no_paged_on_pipe_mesh()
        return stacked_init_paged_cache(
            self.block, self.num_blocks, params["blocks"], num_blocks,
            block_size, dtype,
        )

    def paged_decode(self, params, state, cache, x, *, block_tables,
                     positions):
        from .scan import stacked_paged_decode

        self._no_paged_on_pipe_mesh()
        return stacked_paged_decode(
            self.block, params["blocks"], {}, cache, x,
            block_tables=block_tables, positions=positions,
        )

    def paged_verify(self, params, state, cache, x, *, block_tables,
                     positions):
        from .scan import stacked_paged_verify

        self._no_paged_on_pipe_mesh()
        return stacked_paged_verify(
            self.block, params["blocks"], {}, cache, x,
            block_tables=block_tables, positions=positions,
        )

    def paged_prefill(self, params, state, cache, x, *, block_table, start):
        from .scan import stacked_paged_prefill

        self._no_paged_on_pipe_mesh()
        return stacked_paged_prefill(
            self.block, params["blocks"], {}, cache, x,
            block_table=block_table, start=start,
        )

    def decode(self, params, state, cache, x, *, pos):
        from ..parallel.strategy import current_strategy
        from .scan import stacked_decode

        mesh, pipe_axis = _live_pipe_mesh(current_strategy())
        stacked = params["blocks"]
        if mesh is not None:
            # Loud failures for every config the ring schedule can't run:
            # silently taking the gather-everything path would materialize
            # the full stack on every device — the opposite of what a pipe
            # mesh promises.
            if self.num_blocks % int(mesh.shape[pipe_axis]):
                raise ValueError(
                    f"{self.num_blocks} blocks not divisible by "
                    f"{pipe_axis}={int(mesh.shape[pipe_axis])} stages"
                )
            if not jax.tree_util.tree_leaves(cache):
                raise ValueError(
                    "PipelinedBlocks.decode on a live pipe mesh needs a "
                    "per-block cache (the template block's init_cache "
                    "returned nothing) — a cacheless stack would scan the "
                    "pipe-sharded params and all-gather the full stack on "
                    "every rank; decode off the pipe mesh instead"
                )
        if mesh is None:
            return stacked_decode(self.block, stacked, {}, cache, x, pos=pos)

        # Memory-sharded ring decode (class comment): every rank holds its
        # local stage slice; all ranks start from the replicated token
        # activation, and after hop i rank i holds the TRUE activation —
        # so rank r's cache write is kept only at iteration r, and after n
        # hops the final output has wrapped around to rank 0.
        n = int(mesh.shape[pipe_axis])
        block = self.block

        p_specs = jax.tree_util.tree_map(_stage_spec(pipe_axis), stacked)
        c_specs = jax.tree_util.tree_map(
            _stage_spec(pipe_axis), cache["blocks"]
        )
        x_spec = PartitionSpec(*((None,) * x.ndim))

        def local_fn(p_local, c_local, h, pos):
            my = lax.axis_index(pipe_axis)
            perm = [(j, (j + 1) % n) for j in range(n)]

            def hop(carry, i):
                h, c = carry
                y, new_c = stacked_decode(
                    block, p_local, {}, {"blocks": c}, h, pos=pos
                )
                new_c = new_c["blocks"]
                keep = i == my
                c = jax.tree_util.tree_map(
                    lambda nl, ol: jnp.where(keep, nl, ol), new_c, c
                )
                return (lax.ppermute(y, pipe_axis, perm), c), None

            (h, c_local), _ = lax.scan(hop, (h, c_local), jnp.arange(n))
            out = lax.psum(
                jnp.where(my == 0, h, jnp.zeros_like(h)), pipe_axis
            )
            return out, c_local

        out, new_blocks = shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(p_specs, c_specs, x_spec, PartitionSpec()),
            out_specs=(x_spec, c_specs),
            **_CHECK_KWARGS,
        )(stacked, cache["blocks"], x, jnp.asarray(pos))
        return out, {"blocks": new_blocks}
