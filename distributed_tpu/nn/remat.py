"""Gradient checkpointing (rematerialization) as a transparent Layer wrapper.

``Remat(layer)`` behaves exactly like ``layer`` but wraps its forward in
``jax.checkpoint``: the backward pass recomputes the wrapped activations
instead of keeping them live in HBM — the standard TPU trade of MXU FLOPs
(cheap) for HBM residency (the bottleneck). With per-block remat a
transformer's activation memory drops from O(layers) to O(1) blocks plus
the recompute; this is what makes long-context/bigger-batch configs fit.

Transparency contract: the wrapper adopts the inner layer's name, params,
state, sharding hints and decode behavior, so toggling remat on an existing
model changes neither checkpoints nor TP sharding — only the XLA schedule.

The reference has nothing comparable (its model is a 347k-param CNN,
/root/reference/README.md:292-298); this is scale-out infrastructure for
the model families the framework adds (SURVEY.md §7 build order step 8).
"""

from __future__ import annotations

from typing import Optional

import jax

from .core import Layer


class Remat(Layer):
    """Wrap a layer so its forward rematerializes during backward.

    ``policy``: optional ``jax.checkpoint_policies`` entry (e.g.
    ``jax.checkpoint_policies.dots_with_no_batch_dims_saveable`` to keep
    matmul outputs and recompute only elementwise chains). Default saves
    nothing (full recompute of the wrapped block).
    """

    def __init__(self, inner: Layer, *, policy=None, name: Optional[str] = None):
        # No super().__init__: name and _name_explicit are properties
        # mirroring the inner layer, so an explicitly-named inner layer
        # keeps its name (and its checkpoint path) through the wrapper.
        self.inner = inner
        self.policy = policy
        if name is not None:
            inner.name = name
            inner._name_explicit = True

    # -- transparency: look exactly like the inner layer --------------------
    @property
    def name(self):
        return self.inner.name

    @name.setter
    def name(self, value):
        self.inner.name = value

    @property
    def _name_explicit(self):
        return self.inner._name_explicit

    def default_name(self) -> str:
        return self.inner.default_name()

    @property
    def needs_rng(self) -> bool:
        return getattr(self.inner, "needs_rng", False)

    @property
    def decode_safe(self) -> bool:
        return self.inner.decode_safe

    def init(self, key, input_shape):
        return self.inner.init(key, input_shape)

    def sharding_hints(self):
        return self.inner.sharding_hints()

    def dtype_hints(self):
        return self.inner.dtype_hints()

    def param_spec(self, input_shape):
        return self.inner.param_spec(input_shape)

    def init_cache(self, params, batch, max_len, dtype):
        return self.inner.init_cache(params, batch, max_len, dtype)

    def decode(self, params, state, cache, x, *, pos):
        # No remat at decode: one-token steps have nothing worth dropping.
        return self.inner.decode(params, state, cache, x, pos=pos)

    def init_paged_cache(self, params, num_blocks, block_size, dtype):
        return self.inner.init_paged_cache(params, num_blocks, block_size,
                                           dtype)

    def paged_decode(self, params, state, cache, x, *, block_tables,
                     positions):
        return self.inner.paged_decode(
            params, state, cache, x,
            block_tables=block_tables, positions=positions,
        )

    def paged_verify(self, params, state, cache, x, *, block_tables,
                     positions):
        return self.inner.paged_verify(
            params, state, cache, x,
            block_tables=block_tables, positions=positions,
        )

    def paged_prefill(self, params, state, cache, x, *, block_table, start):
        return self.inner.paged_prefill(
            params, state, cache, x, block_table=block_table, start=start,
        )

    # -- the actual behavior ------------------------------------------------
    def apply(self, params, state, x, *, train=False, rng=None):
        inner = self.inner

        def fwd(p, s, xx, r):
            return inner.apply(p, s, xx, train=train, rng=r)

        ckpt = jax.checkpoint(
            fwd, policy=self.policy, static_argnums=()
        )
        return ckpt(params, state, x, rng)

    def __repr__(self):
        return f"Remat({self.inner!r})"
