"""Weight-stacked sequential block execution via ``lax.scan``.

TPU-first rationale: a deep stack of structurally identical blocks
(ResNet stage tails, transformer blocks) unrolled as separate layers
compiles to O(depth) static HLO ops. On TPU the XLA program is traced and
scheduled per static op, so depth inflates compile time and — on runtimes
with per-op dispatch cost — step time; measured on the tunneled v5e, a
ResNet-50 train step spends more time on per-op overhead (~3,500 static
ops) than on convolution FLOPs. Stacking the blocks' parameters with a
leading (S, ...) dim and scanning one block body over them emits the body
ONCE: static op count, compile time, and the optimizer's per-tensor update
ops all become depth-independent. This is the flax ``remat_scan`` /
praxis ``repeat`` idiom, built on this framework's own Layer contract.

Unlike :class:`~distributed_tpu.nn.pipeline.PipelinedBlocks` (its
pipeline-parallel sibling), ScannedBlocks supports *stateful* blocks:
per-block state (BatchNorm running stats) is stacked alongside the params
and threaded through the scan as per-iteration inputs/outputs.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .core import Layer, Shape

# Trace-time record of the most recent ScannedBlocks.apply on this thread:
# whether the gather overlap engaged and over how many layers. Model.fit's
# telemetry exit reads it (training/model.py) to attribute exposed
# communication without a layer-tree traversal protocol — best-effort by
# design, like the threadlocal strategy scope it mirrors.
_overlap_trace = threading.local()


def last_overlap_trace() -> Optional[dict]:
    """``{"layers": int, "active": bool}`` from the most recent scanned
    apply traced on this thread, or None before any."""
    return getattr(_overlap_trace, "record", None)


def init_stacked_blocks(
    block_fn, template, num_blocks, key, input_shape, *,
    require_stateless=False, container="ScannedBlocks",
):
    """Init ``num_blocks`` fresh blocks and stack their params (and state)
    with a leading (S, ...) dim. Shared by ScannedBlocks and
    PipelinedBlocks so the stacked-layout contract stays in one place.

    Returns (stacked_params, stacked_state)."""
    shape = tuple(input_shape)
    keys = jax.random.split(key, num_blocks)
    per_block_p, per_block_s = [], []
    for i in range(num_blocks):
        # Fresh instance per block: container naming is stateful and the
        # template must not accumulate names.
        block = template if i == 0 else block_fn()
        p, s, out = block.init(keys[i], shape)
        if require_stateless and s:
            raise ValueError(
                f"{container} requires stateless blocks (got state keys "
                f"{list(s)}); running stats can't ride a microbatch "
                "schedule"
            )
        if tuple(out) != shape:
            raise ValueError(
                f"{container} blocks must preserve shape: {shape} -> {out}"
            )
        per_block_p.append(p)
        per_block_s.append(s)
    if not jax.tree_util.tree_leaves(per_block_p[0]):
        raise ValueError(
            f"{container} requires parameterized blocks (the template "
            "block has no params); wrap param-free layers directly in "
            "a Sequential instead"
        )
    params = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_block_p
    )
    state = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_block_s
    )
    return params, state


def scan_stacked(block, stacked_p, stacked_s, x, *, train, rngs,
                 overlap_gather=None):
    """Apply a stack of block params (and optional stacked state) to x as
    one ``lax.scan``. Returns (y, stacked_new_state). Shared by
    ScannedBlocks and PipelinedBlocks' sequential path — the 'identical
    numerics' contract both promise lives here.

    ``overlap_gather`` (from ``Strategy.overlap_spec``): when given, the
    scan double-buffers the per-layer parameter gather. Iteration i's
    carry already holds layer i's GATHERED params; the body's first act is
    to issue layer i+1's gather (its xs slice arrives SHARDED — the
    stacked params ride through the scan rolled by -1 so slice i is layer
    i+1), which depends only on the slice, not on layer i's compute — the
    scheduler is free to run the all-gather behind the layer's matmuls
    instead of serializing it in front of them. Only layer 0's warm-up
    gather (issued before the scan) has nothing to hide behind. The final
    iteration's wrap-around gather (layer 0 again, from the roll) is
    dead code XLA drops. Values are identical to the plain body:
    gathering is a layout constraint, not arithmetic."""

    def body(h, per_iter):
        p, s, r = per_iter
        y, new_s = block.apply(p, s, h, train=train, rng=r)
        # Carry dtype must be stable across iterations (a bf16-compute
        # block in an f32 stream behaves like any mixed-precision layer).
        return y.astype(h.dtype), new_s

    if overlap_gather is not None:
        p0 = jax.tree_util.tree_map(lambda l: l[0], stacked_p)
        g0 = overlap_gather(p0)
        rolled = jax.tree_util.tree_map(
            lambda l: jnp.roll(l, -1, axis=0), stacked_p
        )

        def body_overlap(carry, per_iter):
            h, g = carry
            p_next, s, r = per_iter
            g_next = overlap_gather(p_next)
            y, new_s = block.apply(g, s, h, train=train, rng=r)
            return (y.astype(h.dtype), g_next), new_s

        if rngs is None:
            (out, _), new_s = lax.scan(
                lambda c, ps: body_overlap(c, (ps[0], ps[1], None)),
                (x, g0),
                (rolled, stacked_s),
            )
        else:
            (out, _), new_s = lax.scan(
                body_overlap, (x, g0), (rolled, stacked_s, rngs)
            )
        return out, new_s

    if rngs is None:
        return lax.scan(
            lambda h, ps: body(h, (ps[0], ps[1], None)),
            x,
            (stacked_p, stacked_s),
        )
    return lax.scan(body, x, (stacked_p, stacked_s, rngs))


def stacked_init_cache(block, num_blocks, stacked_p, batch, max_len, dtype):
    """Stacked (S, ...) decode caches for a block stack — shared by
    ScannedBlocks and PipelinedBlocks so the cache layout can't diverge.
    Broadcasts the template's cache rather than allocating zeros: a layer
    whose cache initializes non-zero must start every block's slice from
    those values, exactly as the unrolled form would."""
    p0 = jax.tree_util.tree_map(lambda l: l[0], stacked_p)
    c0 = block.init_cache(p0, batch, max_len, dtype)
    if not jax.tree_util.tree_leaves(c0):
        return {}
    return {
        "blocks": jax.tree_util.tree_map(
            lambda l: jnp.broadcast_to(l, (num_blocks,) + l.shape).copy(),
            c0,
        )
    }


def stacked_decode(block, stacked_p, stacked_s, cache, x, *, pos):
    """One-token step through a block stack: scan the template's cached
    decode over the stacked (params, state, cache), writing each block's
    new KV rows back into its slice. Returns (y, new_cache_tree_or_cache).
    Shared by ScannedBlocks and PipelinedBlocks (which passes an empty
    state stack — its blocks are validated stateless at init)."""

    def body(h, per_block):
        p, s, c = per_block
        y, new_c = block.decode(p, s, c, h, pos=pos)
        return y.astype(h.dtype), new_c

    out, new_cache = lax.scan(
        body, x, (stacked_p, stacked_s, cache.get("blocks", {}))
    )
    if jax.tree_util.tree_leaves(new_cache):
        return out, {"blocks": new_cache}
    return out, cache


# The reserved key the stacked PAGED pools live under. serving/kv_cache's
# pool walkers key on it: leaves below carry a leading (S, ...) stage dim,
# so the pool-block axis is 1, not 0 (copy-on-write and per-block byte
# accounting must index/skip accordingly). A dict key (not a wrapper type)
# keeps the pools an ordinary pytree for jit/donation.
STACKED_POOL_KEY = "stacked"


def stacked_init_paged_cache(block, num_blocks, stacked_p, pool_blocks,
                             block_size, dtype):
    """Stacked (S, ...) paged pools for a block stack, under
    ``STACKED_POOL_KEY`` — shared by ScannedBlocks and PipelinedBlocks'
    sequential path so the layout can't diverge. Broadcasts the template's
    pools (same rationale as ``stacked_init_cache``)."""
    p0 = jax.tree_util.tree_map(lambda l: l[0], stacked_p)
    c0 = block.init_paged_cache(p0, pool_blocks, block_size, dtype)
    if not jax.tree_util.tree_leaves(c0):
        return {}
    return {
        STACKED_POOL_KEY: jax.tree_util.tree_map(
            lambda l: jnp.broadcast_to(l, (num_blocks,) + l.shape).copy(),
            c0,
        )
    }


def _stacked_paged_step(step_name, block, stacked_p, stacked_s, cache, x,
                        **kw):
    """Scan one of the template block's paged hooks over the stacked
    (params, state, pools). The block tables and per-slot positions are
    closed over — every layer of every block addresses the same tables,
    exactly as the unrolled Sequential's per-layer pools do — and each
    block's paged step reads/writes only its own (S,) slice of the pools."""
    step = getattr(block, step_name)

    def body(h, per_block):
        p, s, c = per_block
        y, new_c = step(p, s, c, h, **kw)
        return y.astype(h.dtype), new_c

    out, new_cache = lax.scan(
        body, x, (stacked_p, stacked_s, cache.get(STACKED_POOL_KEY, {}))
    )
    if jax.tree_util.tree_leaves(new_cache):
        return out, {STACKED_POOL_KEY: new_cache}
    return out, cache


def stacked_paged_decode(block, stacked_p, stacked_s, cache, x, *,
                         block_tables, positions):
    return _stacked_paged_step(
        "paged_decode", block, stacked_p, stacked_s, cache, x,
        block_tables=block_tables, positions=positions,
    )


def stacked_paged_verify(block, stacked_p, stacked_s, cache, x, *,
                         block_tables, positions):
    return _stacked_paged_step(
        "paged_verify", block, stacked_p, stacked_s, cache, x,
        block_tables=block_tables, positions=positions,
    )


def stacked_paged_prefill(block, stacked_p, stacked_s, cache, x, *,
                          block_table, start):
    return _stacked_paged_step(
        "paged_prefill", block, stacked_p, stacked_s, cache, x,
        block_table=block_table, start=start,
    )


class ScannedBlocks(Layer):
    """S structurally identical, shape-preserving blocks run as one scan.

    ``block_fn()`` must return a fresh ``Layer`` with identical structure
    each call. Blocks may hold state (running stats); its leaves are
    stacked with a leading (S, ...) dim like the params. Deterministic
    computation is numerically identical to the unrolled
    ``Sequential([block_fn() for _ in range(S)])`` given the same per-block
    parameters (asserted in tests/test_scanned_blocks.py). Rng ROUTING
    differs, though: apply() splits one key into S per-block streams, while
    an unrolled Sequential splits across all rng-consuming layers globally —
    Dropout/augmentation masks therefore differ between the scanned and
    unrolled forms (each is still a valid i.i.d. mask stream).
    """

    # Incremental decode IS supported (unlike PipelinedBlocks): the KV
    # caches are stacked with a leading (S, ...) block dim like the params,
    # and decode() scans the template block's cached one-token step over
    # them. decode_safe stays False so a template whose own decode would
    # silently be wrong (position-mixing layers without a cached override)
    # still fails loudly inside the scan body.
    decode_safe = False

    def __init__(
        self,
        block_fn: Callable[[], Layer],
        num_blocks: int,
        *,
        overlap: str = "auto",
        name: Optional[str] = None,
    ):
        """``overlap``: comm/compute overlap for the per-layer parameter
        gather. 'auto' (default) double-buffers the gather whenever the
        AMBIENT strategy provides one (``Strategy.overlap_spec`` — the
        FSDP family; resolved at trace time, so one module serves every
        strategy); 'off' keeps the plain scan body under every strategy;
        'require' raises at trace time if the strategy has no gather to
        overlap (use it to make a perf assumption loud)."""
        super().__init__(name)
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if overlap not in ("auto", "off", "require"):
            raise ValueError(
                "overlap must be 'auto', 'off' or 'require', got "
                f"{overlap!r}"
            )
        self.num_blocks = int(num_blocks)
        self.overlap = overlap
        self.block_fn = block_fn
        self.block = block_fn()  # template: defines structure + names

    def default_name(self) -> str:
        return "scanned_blocks"

    @property
    def needs_rng(self) -> bool:
        return getattr(self.block, "needs_rng", False)

    def sharding_hints(self):
        # Pass the template block's tensor-parallel roles through, shifted
        # past the leading stack dim: 'col' still targets the last dim;
        # 'row' (input dim, dim 0 of the unstacked leaf) becomes 'row1'
        # (dim 1 behind the stack index). Strategies that don't know a role
        # fall back to their default placement.
        def shift(h):
            if isinstance(h, dict):
                return {k: shift(v) for k, v in h.items()}
            if h in ("expert", "pipe"):
                # These roles target dim 0 of their (unstacked) leaf; behind
                # the stack index they would shard the block-stack dim S.
                raise ValueError(
                    f"ScannedBlocks cannot stack blocks with {h!r}-role "
                    "params (MoE expert stacks / nested pipeline stages)"
                )
            return "row1" if h == "row" else h

        inner = shift(self.block.sharding_hints())
        return {"blocks": inner} if inner else {}

    def dtype_hints(self):
        # Stacked params mirror the template block's tree one level down,
        # so its explicit per-layer dtype overrides pass straight through.
        h = self.block.dtype_hints()
        return {"blocks": h} if h is not None and h != {} else {}

    def init(self, key, input_shape: Shape):
        shape = tuple(input_shape)
        params, state = init_stacked_blocks(
            self.block_fn, self.block, self.num_blocks, key, shape,
        )
        out_s = {"blocks": state} if jax.tree_util.tree_leaves(state) else {}
        return {"blocks": params}, out_s, shape

    def _overlap_gather(self):
        """Resolve the ambient strategy's gather at TRACE time (the
        ``current_strategy`` idiom — strategy scopes are entered around
        every jitted step body by ``Model._scoped``)."""
        if self.overlap == "off":
            return None
        from ..parallel.strategy import current_strategy
        strat = current_strategy()
        gather = strat.overlap_spec() if strat is not None else None
        if gather is None and self.overlap == "require":
            raise ValueError(
                "ScannedBlocks(overlap='require') needs an ambient "
                "strategy with an overlap_spec gather (the FSDP family); "
                f"got {type(strat).__name__ if strat else None}"
            )
        return gather

    def apply(self, params, state, x, *, train=False, rng=None):
        rngs = (
            jax.random.split(rng, self.num_blocks) if rng is not None else None
        )
        gather = self._overlap_gather()
        _overlap_trace.record = {
            "layers": self.num_blocks, "active": gather is not None,
        }
        out, new_s = scan_stacked(
            self.block, params["blocks"], state.get("blocks", {}), x,
            train=train, rngs=rngs, overlap_gather=gather,
        )
        # Blocks that return no state (eval-mode BatchNorm, stateless
        # blocks) produce an empty ys tree; mirror Sequential's "omit when
        # empty" contract.
        if jax.tree_util.tree_leaves(new_s):
            return out, {"blocks": new_s}
        return out, {}

    # ---------------------------------------------------- incremental decode
    def init_cache(self, params, batch, max_len, dtype):
        return stacked_init_cache(
            self.block, self.num_blocks, params["blocks"], batch, max_len,
            dtype,
        )

    def decode(self, params, state, cache, x, *, pos):
        return stacked_decode(
            self.block, params["blocks"], state.get("blocks", {}), cache, x,
            pos=pos,
        )

    # Paged (block KV) serving: the per-layer pools stack with a leading
    # (S, ...) stage dim like everything else in this module, and each
    # hook scans the template block's paged step over the stack with the
    # block tables / per-slot position vectors closed over. The serving
    # engine's allocator and prefix store see block indices on axis 1
    # (the STACKED_POOL_KEY contract in serving/kv_cache.py).
    def init_paged_cache(self, params, num_blocks, block_size, dtype):
        return stacked_init_paged_cache(
            self.block, self.num_blocks, params["blocks"], num_blocks,
            block_size, dtype,
        )

    def paged_decode(self, params, state, cache, x, *, block_tables,
                     positions):
        return stacked_paged_decode(
            self.block, params["blocks"], state.get("blocks", {}), cache, x,
            block_tables=block_tables, positions=positions,
        )

    def paged_verify(self, params, state, cache, x, *, block_tables,
                     positions):
        return stacked_paged_verify(
            self.block, params["blocks"], state.get("blocks", {}), cache, x,
            block_tables=block_tables, positions=positions,
        )

    def paged_prefill(self, params, state, cache, x, *, block_table, start):
        return stacked_paged_prefill(
            self.block, params["blocks"], state.get("blocks", {}), cache, x,
            block_table=block_table, start=start,
        )
