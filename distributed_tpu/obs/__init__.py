"""Unified observability runtime.

One always-cheap telemetry surface for a codebase that had five
(``Model.last_fit_telemetry``, ``Engine.last_run_telemetry``, fleet
request rows, supervisor recovery rows, the resilience event log):

- :mod:`~distributed_tpu.obs.registry` — counters, gauges, fixed-bucket
  histograms, bounded per-step rings; the legacy ``last_*_telemetry``
  dicts are views stored here (``set_report``/``get_report``).
- :mod:`~distributed_tpu.obs.spans` — nested host-side spans
  (``obs.span("prefill")``) that accrue into the registry, forward to
  ``jax.profiler.TraceAnnotation`` (same names on XProf), and carry the
  ``StepTimer`` stall-category attribution through one code path.
- :mod:`~distributed_tpu.obs.flight` — a bounded ring of the last N
  per-step records, dumped (fsync'd JSONL) on preemption, fault-injected
  kills, and unhandled exceptions: the seconds before death.
- :mod:`~distributed_tpu.obs.aggregate` — cross-rank skew + straggler
  attribution over ``metrics_snapshot`` events flushed through the
  ``DTPU_EVENT_LOG`` transport; the supervisor names the slowest rank.
- :mod:`~distributed_tpu.obs.export` — Prometheus text format + JSONL
  snapshot files.
- :mod:`~distributed_tpu.obs.cli` — the ``dtpu-events`` postmortem CLI.

Gate: ``bench.py obs`` asserts instrumented-vs-bare fit overhead <= 3%
and that an injected slow rank is correctly named on a supervised gang
(BENCH_obs.json). See docs/OBSERVABILITY.md.

jax-free at import (controller processes import it next to the
supervisor); spans resolve jax lazily.
"""

from __future__ import annotations

from . import aggregate, export, flight, registry, spans
from .flight import FlightRecorder, default_recorder, dump as dump_flight
from .registry import (
    MetricsRegistry,
    default_registry,
    enabled,
    set_enabled,
)
from .spans import Span, current_span, span

__all__ = [
    "FlightRecorder",
    "MetricsRegistry",
    "Span",
    "aggregate",
    "current_span",
    "default_recorder",
    "default_registry",
    "dump_flight",
    "enabled",
    "export",
    "flight",
    "registry",
    "set_enabled",
    "span",
    "spans",
]
