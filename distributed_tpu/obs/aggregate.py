"""Cross-rank aggregation + straggler attribution.

A synchronous gang runs at the speed of its SLOWEST rank: one worker on a
degraded host drags every peer's step time up, and per-process telemetry
cannot see it — every rank reports the same (slow) rate, because the
collective serializes them. What CAN see it is the per-rank *host-side*
step timings before the collective equalizes them.

Transport: workers flush compact ``metrics_snapshot`` events into the
existing ``DTPU_EVENT_LOG`` file (``Model.fit`` does this every
``DTPU_OBS_FLUSH_EVERY`` steps — the event log is already the
supervisor<->worker channel, and ``emit`` is a no-op unsupervised). Each
snapshot carries the rank's recent per-step wall seconds.

Which signal: per-step *wall* time is equalized across a synchronous gang
by the collectives themselves — the victims spend the skew WAITING (their
``dispatch`` stall bucket), the straggler spends it WORKING — so the
aggregation keys on ``self_seconds`` (wall minus dispatch/input waits,
the rank's own host time; ``Model.fit`` flushes both) and falls back to
``step_seconds`` for streams that predate the field.

Chief side: :func:`skew_report` computes per-rank step-time stats and the
max/median skew; :func:`straggler` names the slowest rank when its median
step time exceeds the gang median by a threshold. The supervisor runs
both at every terminal boundary and emits ``rank_skew`` (always, when
snapshots exist) and ``straggler`` (when one is detected) events —
verified end-to-end by ``bench.py obs`` with an injected ``slow_steps``
fault on a real 2-worker gang.

jax-free: aggregation runs on the supervisor's controller process.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..utils import event_schema as evs

DEFAULT_THRESHOLD = 1.5


def _median(values: Sequence[float]) -> Optional[float]:
    vals = sorted(v for v in values if v is not None)
    if not vals:
        return None
    mid = len(vals) // 2
    if len(vals) % 2:
        return float(vals[mid])
    return float((vals[mid - 1] + vals[mid]) / 2.0)


def snapshots(events: Sequence[dict]) -> List[dict]:
    """The ``metrics_snapshot`` records of an event stream, in order."""
    return [e for e in events if e.get("event") == evs.METRICS_SNAPSHOT]


def rank_step_seconds(events: Sequence[dict]) -> dict:
    """Per-rank concatenated per-step samples from every snapshot flush:
    ``{rank: [seconds, ...]}``. Prefers each snapshot's ``self_seconds``
    (host self time — see module docstring) over ``step_seconds``."""
    per: dict = {}
    for snap in snapshots(events):
        rank = snap.get("rank")
        if rank is None:
            continue
        samples = snap.get("self_seconds") or snap.get("step_seconds", ())
        per.setdefault(int(rank), []).extend(float(s) for s in samples)
    return per


def skew_report(events: Sequence[dict]) -> Optional[dict]:
    """Per-rank min/median/max step seconds plus the cross-rank skew:
    ``skew = rank_median / gang_median`` (gang median = median of the
    per-rank medians — robust to one bad rank, which is the point).
    None when the stream holds no snapshots (unsupervised or pre-obs
    logs)."""
    per = rank_step_seconds(events)
    per = {r: v for r, v in per.items() if v}
    if not per:
        return None
    rank_rows = []
    medians = {}
    for rank in sorted(per):
        vals = per[rank]
        med = _median(vals)
        medians[rank] = med
        rank_rows.append({
            "rank": rank,
            "samples": len(vals),
            "min_step_s": round(min(vals), 6),
            "median_step_s": round(med, 6),
            "max_step_s": round(max(vals), 6),
        })
    gang_median = _median(list(medians.values()))
    for row in rank_rows:
        row["skew"] = (
            round(row["median_step_s"] / gang_median, 4)
            if gang_median else None
        )
    slowest = max(rank_rows, key=lambda r: r["median_step_s"])
    return {
        "ranks": rank_rows,
        "world": len(rank_rows),
        "gang_median_step_s": round(gang_median, 6) if gang_median else None,
        "max_skew": slowest["skew"],
        "slowest_rank": slowest["rank"],
    }


def straggler(events: Sequence[dict],
              threshold: float = DEFAULT_THRESHOLD) -> Optional[dict]:
    """The straggler verdict: the slowest rank, when its median step time
    exceeds the gang median by ``threshold`` AND there are >= 2 ranks to
    compare (a single process cannot straggle relative to itself).
    Returns the row the supervisor emits as a ``straggler`` event, or
    None."""
    report = skew_report(events)
    if report is None or report["world"] < 2:
        return None
    if report["max_skew"] is None or report["max_skew"] < float(threshold):
        return None
    row = next(r for r in report["ranks"]
               if r["rank"] == report["slowest_rank"])
    return {
        "rank": report["slowest_rank"],
        "skew": report["max_skew"],
        "median_step_s": row["median_step_s"],
        "gang_median_step_s": report["gang_median_step_s"],
        "threshold": float(threshold),
        "world": report["world"],
    }


__all__ = [
    "DEFAULT_THRESHOLD",
    "rank_step_seconds",
    "skew_report",
    "snapshots",
    "straggler",
]
