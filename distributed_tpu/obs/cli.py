"""``dtpu-events``: summarize an event log + flight dumps into a postmortem.

    dtpu-events run.events.jsonl
    dtpu-events run.events.jsonl --flight /tmp/flight-rank1-pid33.jsonl
    dtpu-events run.events.jsonl --json
    dtpu-events run.events.jsonl --follow   # live tail for a running gang

Reads a supervised run's JSONL event log (``utils.events``) and renders a
human postmortem: the attempt timeline, injected faults, per-recovery
MTTR rows, cross-rank skew / straggler attribution (``obs.aggregate``),
and the tail of every flight-recorder dump the run referenced
(``flight_dump`` events; ``--flight`` adds files by hand) — the seconds
before each death, not just the lifecycle facts. ``--json`` emits the
same summary as one machine-readable object.

``--follow`` tails a LIVE log instead: one rendered line per event as
it lands, surviving the writer's rotate/truncate the same way
``EventLog`` survives its reader's (stat the inode, reopen on change)
and skipping a torn tail line until its newline arrives — watch a
serving gang (``serve_service``) or a supervised training run without
re-running the postmortem.

jax-free: runs on any controller box against a copied log file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path
from typing import List, Optional

from ..utils import event_schema as evs
from ..utils.events import read_events
from . import aggregate
from .flight import read_dump


def _fmt_ts(ts) -> str:
    try:
        return time.strftime("%H:%M:%S", time.localtime(float(ts)))
    except (TypeError, ValueError):
        return "?"


def summarize(events: List[dict], flight_paths=(),
              straggler_threshold: float = aggregate.DEFAULT_THRESHOLD
              ) -> dict:
    """The postmortem as data; ``render`` turns it into text."""
    attempts = [e for e in events if e["event"] == evs.ATTEMPT_START]
    ends = [e for e in events if e["event"] == evs.ATTEMPT_END]
    faults = [e for e in events if e["event"] == evs.FAULT_INJECTED]
    recoveries = [e for e in events if e["event"] == evs.RECOVERY]
    resizes = [e for e in events if e["event"] == evs.GANG_RESIZE]
    terminal = next(
        (e for e in reversed(events)
         if e["event"] in (evs.RUN_COMPLETE, evs.BUDGET_EXHAUSTED,
                           evs.PREEMPTION_CAP_EXHAUSTED)),
        None,
    )
    dump_paths: List[str] = [
        e["path"] for e in events
        if e["event"] == evs.FLIGHT_DUMP and e.get("path")
    ]
    for p in flight_paths:
        if str(p) not in dump_paths:
            dump_paths.append(str(p))
    dumps = []
    for p in dump_paths:
        records = read_dump(p)
        header = next(
            (r for r in records if r.get("kind") == "flight_header"), None
        )
        dumps.append({
            "path": str(p),
            "readable": bool(records),
            "reason": (header or {}).get("reason"),
            "rank": (header or {}).get("rank"),
            "records": [r for r in records
                        if r.get("kind") != "flight_header"],
        })
    return {
        "events": len(events),
        "attempts": [
            {
                "attempt": a.get("attempt"),
                "world_size": a.get("world_size"),
                "started": a.get("ts"),
                "ok": next(
                    (e.get("ok") for e in ends
                     if e.get("attempt") == a.get("attempt")), None
                ),
                "failed_ranks": next(
                    (e.get("failed_ranks") for e in ends
                     if e.get("attempt") == a.get("attempt")), None
                ),
            }
            for a in attempts
        ],
        "terminal": terminal,
        "faults": faults,
        "resizes": resizes,
        "recoveries": recoveries,
        "rank_skew": aggregate.skew_report(events),
        "straggler": aggregate.straggler(events, straggler_threshold),
        "straggler_events": [e for e in events if e["event"] == evs.STRAGGLER],
        # Last-writer-wins: one schedule/bubble row per postmortem (each
        # fit re-emits; the latest reflects the run that ended the log).
        "pipeline_schedule": next(
            (e for e in reversed(events)
             if e["event"] == evs.PIPELINE_SCHEDULE_SELECTED), None
        ),
        "bubble": next(
            (e for e in reversed(events)
             if e["event"] == evs.BUBBLE_REPORT), None
        ),
        # Speculation-that-pays timeline: last spec_verify aggregate,
        # every draft sync / per-tenant k move, and the gossip traffic.
        "spec_verify": next(
            (e for e in reversed(events)
             if e["event"] == evs.SPEC_VERIFY), None
        ),
        "draft_syncs": [e for e in events if e["event"] == evs.DRAFT_SYNC],
        "spec_k_adjusts": [e for e in events
                           if e["event"] == evs.SPEC_K_ADJUST],
        "gossip_advertises": [e for e in events
                              if e["event"] == evs.PREFIX_GOSSIP_ADVERTISE],
        "gossip_adopts": [e for e in events
                          if e["event"] == evs.PREFIX_GOSSIP_ADOPT],
        "flight_dumps": dumps,
    }


def render(summary: dict, *, tail: int = 10) -> str:
    lines = [f"postmortem: {summary['events']} events"]
    for a in summary["attempts"]:
        status = ("ok" if a["ok"] else
                  "FAILED" if a["ok"] is not None else "no end record")
        extra = (f" failed_ranks={a['failed_ranks']}"
                 if a.get("failed_ranks") else "")
        lines.append(
            f"  attempt {a['attempt']} [{_fmt_ts(a['started'])}] "
            f"world={a['world_size']}: {status}{extra}"
        )
    term = summary["terminal"]
    if term is not None:
        lines.append(f"  terminal: {term['event']}")
    for f in summary["faults"]:
        where = f" replica={f['replica']}" if f.get("replica") else ""
        lines.append(
            f"  fault injected: {f.get('mode')} at step {f.get('step')}"
            f"{where} [{_fmt_ts(f.get('ts'))}]"
        )
    for rs in summary["resizes"]:
        lines.append(
            f"  gang resize {rs.get('from_world')} -> {rs.get('to_world')} "
            f"({rs.get('reason')}, {rs.get('trigger')})"
        )
    for r in summary["recoveries"]:
        lines.append(
            f"  recovery (attempt {r.get('failed_attempt')} -> "
            f"{r.get('recovered_attempt')}): detect={r.get('detect_s')}s "
            f"gang_reform={r.get('gang_reform_s')}s "
            f"restore={r.get('restore_s')}s[{r.get('restore_tier')}] "
            f"recompile={r.get('recompile_s')}s"
        )
        for p in r.get("flight_dumps") or ():
            lines.append(f"    flight dump: {p}")
    skew = summary["rank_skew"]
    if skew is not None:
        lines.append(
            f"  rank skew: gang median {skew['gang_median_step_s']}s/step, "
            f"max skew {skew['max_skew']}x (rank {skew['slowest_rank']})"
        )
        for row in skew["ranks"]:
            lines.append(
                f"    rank {row['rank']}: median {row['median_step_s']}s "
                f"(x{row['skew']}, {row['samples']} samples)"
            )
    sched = summary.get("pipeline_schedule")
    if sched is not None:
        lines.append(
            f"  pipeline schedule: {sched.get('schedule')} "
            f"(interleave={sched.get('interleave')}, "
            f"stages={sched.get('num_stages')}, "
            f"microbatches={sched.get('num_microbatches')})"
        )
    bub = summary.get("bubble")
    if bub is not None:
        lines.append(
            f"  pipeline bubble: {bub.get('bubble_fraction')} idle "
            f"over {bub.get('ticks')} ticks"
        )
    sv = summary.get("spec_verify")
    if sv is not None:
        lines.append(
            f"  speculative decode: accept_rate={sv.get('accept_rate')} "
            f"({sv.get('accepted')}/{sv.get('proposed')} over "
            f"{sv.get('rounds')} rounds, "
            f"{sv.get('tokens_per_dispatch')} tok/dispatch)"
        )
    for ds in summary.get("draft_syncs", ()):
        lines.append(
            f"  draft sync [{_fmt_ts(ds.get('ts'))}]: "
            f"weights_version={ds.get('weights_version')} "
            f"staleness={ds.get('staleness')} source={ds.get('source')}"
        )
    for ka in summary.get("spec_k_adjusts", ()):
        lines.append(
            f"  spec_k adjust [{_fmt_ts(ka.get('ts'))}]: "
            f"tenant={ka.get('tenant')} {ka.get('old_k')} -> "
            f"{ka.get('new_k')} (accept_ema={ka.get('accept_ema')})"
        )
    adv = summary.get("gossip_advertises", ())
    adp = summary.get("gossip_adopts", ())
    if adv or adp:
        lines.append(
            f"  prefix gossip: {len(adv)} advertise(s) "
            f"({sum(int(e.get('blocks', 0)) for e in adv)} blocks), "
            f"{len(adp)} adopt(s) "
            f"({sum(int(e.get('blocks', 0)) for e in adp)} blocks)"
        )
        for e in adp:
            lines.append(
                f"    adopt [{_fmt_ts(e.get('ts'))}]: {e.get('source')} "
                f"-> {e.get('replica')} ({e.get('blocks')} blocks, "
                f"weights_version={e.get('weights_version')})"
            )
    strag = summary["straggler"] or next(
        iter(summary["straggler_events"]), None
    )
    if strag is not None:
        lines.append(
            f"  STRAGGLER: rank {strag.get('rank')} at "
            f"{strag.get('skew')}x the gang median "
            f"(threshold {strag.get('threshold')})"
        )
    for d in summary["flight_dumps"]:
        if not d["readable"]:
            lines.append(f"  flight dump {d['path']}: unreadable/empty")
            continue
        lines.append(
            f"  flight dump {d['path']} (rank {d['rank']}, "
            f"reason={d['reason']!r}): last {min(tail, len(d['records']))} "
            f"of {len(d['records'])} records"
        )
        for rec in d["records"][-tail:]:
            body = {k: v for k, v in rec.items() if k not in ("ts", "kind")}
            lines.append(
                f"    [{_fmt_ts(rec.get('ts'))}] {rec.get('kind')} "
                + " ".join(f"{k}={v}" for k, v in body.items())
            )
    return "\n".join(lines)


def event_line(event: dict) -> str:
    """One event as one follow-mode line: timestamp, kind, then the
    payload keys in emit order (the transport's own ts/event/pid are
    folded into the prefix)."""
    body = {k: v for k, v in event.items()
            if k not in ("ts", "event", "pid")}
    fields = " ".join(f"{k}={v}" for k, v in body.items())
    return (f"[{_fmt_ts(event.get('ts'))}] {event.get('event')}"
            + (f" {fields}" if fields else ""))


def follow(path, *, poll_s: float = 0.2, stop=None):
    """Yield events appended to ``path`` as they land, forever (or until
    ``stop()`` returns true — the test seam). The reader mirrors
    ``EventLog``'s writer idiom from the other side: on EOF, stat the
    path and reopen when the inode changed or the file shrank (rotation/
    truncation), and hold back a torn tail line until its newline
    arrives — a half-written record is pending, not corrupt. A path that
    does not exist yet is waited for, so the tail can start before the
    gang does."""
    path = str(path)
    f = None
    ino = None
    buf = ""
    try:
        while True:
            if f is None:
                try:
                    f = open(path, "r")
                    ino = os.fstat(f.fileno()).st_ino
                    buf = ""
                except FileNotFoundError:
                    if stop is not None and stop():
                        return
                    time.sleep(poll_s)
                    continue
            chunk = f.read()
            if chunk:
                buf += chunk
                while "\n" in buf:
                    line, buf = buf.split("\n", 1)
                    if not line.strip():
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn mid-rotation: skip, keep tailing
                continue
            try:
                st = os.stat(path)
                rotated = (st.st_ino != ino
                           or st.st_size < f.tell() - len(buf))
            except FileNotFoundError:
                rotated = True
            if rotated:
                f.close()
                f = None
                continue
            if stop is not None and stop():
                return
            time.sleep(poll_s)
    finally:
        if f is not None:
            f.close()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="dtpu-events", description=__doc__)
    ap.add_argument("event_log", type=str,
                    help="JSONL event log (the supervisor's DTPU_EVENT_LOG)")
    ap.add_argument("--flight", action="append", default=[],
                    help="extra flight-dump file(s) to include (dumps "
                         "referenced by flight_dump events are found "
                         "automatically)")
    ap.add_argument("--tail", type=int, default=10,
                    help="flight records to show per dump (default 10)")
    ap.add_argument("--straggler-threshold", type=float,
                    default=aggregate.DEFAULT_THRESHOLD)
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object instead of "
                         "the human rendering")
    ap.add_argument("--follow", action="store_true",
                    help="tail the log live (one line per event as it "
                         "lands; waits for the file if it does not exist "
                         "yet; ctrl-C to stop)")
    args = ap.parse_args(argv)
    if args.follow:
        try:
            for event in follow(args.event_log):
                print(json.dumps(event) if args.json
                      else event_line(event), flush=True)
        except KeyboardInterrupt:
            pass
        return 0
    if not Path(args.event_log).exists():
        print(f"dtpu-events: no such event log: {args.event_log}",
              file=sys.stderr)
        return 2
    events = read_events(args.event_log)
    summary = summarize(events, flight_paths=args.flight,
                        straggler_threshold=args.straggler_threshold)
    if args.json:
        print(json.dumps(summary))
    else:
        print(render(summary, tail=args.tail))
    return 0


if __name__ == "__main__":
    sys.exit(main())
