"""Exporters: Prometheus text format + JSONL snapshot files.

Two ways out of the registry, both built on the deterministic
:meth:`MetricsRegistry.snapshot`:

- :func:`prometheus_text` renders the standard text exposition format
  (``dtpu_``-prefixed, histograms as cumulative ``_bucket{le=...}`` +
  ``_sum``/``_count``) — paste behind any HTTP handler or textfile
  collector; :func:`write_prometheus` drops it to a file atomically
  enough for the node-exporter textfile pattern (tmp + rename).
- :func:`append_snapshot` appends ONE JSON line holding the full
  snapshot to a JSONL file — the same one-line-per-record shape as the
  event log, so ``read_events`` parses snapshot files too.
"""

from __future__ import annotations

import json
import os
import re
from pathlib import Path
from typing import Optional

from . import registry as registry_mod

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Registry names use ``/`` for nesting and ``.`` freely; Prometheus
    metric names allow ``[a-zA-Z0-9_:]`` — everything else becomes ``_``."""
    return "dtpu_" + _NAME_RE.sub("_", name)


def prometheus_text(snapshot: Optional[dict] = None, *, registry=None) -> str:
    """Render a snapshot (default: the global registry's, taken now) in
    the Prometheus text exposition format. Deterministic: sorted names,
    stable bucket order."""
    if snapshot is None:
        reg = registry or registry_mod.default_registry()
        snapshot = reg.snapshot()
    lines = []
    for name, value in snapshot.get("counters", {}).items():
        p = _prom_name(name)
        lines.append(f"# TYPE {p} counter")
        lines.append(f"{p} {value}")
    for name, value in snapshot.get("gauges", {}).items():
        p = _prom_name(name)
        lines.append(f"# TYPE {p} gauge")
        lines.append(f"{p} {value}")
    for name, hist in snapshot.get("histograms", {}).items():
        p = _prom_name(name)
        lines.append(f"# TYPE {p} histogram")
        cum = 0
        for bound, count in zip(hist["buckets"], hist["counts"]):
            cum += count
            lines.append(f'{p}_bucket{{le="{bound}"}} {cum}')
        cum += hist.get("overflow", 0)
        lines.append(f'{p}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{p}_sum {hist['sum']}")
        lines.append(f"{p}_count {hist['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def write_prometheus(path, *, registry=None) -> Path:
    """Write the current exposition to ``path`` via tmp+rename (the
    textfile-collector contract: scrapers never see a half-written
    file)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(prometheus_text(registry=registry))
    os.replace(tmp, path)
    return path


def append_snapshot(path, *, registry=None, **extra) -> Path:
    """Append one full-snapshot JSON line (plus ``extra`` fields, e.g.
    ``step=``) to a JSONL file."""
    reg = registry or registry_mod.default_registry()
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    rec = {**reg.snapshot(), **extra}
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return path


__all__ = ["append_snapshot", "prometheus_text", "write_prometheus"]
