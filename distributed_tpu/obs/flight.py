"""Flight recorder: the last N per-step records, dumped at death.

The JSONL event log (``utils.events``) records LIFECYCLE facts — restarts,
preemptions, restores. What a postmortem actually needs first is the
seconds *before* death: was the step rate degrading, was input stalling,
which step was in flight. The flight recorder is that black box — a
bounded in-memory ring of small per-step records (``Model.fit`` appends
one per dispatch; custom loops can append their own) that costs one deque
append per step while alive, and is dumped to a fsync'd JSONL file on the
paths where a process is about to die:

- ``PreemptionHandler`` before its exit-75,
- ``FaultInjector`` kills before their ``os._exit`` (every injected crash
  leaves a readable dump — asserted by tests and ``bench.py obs``),
- ``Model.fit``'s unhandled-exception path.

Dumps land next to the supervisor's event log (``$DTPU_FLIGHT_DIR``, or
the ``DTPU_EVENT_LOG`` directory) as ``flight-rank<r>-pid<p>.jsonl``, and
every dump emits a ``flight_dump`` event into the event log so
``Supervisor.recovery_rows`` / ``dtpu-events`` can reference the file
from the recovery postmortem. The dump file reuses the event-log
durability idiom: whole JSON lines, flushed and fsync'd, with a torn
final line skipped on read (``utils.events.read_events`` reads dumps
too — same skip-torn-tail property).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from pathlib import Path
from typing import List, Optional

from ..utils import event_schema as evs
from ..utils import events as events_lib
from ..utils.logging import rank_world
from . import registry as registry_mod

ENV_DIR = "DTPU_FLIGHT_DIR"

DEFAULT_CAPACITY = 128


class FlightRecorder:
    """Bounded ring of per-step records; ``dump()`` writes them durably."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._ring = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._ring)

    def record(self, kind: str, **fields) -> None:
        """Append one record (no-op when observability is disabled). Keep
        records small and host-side only — never fetch a device value to
        record it (that would put a sync on the step path)."""
        if not registry_mod.enabled():
            return
        rec = {"ts": time.time(), "kind": kind, **fields}
        with self._lock:
            self._ring.append(rec)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def dump(self, path=None, *, reason: str = "", extra: Optional[dict] = None
             ) -> Optional[Path]:
        """Write a header line + every ring record to ``path`` (default:
        :func:`default_dump_path`), fsync'd, then emit a ``flight_dump``
        event referencing it. Returns the path, or None when no dump
        location is configured (unsupervised, no ``DTPU_FLIGHT_DIR``).
        Overwrites a previous dump at the same path — the latest death
        wins, and the per-rank-per-pid filename keeps gangs separate."""
        if path is None:
            path = default_dump_path()
            if path is None:
                return None
        path = Path(path)
        rank, world = rank_world()
        records = self.snapshot()
        header = {
            "ts": time.time(),
            "kind": "flight_header",
            "reason": reason,
            "pid": os.getpid(),
            "rank": rank,
            "world": world,
            "records": len(records),
            "capacity": self.capacity,
            **(extra or {}),
        }
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "w") as f:
            f.write("\n".join(json.dumps(r) for r in [header] + records))
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        events_lib.emit(
            evs.FLIGHT_DUMP, path=str(path), reason=reason, rank=rank,
            records=len(records),
            attempt=_int_env("DTPU_ATTEMPT"),
        )
        return path


def _int_env(name: str) -> Optional[int]:
    val = os.environ.get(name)
    try:
        return int(val) if val else None
    except ValueError:
        return None


def default_dump_path() -> Optional[Path]:
    """``$DTPU_FLIGHT_DIR/flight-rank<r>-pid<p>.jsonl``, falling back to
    the ``DTPU_EVENT_LOG`` directory (the supervisor's transport — so a
    supervised gang gets flight dumps with zero extra configuration), or
    None when neither is set (unsupervised runs pay nothing)."""
    base = os.environ.get(ENV_DIR)
    if not base:
        log = os.environ.get(events_lib.ENV_VAR)
        if not log:
            return None
        base = str(Path(log).parent)
    rank, _ = rank_world()
    return Path(base) / f"flight-rank{rank}-pid{os.getpid()}.jsonl"


def read_dump(path) -> List[dict]:
    """All well-formed records of a dump, torn final line skipped — the
    same read the event log uses (a crash mid-dump must never make the
    postmortem unreadable)."""
    return events_lib.read_events(path)


_default = FlightRecorder()


def default_recorder() -> FlightRecorder:
    """The process-global recorder ``Model.fit`` and the death paths use."""
    return _default


def dump(reason: str = "", **extra) -> Optional[Path]:
    """Dump the global recorder; never raises (a failed dump must not
    change how a process dies)."""
    try:
        return _default.dump(reason=reason, extra=extra or None)
    except Exception:
        return None


__all__ = [
    "DEFAULT_CAPACITY",
    "FlightRecorder",
    "default_dump_path",
    "default_recorder",
    "dump",
    "read_dump",
]
