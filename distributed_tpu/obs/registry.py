"""Metrics registry: counters, gauges, histograms, per-step rings.

One process-global :class:`MetricsRegistry` (``default_registry()``) that
every subsystem publishes into — ``Model.fit``, ``serving.Engine.run``,
``fleet.ServingFleet``, ``rl.PostTrainer``, and the resilience stack —
instead of five incompatible ad-hoc telemetry surfaces. The legacy
``last_fit_telemetry`` / ``last_run_telemetry`` dicts are VIEWS stored
here (:meth:`MetricsRegistry.set_report`), key-for-key identical to what
they always held (pinned by tests/test_obs.py's parity tests).

Always cheap: every mutator is a dict update under one lock (~1 µs), and
``set_enabled(False)`` (or ``DTPU_OBS=0``) turns all of them into no-ops
— which is what ``bench.py obs`` compares against to assert the ≤ 3%
instrumented-vs-bare overhead gate.

Deterministic snapshots: :meth:`snapshot` emits every section with sorted
keys, so the same run produces the same key sequence (and the Prometheus
/ JSONL exporters in ``obs.export`` inherit the stability).

jax-free by design: the registry is importable on jax-free controllers
(the supervisor's rule), and the span tracer keeps its jax dependency
lazy in ``obs.spans``.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, List, Optional

ENABLE_ENV = "DTPU_OBS"

# Seconds-scale latency buckets: wide enough for everything from a CPU-sim
# dispatch (~1 ms) to a gang restore (~10 s). Fixed at registry level so
# cross-rank aggregation compares like with like.
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0)

DEFAULT_RING_SIZE = 256

_enabled = os.environ.get(ENABLE_ENV, "1") != "0"


def enabled() -> bool:
    """Whether the registry (and with it spans and the flight recorder)
    records anything. ``DTPU_OBS=0`` disables at import; ``set_enabled``
    flips it at runtime (the bench's bare-vs-instrumented pair)."""
    return _enabled


def set_enabled(value: bool) -> bool:
    global _enabled
    prev = _enabled
    _enabled = bool(value)
    return prev


class Histogram:
    """Fixed-bucket histogram (cumulative-le semantics on export): counts
    per upper bound plus an overflow bucket, a running sum, and a count."""

    __slots__ = ("buckets", "counts", "overflow", "sum", "count")

    def __init__(self, buckets=DEFAULT_BUCKETS):
        self.buckets = tuple(float(b) for b in buckets)
        self.counts = [0] * len(self.buckets)
        self.overflow = 0
        self.sum = 0.0
        self.count = 0

    def record(self, value: float):
        v = float(value)
        self.sum += v
        self.count += 1
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.overflow += 1

    def snapshot(self) -> dict:
        return {
            "buckets": list(self.buckets),
            "counts": list(self.counts),
            "overflow": self.overflow,
            "sum": round(self.sum, 6),
            "count": self.count,
        }


class MetricsRegistry:
    """Counters, gauges, fixed-bucket histograms, bounded per-step rings,
    and stored structured reports. Thread-safe (fit loops, checkpoint
    writer threads, and fleet step threads all publish concurrently)."""

    def __init__(self, ring_size: int = DEFAULT_RING_SIZE):
        self.ring_size = int(ring_size)
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._rings: Dict[str, collections.deque] = {}
        self._reports: Dict[str, dict] = {}

    # ------------------------------------------------------------- mutators
    def counter(self, name: str, inc: float = 1.0) -> None:
        """Monotonic accumulator (counts, seconds-of-stall, bytes)."""
        if not _enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + float(inc)

    def gauge(self, name: str, value: float) -> None:
        """Last-value-wins instantaneous reading (queue depth, utilization,
        bytes per device)."""
        if not _enabled:
            return
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float, buckets=None) -> None:
        """Record one sample into the named fixed-bucket histogram."""
        if not _enabled:
            return
        with self._lock:
            hist = self._histograms.get(name)
            if hist is None:
                hist = Histogram(buckets or DEFAULT_BUCKETS)
                self._histograms[name] = hist
            hist.record(value)

    def ring_append(self, name: str, record: dict) -> None:
        """Append to the named bounded per-step ring (newest-last; the
        oldest record falls off past ``ring_size``). Records should be
        small flat dicts — they ride in cross-rank snapshot flushes."""
        if not _enabled:
            return
        with self._lock:
            ring = self._rings.get(name)
            if ring is None:
                ring = collections.deque(maxlen=self.ring_size)
                self._rings[name] = ring
            ring.append(dict(record))

    def set_report(self, name: str, report: dict) -> dict:
        """Store a structured telemetry view (e.g. the dict behind
        ``model.last_fit_telemetry``) and return the STORED object, so the
        legacy attribute and the registry hold the same dict — the
        derived-view contract the parity tests pin. Stored even when
        disabled: reports are the backward-compatible surface, and
        ``set_enabled(False)`` must not silently null legacy telemetry."""
        with self._lock:
            self._reports[name] = report
        return report

    # -------------------------------------------------------------- readers
    def get_report(self, name: str) -> Optional[dict]:
        with self._lock:
            return self._reports.get(name)

    def ring(self, name: str) -> List[dict]:
        with self._lock:
            ring = self._rings.get(name)
            return [dict(r) for r in ring] if ring is not None else []

    def counter_value(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def gauge_value(self, name: str) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name)

    def snapshot(self) -> dict:
        """Deterministic full snapshot: every section sorted by name, so
        identical runs produce identical key sequences (pinned by
        tests/test_obs.py) and exporters emit stable output."""
        with self._lock:
            return {
                "ts": time.time(),
                "counters": {
                    k: round(self._counters[k], 6)
                    for k in sorted(self._counters)
                },
                "gauges": {
                    k: round(self._gauges[k], 6) for k in sorted(self._gauges)
                },
                "histograms": {
                    k: self._histograms[k].snapshot()
                    for k in sorted(self._histograms)
                },
                "rings": {
                    k: [dict(r) for r in self._rings[k]]
                    for k in sorted(self._rings)
                },
                "reports": {k: self._reports[k] for k in sorted(self._reports)},
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._rings.clear()
            self._reports.clear()


_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-global registry every built-in producer publishes to."""
    return _default


__all__ = [
    "DEFAULT_BUCKETS",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
    "enabled",
    "set_enabled",
]
