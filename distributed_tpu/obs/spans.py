"""Host-side span tracer: nested named regions, one code path for all
attribution.

``with obs.span("prefill"):`` times a block and

- accrues the elapsed seconds into the metrics registry as a
  ``span_seconds/<path>`` histogram (``<path>`` is the slash-joined
  nesting, e.g. ``decode/sample``) plus a ``span_calls/<path>`` counter,
- forwards the block to ``jax.profiler.TraceAnnotation`` so the SAME
  name shows up on XProf/TensorBoard device timelines, and
- optionally attributes into a live ``StepTimer`` (``span(name,
  timer=t)`` calls ``t.attribute(name, seconds)``), which is how the
  train/serve/checkpoint stall categories flow through one code path
  instead of hand-rolled ``perf_counter`` pairs.

The jax import is lazy (and optional): a jax-free controller process can
use spans — they just skip the trace annotation. When the registry is
disabled (``obs.set_enabled(False)`` / ``DTPU_OBS=0``) a span degrades to
a plain timed block: the timer attribution still happens (legacy
telemetry must not change when observability is off), the registry and
annotation work is skipped.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional

from . import registry as registry_mod

_tls = threading.local()

_trace_annotation = None  # resolved lazily: jax.profiler.TraceAnnotation


def _annotation(name: str):
    global _trace_annotation
    if _trace_annotation is None:
        try:
            import jax

            _trace_annotation = jax.profiler.TraceAnnotation
        except Exception:  # jax-free controller: spans still time/attribute
            _trace_annotation = contextlib.nullcontext
    try:
        return _trace_annotation(name)
    except TypeError:  # nullcontext() takes no useful arg on some versions
        return contextlib.nullcontext()


def span_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def current_span() -> Optional[str]:
    """Slash-joined path of the innermost open span on this thread."""
    stack = span_stack()
    return "/".join(stack) if stack else None


class Span:
    """Yielded handle: ``seconds`` is filled when the block exits, so the
    caller can reuse the measured wall time (the fit loop's flight-record
    rows) without timing the block twice."""

    __slots__ = ("name", "path", "seconds")

    def __init__(self, name: str, path: str):
        self.name = name
        self.path = path
        self.seconds = 0.0


@contextlib.contextmanager
def span(name: str, *, timer=None, registry=None):
    """Time a named, nestable region. See module docstring.

    ``timer``: a ``utils.profiler.StepTimer`` to attribute the elapsed
    seconds to (category = ``name``, NOT the nested path — stall buckets
    stay flat, matching the pre-span contract). ``registry``: override
    the target registry (default: the process-global one).
    """
    stack = span_stack()
    stack.append(name)
    path = "/".join(stack)
    handle = Span(name, path)
    on = registry_mod.enabled()
    ctx = _annotation(name) if on else contextlib.nullcontext()
    t0 = time.perf_counter()
    try:
        with ctx:
            yield handle
    finally:
        dt = time.perf_counter() - t0
        handle.seconds = dt
        stack.pop()
        if timer is not None:
            timer.attribute(name, dt)
        if on:
            reg = registry or registry_mod.default_registry()
            reg.observe(f"span_seconds/{path}", dt)
            reg.counter(f"span_calls/{path}")


__all__ = ["Span", "current_span", "span", "span_stack"]
