import importlib

from . import losses, metrics

# Submodules are exported lazily BY MODULE (not by re-exported function):
# a module attribute and a function of the same name would shadow each other
# depending on import order (importing the submodule binds it on this
# package, silently replacing a re-exported function). Call sites use
# ops.flash_attention.flash_attention / ops.ring_attention.ring_attention.
_LAZY_SUBMODULES = (
    "flash_attention",
    "ring_attention",
    "pallas_kernels",
    "fused_update",
    "paged_attention",
)

__all__ = ["losses", "metrics", *_LAZY_SUBMODULES]


def __getattr__(name):
    # Lazy: these import jax.experimental.pallas / shard_map machinery not
    # needed by the common CNN paths.
    if name in _LAZY_SUBMODULES:
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
