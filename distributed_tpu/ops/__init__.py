from . import losses, metrics

__all__ = ["losses", "metrics", "flash_attention", "ring_attention"]


def __getattr__(name):
    # Lazy: flash/ring attention import jax.experimental.pallas / shard_map
    # machinery not needed by the common CNN paths.
    if name == "flash_attention":
        from .flash_attention import flash_attention

        return flash_attention
    if name == "ring_attention":
        from .ring_attention import ring_attention

        return ring_attention
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
