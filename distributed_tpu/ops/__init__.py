from . import losses, metrics

__all__ = ["losses", "metrics"]
