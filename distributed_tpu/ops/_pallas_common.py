"""Helpers shared by every Pallas kernel module in ``ops``.

``flash_attention``, ``pallas_kernels`` and ``fused_update`` all need the
same two decisions — *where* a kernel runs (Mosaic on real TPUs,
interpreter everywhere else) and *how* shapes are padded to tile
boundaries. Both used to be copy-pasted per module; this is the single
definition (ring_attention builds on shard_map/ppermute, not pallas_call,
so it has nothing to consolidate here).
"""

from __future__ import annotations

import jax


def interpret() -> bool:
    """True when pallas_call must run in interpreter mode: Mosaic lowering
    exists only for real TPUs; everywhere else (CPU CI, the 8-device sim)
    the interpreter runs the same kernel semantics."""
    return jax.default_backend() != "tpu"


def round_up(v: int, m: int) -> int:
    """Smallest multiple of ``m`` that is >= ``v``."""
    return -(-v // m) * m


# Column/score padding value shared by the attention-family kernels:
# exp(NEG - max) == 0, and NEG is large enough to never be the row max.
NEG = -1e30

# TPU vector-lane width: the last-dim tile size every kernel in this
# package pads or packs to (flash_attention's head packing, fused_update's
# flat segments, paged_attention's head-flattened pools).
LANES = 128


def packed_supported(num_heads: int, head_dim: int) -> bool:
    """True when ``num_heads`` heads of ``head_dim`` columns tile the
    128-lane vector exactly — the precondition for the lane-packed
    attention kernels (several heads share one lane vector, so a python
    per-head loop over lane slices stays a static unrolled body)."""
    return (
        head_dim <= LANES
        and LANES % head_dim == 0
        and num_heads % (LANES // head_dim) == 0
    )
