"""Flash attention: Pallas TPU forward kernel + blockwise backward.

The dense attention path materializes the (B, H, T, T) score tensor in HBM —
at T=8k and 12 heads that is the whole memory budget. This kernel computes
softmax(QK^T)V with the online-softmax recurrence entirely in VMEM: the
grid walks (batch*heads, q_blocks, kv_blocks) with the kv dimension
innermost and sequential, carrying the running max/sum/accumulator in
scratch, so HBM traffic is O(T*D) instead of O(T^2).

The backward pass is a pair of Pallas kernels (dq with the kv dimension
innermost; dk/dv with the q dimension innermost) that recompute the
probabilities in VMEM from the saved per-row statistics (m, l) —
flash-style rematerialization; HBM traffic stays O(T*D) and no (T, T)
matrix ever exists. (The first implementation was a plain-JAX blockwise
scan; on the TPU it ran at ~12% MFU per layer because XLA serialized the
kv-block loop as a while op — the kernels keep the MXU busy instead.)

The reference has no attention anywhere (SURVEY.md §2c); this is part of the
long-context tier the framework adds (with ops.ring_attention for the
sequence-parallel case — ring attention distributes *across chips*, flash
attention blocks *within* a chip; MultiHeadAttention composes them).

CPU/tests run the same kernel via Pallas interpret mode; on TPU it compiles
to Mosaic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from ._pallas_common import (
    LANES as _LANES,
    NEG as _NEG,
    interpret as _interpret,
    packed_supported as _packed_supported,
    round_up as _round_up,
)


# -------------------------------------------------- shared kernel helpers --
def _valid_mask(qi, ki, block_q, block_k, t_actual, causal):
    """(block_q, block_k) mask: real columns, and under causality the
    lower-triangular band for this (qi, ki) block pair. The single source
    of truth for masking across all six kernels (folded + packed)."""
    col = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    valid = col < t_actual
    if causal:
        row = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        valid = jnp.logical_and(valid, col <= row)
    return valid


def _p_ds(q, k, v, do, m, l, delta, valid, scale):
    """Backward-pass block math shared by all dq/dk/dv kernels: recompute
    p from the saved row stats (flash-style), then ds = p*(dO V^T -
    delta)*scale. q/do: (bq, d); k/v: (bk, d); m/l/delta: (bq, 1)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    p = jnp.where(valid, jnp.exp(s - m) / jnp.maximum(l, 1e-30), 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta) * scale
    return p, ds


# ------------------------------------------------------------------ forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_out_ref, l_out_ref,
                m_ref, l_ref, acc_ref,
                *, scale, block_q, block_k, t_actual, causal, nk):
    """One (bh, qi, ki) grid step. Scratch carries the online-softmax state
    across the sequential ki dimension."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Causal: a kv block strictly above the diagonal band contributes
    # nothing — skip its matmuls entirely (the scratch carries through).
    def compute():
        # Keep inputs in their storage dtype for the MXU (bf16 matmul with
        # f32 accumulate); only the softmax recurrence runs in f32.
        q = q_ref[0]  # (block_q, d_pad)
        k = k_ref[0]  # (block_k, d_pad)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k) f32

        valid = _valid_mask(qi, ki, block_q, block_k, t_actual, causal)
        s = jnp.where(valid, s, _NEG)

        m_prev = m_ref[...]  # (block_q, 128), all lanes equal
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # (block_q, 1)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        alpha = jnp.exp(m_prev[:, :1] - m_new[:, :1])  # (block_q, 1)
        p = jnp.exp(s - m_new[:, :1])  # (block_q, block_k)
        l_new = l_prev * alpha + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), l_prev.shape
        )
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_ref[...] = m_new
        l_ref[...] = l_new

    if causal:
        # Not taken only when the whole block is above the diagonal.
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        m_out_ref[0] = m_ref[...]
        l_out_ref[0] = l_ref[...]


def _fwd_pallas(q, k, v, scale, causal, block_q, block_k):
    """q,k,v: (BH, T, D). Returns (out, m_rows, l_rows) with m/l: (BH, T)."""
    bh, t, d = q.shape
    if max(block_q, block_k) % min(block_q, block_k):
        raise ValueError(
            f"block_q={block_q} and block_k={block_k} must divide each "
            "other, or trailing rows would fall outside the grid"
        )
    t_pad = _round_up(t, max(block_q, block_k))
    d_pad = _round_up(max(d, 128), 128)
    pad = lambda x: jnp.pad(
        x, ((0, 0), (0, t_pad - t), (0, d_pad - d))
    )
    qp, kp, vp = pad(q), pad(k), pad(v)
    nq = t_pad // block_q
    nk = t_pad // block_k

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
        t_actual=t, causal=causal, nk=nk,
    )
    out, m_out, l_out = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_pad, d_pad), q.dtype),
            jax.ShapeDtypeStruct((bh, t_pad, 128), jnp.float32),
            jax.ShapeDtypeStruct((bh, t_pad, 128), jnp.float32),
        ],
        scratch_shapes=[
            # m, l, acc live across the sequential ki dimension.
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d_pad), jnp.float32),
        ],
        interpret=_interpret(),
    )(qp, kp, vp)
    # Residual stats are sliced to one value per row: the lane-replicated
    # (bh, t_pad, 128) kernel form is 128x larger and would dominate
    # forward->backward residual memory at long T; the backward
    # re-broadcasts transiently instead.
    return out[:, :t, :d], m_out[:, :t, 0], l_out[:, :t, 0]


# ----------------------------------------------------------------- backward
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, m_ref, l_ref, dl_ref, dq_ref,
               acc_ref, *, scale, block_q, block_k, t_actual, causal, nk):
    """dq for one (bh, qi, ki) grid step; ki sequential, acc in scratch.

    p is recomputed from the saved row statistics (m, l) flash-style —
    never a (T, T) tensor in HBM; ds = p * (dO V^T - delta) * scale;
    dq += ds K."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def compute():
        k = k_ref[0]
        valid = _valid_mask(qi, ki, block_q, block_k, t_actual, causal)
        _, ds = _p_ds(
            q_ref[0], k, v_ref[0], do_ref[0],
            m_ref[0][:, :1], l_ref[0][:, :1], dl_ref[0][:, :1],
            valid, scale,
        )
        acc_ref[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, m_ref, l_ref, dl_ref,
                dk_ref, dv_ref, acc_dk, acc_dv,
                *, scale, block_q, block_k, t_actual, causal, nq):
    """dk/dv for one (bh, ki, qi) grid step; qi sequential, accs in scratch.

    dv += p^T dO; dk += ds^T q — both contractions over the q-block rows."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        acc_dk[...] = jnp.zeros_like(acc_dk)
        acc_dv[...] = jnp.zeros_like(acc_dv)

    def compute():
        q = q_ref[0]
        do = do_ref[0]
        valid = _valid_mask(qi, ki, block_q, block_k, t_actual, causal)
        p, ds = _p_ds(
            q, k_ref[0], v_ref[0], do,
            m_ref[0][:, :1], l_ref[0][:, :1], dl_ref[0][:, :1],
            valid, scale,
        )
        acc_dv[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bk, d)
        acc_dk[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bk, d)

    if causal:
        # Skip q blocks entirely above the diagonal band (no row of this
        # q block can see any column of this kv block).
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            compute()
    else:
        compute()

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = acc_dk[...].astype(dk_ref.dtype)
        dv_ref[0] = acc_dv[...].astype(dv_ref.dtype)


def _bwd_pallas(res, g, *, scale, causal, block_q, block_k):
    """Pallas dq/dk/dv from the saved row stats: two kernels (dq with kv
    innermost; dk/dv with q innermost), each O(T*D) HBM traffic."""
    q, k, v, out, m_rows, l_rows = res  # m/l: (bh, t)
    bh, t, d = q.shape
    t_pad = _round_up(t, max(block_q, block_k))
    d_pad = _round_up(max(d, 128), 128)
    pad = lambda x: jnp.pad(x, ((0, 0), (0, t_pad - t), (0, d_pad - d)))
    qp, kp, vp = pad(q), pad(k), pad(v)
    dop = pad(g.astype(q.dtype))
    nq = t_pad // block_q
    nk = t_pad // block_k

    # delta_i = sum_j dO_ij O_ij; m/l/delta broadcast across lanes into
    # the kernels' (1, block_q, 128) row-stat form (transient buffers —
    # only the (bh, t) stats are held as residuals from the forward).
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # (bh, t)

    def rowstat(x):
        return jnp.broadcast_to(
            jnp.pad(x, ((0, 0), (0, t_pad - t)))[..., None],
            (bh, t_pad, 128),
        )

    m_b, l_b, dl_b = rowstat(m_rows), rowstat(l_rows), rowstat(delta)

    row_spec = pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, block_q=block_q, block_k=block_k,
            t_actual=t, causal=causal, nk=nk,
        ),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            row_spec, row_spec, row_spec,
        ],
        out_specs=pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t_pad, d_pad), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d_pad), jnp.float32)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, m_b, l_b, dl_b)

    row_spec_kv = pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, block_q=block_q, block_k=block_k,
            t_actual=t, causal=causal, nq=nq,
        ),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, j, 0)),
            row_spec_kv, row_spec_kv, row_spec_kv,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_pad, d_pad), k.dtype),
            jax.ShapeDtypeStruct((bh, t_pad, d_pad), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d_pad), jnp.float32),
            pltpu.VMEM((block_k, d_pad), jnp.float32),
        ],
        interpret=_interpret(),
    )(qp, kp, vp, dop, m_b, l_b, dl_b)
    return dq[:, :t, :d], dk[:, :t, :d], dv[:, :t, :d]


# ------------------------------------------------- lane-packed (B,T,H*D) --
# The folded kernels above take (B*H, T, D) and therefore need a
# (B,T,H,D) -> (B,H,T,D) transpose around every call — profiled at
# 25-30% of a GPT-2-small training step on v5e (the transposes run at
# ~150 GB/s and there are ~8 per layer). The kernels below read the
# attention heads straight out of the projection layout (B, T, H*D):
# each 128-lane block holds 128//D whole heads side by side, the grid
# walks (batch, head-block, q-block, kv-block), and the per-head math
# slices lanes in VMEM. No HBM transpose exists in either direction.
# Requires 128 % D == 0 and H % (128//D) == 0 (covers head_dim 64/128);
# other shapes fall back to the folded path.

# Sequence length (padded) above which the packed kernels save their row
# stats compactly ((b, nh, t_pad, heads_per_block)) and re-expand in the
# backward: the lane-replicated form reads fastest under Mosaic but costs
# 128/heads_per_block x the residual memory, which only matters once T is
# long enough for stats to rival the activations themselves.
_COMPACT_STATS_MIN_T = 2048


def _fwd_kernel_packed(q_ref, k_ref, v_ref, o_ref, m_out_ref, l_out_ref,
                       m_ref, l_ref, acc_ref,
                       *, scale, hd, block_q, block_k, t_actual, causal, nk):
    """One (b, hblk, qi, ki) grid step on (1, block, 128) lane-packed tiles;
    the 128 lanes hold 128//hd heads. Scratch m/l keep each head's running
    stat replicated across that head's lane span."""
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def compute():
        q = q_ref[0]  # (block_q, 128)
        k = k_ref[0]  # (block_k, 128)
        v = v_ref[0]
        valid = _valid_mask(qi, ki, block_q, block_k, t_actual, causal)
        for hx in range(_LANES // hd):
            sl = slice(hx * hd, (hx + 1) * hd)
            s = jax.lax.dot_general(
                q[:, sl], k[:, sl], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # (block_q, block_k)
            s = jnp.where(valid, s, _NEG)
            m_prev = m_ref[:, sl]  # (block_q, hd), lanes equal
            l_prev = l_ref[:, sl]
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(
                m_prev, jnp.broadcast_to(m_cur, m_prev.shape)
            )
            alpha = jnp.exp(m_prev[:, :1] - m_new[:, :1])
            p = jnp.exp(s - m_new[:, :1])
            l_new = l_prev * alpha + jnp.broadcast_to(
                jnp.sum(p, axis=-1, keepdims=True), l_prev.shape
            )
            acc_ref[:, sl] = acc_ref[:, sl] * alpha + jnp.dot(
                p.astype(v.dtype), v[:, sl],
                preferred_element_type=jnp.float32,
            )
            m_ref[:, sl] = m_new
            l_ref[:, sl] = l_new

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _finish():
        for hx in range(_LANES // hd):
            sl = slice(hx * hd, (hx + 1) * hd)
            o_ref[0, :, sl] = (
                acc_ref[:, sl]
                / jnp.maximum(l_ref[:, hx * hd : hx * hd + 1], 1e-30)
            ).astype(o_ref.dtype)
        m_out_ref[0, 0] = m_ref[...]
        l_out_ref[0, 0] = l_ref[...]


def _dq_kernel_packed(q_ref, k_ref, v_ref, do_ref, m_ref, l_ref, dl_ref,
                      dq_ref, acc_ref,
                      *, scale, hd, block_q, block_k, t_actual, causal, nk):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        valid = _valid_mask(qi, ki, block_q, block_k, t_actual, causal)
        for hx in range(_LANES // hd):
            sl = slice(hx * hd, (hx + 1) * hd)
            _, ds = _p_ds(
                q[:, sl], k[:, sl], v[:, sl], do[:, sl],
                m_ref[0, 0, :, hx * hd : hx * hd + 1],
                l_ref[0, 0, :, hx * hd : hx * hd + 1],
                dl_ref[0, 0, :, hx * hd : hx * hd + 1],
                valid, scale,
            )
            acc_ref[:, sl] += jax.lax.dot_general(
                ds.astype(k.dtype), k[:, sl], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel_packed(q_ref, k_ref, v_ref, do_ref, m_ref, l_ref, dl_ref,
                       dk_ref, dv_ref, acc_dk, acc_dv,
                       *, scale, hd, block_q, block_k, t_actual, causal, nq):
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        acc_dk[...] = jnp.zeros_like(acc_dk)
        acc_dv[...] = jnp.zeros_like(acc_dv)

    def compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        valid = _valid_mask(qi, ki, block_q, block_k, t_actual, causal)
        for hx in range(_LANES // hd):
            sl = slice(hx * hd, (hx + 1) * hd)
            p, ds = _p_ds(
                q[:, sl], k[:, sl], v[:, sl], do[:, sl],
                m_ref[0, 0, :, hx * hd : hx * hd + 1],
                l_ref[0, 0, :, hx * hd : hx * hd + 1],
                dl_ref[0, 0, :, hx * hd : hx * hd + 1],
                valid, scale,
            )
            acc_dv[:, sl] += jax.lax.dot_general(
                p.astype(do.dtype), do[:, sl], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_dk[:, sl] += jax.lax.dot_general(
                ds.astype(q.dtype), q[:, sl], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    if causal:
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            compute()
    else:
        compute()

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = acc_dk[...].astype(dk_ref.dtype)
        dv_ref[0] = acc_dv[...].astype(dv_ref.dtype)


def _fwd_pallas_packed(qf, kf, vf, h, d, scale, causal, block_q, block_k):
    """qf,kf,vf: (B, T, H*D) lane-packed. Returns (out, m, l) with out in
    the same layout and m/l: (B, H//hpb, t_pad, 128)."""
    b, t, _ = qf.shape
    t_pad = _round_up(t, max(block_q, block_k))
    pad = lambda x: jnp.pad(x, ((0, 0), (0, t_pad - t), (0, 0)))
    qp, kp, vp = pad(qf), pad(kf), pad(vf)
    hpb = _LANES // d
    nh = h // hpb
    nq = t_pad // block_q
    nk = t_pad // block_k

    kernel = functools.partial(
        _fwd_kernel_packed, scale=scale, hd=d, block_q=block_q,
        block_k=block_k, t_actual=t, causal=causal, nk=nk,
    )
    lane_q = pl.BlockSpec((1, block_q, _LANES), lambda b, h, i, j: (b, i, h))
    lane_k = pl.BlockSpec((1, block_k, _LANES), lambda b, h, i, j: (b, j, h))
    stat = pl.BlockSpec((1, 1, block_q, _LANES),
                        lambda b, h, i, j: (b, h, i, 0))
    out, m_out, l_out = pl.pallas_call(
        kernel,
        grid=(b, nh, nq, nk),
        in_specs=[lane_q, lane_k, lane_k],
        out_specs=[lane_q, stat, stat],
        out_shape=[
            jax.ShapeDtypeStruct((b, t_pad, h * d), qf.dtype),
            jax.ShapeDtypeStruct((b, nh, t_pad, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((b, nh, t_pad, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(qp, kp, vp)
    if t_pad >= _COMPACT_STATS_MIN_T:
        # Long context: slice to one value per head per row (the 128-lane
        # block holds 128//d heads, each replicated over its d-lane span)
        # so the residual is 1/d the size; the backward re-expands.
        return out[:, :t], m_out[..., ::d], l_out[..., ::d]
    return out[:, :t], m_out, l_out


def _bwd_pallas_packed(h, d, causal, block_q, block_k, res, g):
    qf, kf, vf, out, m_rows, l_rows = res  # m/l: (b, nh, t_pad)
    b, t, _ = qf.shape
    scale = 1.0 / np.sqrt(d)
    t_pad = _round_up(t, max(block_q, block_k))
    pad = lambda x: jnp.pad(x, ((0, 0), (0, t_pad - t), (0, 0)))
    qp, kp, vp = pad(qf), pad(kf), pad(vf)
    dop = pad(g.astype(qf.dtype))
    hpb = _LANES // d
    nh = h // hpb
    nq = t_pad // block_q
    nk = t_pad // block_k

    # Short-T residuals arrive lane-replicated (fastest Mosaic reads);
    # long-T residuals arrive compact and are re-expanded transiently.
    if m_rows.shape[-1] == _LANES:
        m_out, l_out = m_rows, l_rows
    else:
        m_out = jnp.repeat(m_rows, d, axis=-1)  # (b, nh, t_pad, 128)
        l_out = jnp.repeat(l_rows, d, axis=-1)

    # delta per (b, t, head) -> the (b, nh, t_pad, 128) stat layout with
    # each head's value replicated across its lane span.
    gf = g.astype(jnp.float32).reshape(b, t, h, d)
    of = out.astype(jnp.float32).reshape(b, t, h, d)
    delta = jnp.sum(gf * of, axis=-1)  # (b, t, h)
    delta = jnp.repeat(
        delta.reshape(b, t, nh, hpb), d, axis=-1
    )  # (b, t, nh, 128)
    delta = jnp.moveaxis(delta, 2, 1)  # (b, nh, t, 128) — small tensor
    delta = jnp.pad(delta, ((0, 0), (0, 0), (0, t_pad - t), (0, 0)))

    lane_q = pl.BlockSpec((1, block_q, _LANES), lambda b, h, i, j: (b, i, h))
    lane_k = pl.BlockSpec((1, block_k, _LANES), lambda b, h, i, j: (b, j, h))
    stat_q = pl.BlockSpec((1, 1, block_q, _LANES),
                          lambda b, h, i, j: (b, h, i, 0))
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel_packed, scale=scale, hd=d, block_q=block_q,
            block_k=block_k, t_actual=t, causal=causal, nk=nk,
        ),
        grid=(b, nh, nq, nk),
        in_specs=[lane_q, lane_k, lane_k, lane_q, stat_q, stat_q, stat_q],
        out_specs=lane_q,
        out_shape=jax.ShapeDtypeStruct((b, t_pad, h * d), qf.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, _LANES), jnp.float32)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, m_out, l_out, delta)

    lane_q_kv = pl.BlockSpec((1, block_q, _LANES),
                             lambda b, h, i, j: (b, j, h))
    lane_k_kv = pl.BlockSpec((1, block_k, _LANES),
                             lambda b, h, i, j: (b, i, h))
    stat_kv = pl.BlockSpec((1, 1, block_q, _LANES),
                           lambda b, h, i, j: (b, h, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel_packed, scale=scale, hd=d, block_q=block_q,
            block_k=block_k, t_actual=t, causal=causal, nq=nq,
        ),
        grid=(b, nh, nk, nq),
        in_specs=[lane_q_kv, lane_k_kv, lane_k_kv, lane_q_kv,
                  stat_kv, stat_kv, stat_kv],
        out_specs=[lane_k_kv, lane_k_kv],
        out_shape=[
            jax.ShapeDtypeStruct((b, t_pad, h * d), kf.dtype),
            jax.ShapeDtypeStruct((b, t_pad, h * d), vf.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, _LANES), jnp.float32),
            pltpu.VMEM((block_k, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(qp, kp, vp, dop, m_out, l_out, delta)
    return dq[:, :t], dk[:, :t], dv[:, :t]


def _make_packed(h, d, causal, block_q, block_k):
    """custom_vjp fn over (B, T, H*D) arrays for this static config."""

    @jax.custom_vjp
    def packed(qf, kf, vf):
        scale = 1.0 / np.sqrt(d)
        out, _, _ = _fwd_pallas_packed(
            qf, kf, vf, h, d, scale, causal, block_q, block_k
        )
        return out

    def fwd(qf, kf, vf):
        scale = 1.0 / np.sqrt(d)
        out, m_out, l_out = _fwd_pallas_packed(
            qf, kf, vf, h, d, scale, causal, block_q, block_k
        )
        return out, (qf, kf, vf, out, m_out, l_out)

    packed.defvjp(fwd, functools.partial(
        _bwd_pallas_packed, h, d, causal, block_q, block_k
    ))
    return packed


@functools.lru_cache(maxsize=64)
def _packed_cached(h, d, causal, block_q, block_k):
    return _make_packed(h, d, causal, block_q, block_k)


# -------------------------------------------------------------------- public
@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5)
)
def _flash(q, k, v, causal, block_q, block_k):
    scale = 1.0 / np.sqrt(q.shape[-1])
    out, _, _ = _fwd_pallas(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k):
    scale = 1.0 / np.sqrt(q.shape[-1])
    out, m_rows, l_rows = _fwd_pallas(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, m_rows, l_rows)


def _flash_bwd(causal, block_q, block_k, res, g):
    scale = 1.0 / np.sqrt(res[0].shape[-1])
    return _bwd_pallas(res, g, scale=scale, causal=causal,
                       block_q=block_q, block_k=block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


_warned_backend = False


def dense_attention(q, k, v, causal: bool):
    """Stock-XLA attention over (B, T, H, D) tensors — THE dense softmax
    path, shared by MultiHeadAttention's short-T branch, the Ulysses
    non-flash branch, and the no-Mosaic backend fallback below, so mask/
    scale/dtype policy lives in exactly one place."""
    hd = q.shape[-1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.float32(hd))
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, jnp.float32(-1e30))
    a = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", a, v)


def _dense_fallback(q, k, v, causal):
    """Backends with no Mosaic lowering: Pallas interpret mode inside jit is
    orders of magnitude slower than the dense einsums, so non-TPU
    accelerators (GPU) take the dense path with a warning (CPU keeps
    interpret mode — that's the test configuration)."""
    return dense_attention(q, k, v, causal)


def flash_attention(
    q, k, v, *, causal: bool = False,
    block_q: Optional[int] = None, block_k: int = 1024,
):
    """softmax(Q K^T / sqrt(d)) V without materializing the (T, T) scores.

    q, k, v: (B, T, H, D) — same layout MultiHeadAttention produces.
    Returns (B, T, H, D) in q's dtype. Scores/softmax compute in float32.
    On backends with neither a Mosaic lowering nor a test rationale for
    interpret mode (anything but TPU/CPU), falls back to dense XLA attention
    with a one-time warning.

    ``block_q=None`` (default) resolves to the swept 1024, scoped-VMEM-
    clamped to 512 for float32 inputs (any length) and for bf16 above
    T=2048 (see the comment at the clamp). An EXPLICIT block_q is honored
    as passed — sweeps on chips with different VMEM budgets must measure
    what they ask for.
    """
    backend = jax.default_backend()
    if backend not in ("tpu", "cpu"):
        global _warned_backend
        if not _warned_backend:
            from ..utils import logging as dlog

            dlog.warning(
                f"flash_attention: no Mosaic lowering on backend "
                f"{backend!r}; using dense XLA attention"
            )
            _warned_backend = True
        return _dense_fallback(q, k, v, causal)
    b, t, h, d = q.shape
    rt = _round_up(t, 8)
    if block_q is None:
        # Swept default with scoped-VMEM clamps (16MB limit on v5e):
        # - float32 inputs double every resident block (measured compile
        #   failure at T>=2048 with 1024);
        # - bf16 at long sequence: the full-model BACKWARD kernel's stack
        #   (dq/dk/dv blocks + f32 stat rows spanning T) measured over the
        #   limit at T=4096 with bq=1024. T=2048 compiles in-model and is
        #   ~25% faster with 1024 (confirmed twice), so the bf16 clamp
        #   starts strictly above it; (2048, 4096) is clamped — bq=512
        #   still beats the old 256 default by ~11% at T=4096
        #   (docs/PERF.md round-4 sweep).
        block_q = 1024
        if jnp.dtype(q.dtype).itemsize >= 4 or rt > 2048:
            block_q = 512
    bq = min(block_q, rt)
    # Clamp block_k to the q-rounded sequence length: t_pad is a multiple of
    # max(bq, bk), so an unclamped default (1024) would pad mid-size
    # sequences (e.g. T=600) up to 2x. With bk <= round_up(t, bq) the padded
    # work is bounded by one q-block: t_pad <= t + bq.
    bk = min(block_k, _round_up(t, bq))
    if max(bq, bk) % min(bq, bk):  # clamping broke divisibility
        bq = bk = min(bq, bk)
    if _packed_supported(h, d):
        # Lane-packed path: kernels read heads straight from the (B, T,
        # H*D) projection layout — the reshape is free, no transposes.
        packed = _packed_cached(h, d, causal, bq, bk)
        return packed(
            q.reshape(b, t, h * d), k.reshape(b, t, h * d),
            v.reshape(b, t, h * d),
        ).reshape(b, t, h, d)
    fold = lambda x: jnp.moveaxis(x, 2, 1).reshape(b * h, t, d)
    out = _flash(fold(q), fold(k), fold(v), causal, bq, bk)
    return jnp.moveaxis(out.reshape(b, h, t, d), 1, 2)
