"""Flash attention: Pallas TPU forward kernel + blockwise backward.

The dense attention path materializes the (B, H, T, T) score tensor in HBM —
at T=8k and 12 heads that is the whole memory budget. This kernel computes
softmax(QK^T)V with the online-softmax recurrence entirely in VMEM: the
grid walks (batch*heads, q_blocks, kv_blocks) with the kv dimension
innermost and sequential, carrying the running max/sum/accumulator in
scratch, so HBM traffic is O(T*D) instead of O(T^2).

The backward pass recomputes probabilities blockwise in plain JAX from the
saved per-row statistics (m, l) — flash-style rematerialization; one scan
over kv blocks yields dq/dk/dv without ever holding a full (T, T) matrix.
XLA maps each block's matmuls onto the MXU, which is where all the FLOPs
are; the Pallas win in the forward is fusing the softmax recurrence into
the matmul stream.

The reference has no attention anywhere (SURVEY.md §2c); this is part of the
long-context tier the framework adds (with ops.ring_attention for the
sequence-parallel case — ring attention distributes *across chips*, flash
attention blocks *within* a chip; MultiHeadAttention composes them).

CPU/tests run the same kernel via Pallas interpret mode; on TPU it compiles
to Mosaic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


_NEG = -1e30


# ------------------------------------------------------------------ forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_out_ref, l_out_ref,
                m_ref, l_ref, acc_ref,
                *, scale, block_q, block_k, t_actual, causal, nk):
    """One (bh, qi, ki) grid step. Scratch carries the online-softmax state
    across the sequential ki dimension."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Causal: a kv block strictly above the diagonal band contributes
    # nothing — skip its matmuls entirely (the scratch carries through).
    def compute():
        # Keep inputs in their storage dtype for the MXU (bf16 matmul with
        # f32 accumulate); only the softmax recurrence runs in f32.
        q = q_ref[0]  # (block_q, d_pad)
        k = k_ref[0]  # (block_k, d_pad)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k) f32

        col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = col < t_actual
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            valid = jnp.logical_and(valid, col <= row)
        s = jnp.where(valid, s, _NEG)

        m_prev = m_ref[...]  # (block_q, 128), all lanes equal
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # (block_q, 1)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        alpha = jnp.exp(m_prev[:, :1] - m_new[:, :1])  # (block_q, 1)
        p = jnp.exp(s - m_new[:, :1])  # (block_q, block_k)
        l_new = l_prev * alpha + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), l_prev.shape
        )
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_ref[...] = m_new
        l_ref[...] = l_new

    if causal:
        # Not taken only when the whole block is above the diagonal.
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        m_out_ref[0] = m_ref[...]
        l_out_ref[0] = l_ref[...]


def _fwd_pallas(q, k, v, scale, causal, block_q, block_k):
    """q,k,v: (BH, T, D). Returns (out, m_rows, l_rows) with m/l: (BH, T)."""
    bh, t, d = q.shape
    if max(block_q, block_k) % min(block_q, block_k):
        raise ValueError(
            f"block_q={block_q} and block_k={block_k} must divide each "
            "other, or trailing rows would fall outside the grid"
        )
    t_pad = _round_up(t, max(block_q, block_k))
    d_pad = _round_up(max(d, 128), 128)
    pad = lambda x: jnp.pad(
        x, ((0, 0), (0, t_pad - t), (0, d_pad - d))
    )
    qp, kp, vp = pad(q), pad(k), pad(v)
    nq = t_pad // block_q
    nk = t_pad // block_k

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
        t_actual=t, causal=causal, nk=nk,
    )
    out, m_out, l_out = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_pad, d_pad), q.dtype),
            jax.ShapeDtypeStruct((bh, t_pad, 128), jnp.float32),
            jax.ShapeDtypeStruct((bh, t_pad, 128), jnp.float32),
        ],
        scratch_shapes=[
            # m, l, acc live across the sequential ki dimension.
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d_pad), jnp.float32),
        ],
        interpret=_interpret(),
    )(qp, kp, vp)
    return out[:, :t, :d], m_out[:, :t, 0], l_out[:, :t, 0]


# ----------------------------------------------------------------- backward
def _bwd_blockwise(res, g, *, scale, causal, block_k):
    """Blockwise dq/dk/dv from saved row stats. One scan over kv blocks;
    peak extra memory is (T, block_k) per step instead of (T, T)."""
    q, k, v, out, m_rows, l_rows = res
    bh, t, d = q.shape
    t_pad = _round_up(t, block_k)
    nk = t_pad // block_k
    kp = jnp.pad(k, ((0, 0), (0, t_pad - t), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, t_pad - t), (0, 0)))

    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    # D_i = sum_j dO_ij * O_ij  (rowwise)
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)  # (bh, t)
    m_b = m_rows[..., None]  # (bh, t, 1)
    l_b = jnp.maximum(l_rows[..., None], 1e-30)

    row_ids = jnp.arange(t)[None, :, None]  # (1, t, 1)

    def step(dq_acc, j):
        kj = jax.lax.dynamic_slice_in_dim(kp, j * block_k, block_k, axis=1)
        vj = jax.lax.dynamic_slice_in_dim(vp, j * block_k, block_k, axis=1)
        kjf = kj.astype(jnp.float32)
        vjf = vj.astype(jnp.float32)
        s = jnp.einsum(
            "btd,bkd->btk", qf, kjf, preferred_element_type=jnp.float32
        ) * scale
        col_ids = j * block_k + jnp.arange(block_k)[None, None, :]
        valid = col_ids < t
        if causal:
            valid = jnp.logical_and(valid, col_ids <= row_ids)
        p = jnp.where(valid, jnp.exp(s - m_b) / l_b, 0.0)  # (bh, t, bk)
        dv_j = jnp.einsum("btk,btd->bkd", p, gf)
        dp = jnp.einsum("btd,bkd->btk", gf, vjf)
        ds = p * (dp - delta[..., None]) * scale
        dk_j = jnp.einsum("btk,btd->bkd", ds, qf)
        dq_acc = dq_acc + jnp.einsum("btk,bkd->btd", ds, kjf)
        return dq_acc, (dk_j, dv_j)

    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        step, jnp.zeros_like(qf), jnp.arange(nk)
    )
    dk = jnp.moveaxis(dk_blocks, 0, 1).reshape(bh, t_pad, d)[:, :t]
    dv = jnp.moveaxis(dv_blocks, 0, 1).reshape(bh, t_pad, d)[:, :t]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# -------------------------------------------------------------------- public
@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5)
)
def _flash(q, k, v, causal, block_q, block_k):
    scale = 1.0 / np.sqrt(q.shape[-1])
    out, _, _ = _fwd_pallas(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k):
    scale = 1.0 / np.sqrt(q.shape[-1])
    out, m_rows, l_rows = _fwd_pallas(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, m_rows, l_rows)


def _flash_bwd(causal, block_q, block_k, res, g):
    scale = 1.0 / np.sqrt(res[0].shape[-1])
    return _bwd_blockwise(res, g, scale=scale, causal=causal,
                          block_k=block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


_warned_backend = False


def dense_attention(q, k, v, causal: bool):
    """Stock-XLA attention over (B, T, H, D) tensors — THE dense softmax
    path, shared by MultiHeadAttention's short-T branch, the Ulysses
    non-flash branch, and the no-Mosaic backend fallback below, so mask/
    scale/dtype policy lives in exactly one place."""
    hd = q.shape[-1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.float32(hd))
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, jnp.float32(-1e30))
    a = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", a, v)


def _dense_fallback(q, k, v, causal):
    """Backends with no Mosaic lowering: Pallas interpret mode inside jit is
    orders of magnitude slower than the dense einsums, so non-TPU
    accelerators (GPU) take the dense path with a warning (CPU keeps
    interpret mode — that's the test configuration)."""
    return dense_attention(q, k, v, causal)


def flash_attention(
    q, k, v, *, causal: bool = False,
    block_q: int = 256, block_k: int = 512,
):
    """softmax(Q K^T / sqrt(d)) V without materializing the (T, T) scores.

    q, k, v: (B, T, H, D) — same layout MultiHeadAttention produces.
    Returns (B, T, H, D) in q's dtype. Scores/softmax compute in float32.
    On backends with neither a Mosaic lowering nor a test rationale for
    interpret mode (anything but TPU/CPU), falls back to dense XLA attention
    with a one-time warning.
    """
    backend = jax.default_backend()
    if backend not in ("tpu", "cpu"):
        global _warned_backend
        if not _warned_backend:
            from ..utils import logging as dlog

            dlog.warning(
                f"flash_attention: no Mosaic lowering on backend "
                f"{backend!r}; using dense XLA attention"
            )
            _warned_backend = True
        return _dense_fallback(q, k, v, causal)
    b, t, h, d = q.shape
    fold = lambda x: jnp.moveaxis(x, 2, 1).reshape(b * h, t, d)
    rt = _round_up(t, 8)
    bq = min(block_q, rt)
    bk = min(block_k, rt)
    if max(bq, bk) % min(bq, bk):  # clamping broke divisibility
        bq = bk = min(bq, bk)
    out = _flash(fold(q), fold(k), fold(v), causal, bq, bk)
    return jnp.moveaxis(out.reshape(b, h, t, d), 1, 2)
