"""Fused Adam/AdamW optimizer update as one Pallas pass over flat segments.

The stock optax update walks the parameter TREE: for every leaf it emits
the ~10-op elementwise chain (two moment EMAs, two bias corrections, the
rsqrt-normalized step, the -lr scale; AdamW adds the decay term). On a
model with hundreds of leaves that is hundreds of small kernels per
optimizer step — each paying launch overhead and reading/writing its
operands through HBM separately, which is exactly the per-step cost the
``bench.py fused_update`` artifact measures.

This module factors the update the other way: the leaves of the master
tree are raveled and concatenated into one flat buffer per dtype (the
"same-dtype segments"), padded to the TPU lane tile, and a SINGLE Pallas
kernel per segment performs the whole Adam recurrence — moment update,
bias correction, and the parameter-step computation — in one pass through
VMEM: every element of g/m/v is read once, every element of m'/v'/delta
written once. The per-leaf views are then sliced back out (XLA fuses the
slices into the consumers). The arithmetic is kept OPERATION-FOR-OPERATION
identical to ``optax.scale_by_adam`` + ``add_decayed_weights`` + ``scale``
so the fused path is bit-comparable to stock optax on the same backend
(tests/test_fused_update.py pins 10-step trajectories under SingleDevice/
DP/ZeRO-1/FSDP).

Optax compatibility: :func:`fused_adam` / :func:`fused_adamw` are ordinary
``GradientTransformation`` factories — ``update`` returns the DELTA tree
and ``optax.apply_updates`` adds it, so they drop into ``Model.compile``,
``Strategy.init_opt_state`` (the ``FusedAdamState`` moments are a plain
pytree, so ZeRO-1/FSDP shard them leaf-for-leaf like stock Adam state) and
``Strategy.constrain_step`` unchanged. The public constructors in
``distributed_tpu.optim`` wrap them in ``optax.inject_hyperparams`` so the
learning rate lives in the state and ``set_learning_rate`` keeps working.

Sharded strategies: GSPMD cannot partition a Pallas custom call, so on a
mesh the kernel computes the segment REPLICATED on every device — which
for a data-parallel optimizer update is the stock placement anyway (every
DP replica computes the full update), and what keeps the step's output
layouts stable: a sharded-kernel constraint here was measured to leak
row-sharding into the updated params under plain DataParallel, whose
constrain_step pins nothing (see _segment_update). Under ZeRO/FSDP the
segment concat gathers the sharded leaves transiently and constrain_step
re-pins the outputs; those strategies get the fused arithmetic, not a
comms win — docs/PERF.md is explicit.

CPU/tests run the kernel via Pallas interpret mode (same semantics); on
TPU it compiles to Mosaic.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental import pallas as pl

from ._pallas_common import (
    LANES as _LANES,
    interpret as _interpret,
    round_up as _round_up,
)

# Rows (of 128 lanes) per grid block: 256*128 f32 = 128 KiB per operand;
# the kernel holds 5 inputs + 3 outputs + temporaries, comfortably inside
# the ~16 MB VMEM budget.
_BLOCK_ROWS = 256


class FusedAdamState(NamedTuple):
    """State of the fused Adam family: the step count and the first/second
    moment trees. Same content as ``optax.ScaleByAdamState`` — a NamedTuple
    pytree, so it shards/replicates under the usual NamedSharding rules,
    checkpoints leaf-for-leaf, and ``Strategy.constrain_step`` pins it
    exactly like stock Adam state."""

    count: Any
    mu: Any
    nu: Any


def _adam_kernel(hyper_ref, p_ref, g_ref, m_ref, v_ref,
                 u_ref, m_out_ref, v_out_ref):
    """One (block_rows, 128) tile of the fused update. ``hyper`` carries
    the traced scalars [lr, b1, b2, eps, wd, c1, c2, 0] where c1/c2 are
    the bias-correction denominators ``1 - b**count`` (computed outside so
    the count stays a scalar). The arithmetic mirrors optax exactly:

        m' = (1-b1)*g + b1*m            (tree_update_moment, order 1)
        v' = (1-b2)*g^2 + b2*v          (tree_update_moment_per_elem_norm)
        u  = -lr * ((m'/c1) / (sqrt(v'/c2) + eps) + wd*p)

    wd = 0 recovers plain Adam (optax.adam); wd > 0 is AdamW's decoupled
    decay (add_decayed_weights before the -lr scale)."""
    lr = hyper_ref[0, 0]
    b1 = hyper_ref[0, 1]
    b2 = hyper_ref[0, 2]
    eps = hyper_ref[0, 3]
    wd = hyper_ref[0, 4]
    c1 = hyper_ref[0, 5]
    c2 = hyper_ref[0, 6]
    g = g_ref[...]
    m = m_ref[...]
    v = v_ref[...]
    m_new = (1.0 - b1) * g + b1 * m
    v_new = (1.0 - b2) * (g * g) + b2 * v
    m_hat = m_new / c1
    v_hat = v_new / c2
    u = m_hat / (jnp.sqrt(v_hat) + eps)
    u = u + wd * p_ref[...]
    u_ref[...] = (-lr) * u
    m_out_ref[...] = m_new
    v_out_ref[...] = v_new


def _segment_update(hyper, flat_p, flat_g, flat_m, flat_v):
    """Run the fused kernel over one flat (n,) f32 segment, padded to
    whole (block, 128) tiles. Returns (delta, m', v') flat (n,).

    Deliberately NOT routed through shard_map (unlike the fused-xent /
    flash kernels): under a mesh GSPMD replicates the custom call, which
    for the OPTIMIZER is the right placement — data-parallel updates are
    computed replicated on every device by definition (stock optax pays
    the same), and a row-sharding constraint here was measured to LEAK
    through GSPMD propagation into the updated params under plain
    DataParallel (whose constrain_step is the identity), silently turning
    replicated params into row-sharded ones from step 1. ZeRO/FSDP re-pin
    their own layouts in constrain_step; their sharded-update compute is
    a future lever (the segment concat regroups their layouts anyway —
    see the module docstring)."""
    n = flat_p.shape[0]
    rows = _round_up(max(n, 1), _LANES) // _LANES
    bm = min(_BLOCK_ROWS, _round_up(rows, 8))
    rows = _round_up(rows, bm)
    total = rows * _LANES

    def pad2d(a):
        return jnp.pad(a, (0, total - n)).reshape(rows, _LANES)

    p2, g2, m2, v2 = pad2d(flat_p), pad2d(flat_g), pad2d(flat_m), pad2d(flat_v)
    shape = jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)
    u2, m2n, v2n = pl.pallas_call(
        _adam_kernel,
        grid=(rows // bm,),
        in_specs=[
            pl.BlockSpec((1, 8), lambda i: (0, 0)),
        ] + [pl.BlockSpec((bm, _LANES), lambda i: (i, 0))] * 4,
        out_specs=[pl.BlockSpec((bm, _LANES), lambda i: (i, 0))] * 3,
        out_shape=[shape, shape, shape],
        interpret=_interpret(),
    )(hyper, p2, g2, m2, v2)
    return (
        u2.reshape(-1)[:n],
        m2n.reshape(-1)[:n],
        v2n.reshape(-1)[:n],
    )


def _is_float(leaf) -> bool:
    return jnp.issubdtype(jnp.result_type(leaf), jnp.floating)


def fused_adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0):
    """Fused-kernel Adam/AdamW as an optax ``GradientTransformation``.

    Use through ``distributed_tpu.optim.fused_adam(...)`` (which adds the
    ``inject_hyperparams`` wrapper so the learning rate is runtime-mutable
    and checkpointable); this factory is the raw transform. ``update``
    returns the parameter DELTAS (optax contract — ``apply_updates`` adds
    them, and XLA fuses that add into the surrounding jitted step), with
    the moment update + bias correction + step computation performed by
    one Pallas kernel per same-dtype flat segment of the tree.

    Non-float32 floating leaves are updated in f32 inside the kernel and
    cast back (the framework's masters are f32, where the path is exact
    vs stock optax); integer leaves pass through with zero updates."""

    def init_fn(params):
        def zeros(p):
            return jnp.zeros_like(p)

        return FusedAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree_util.tree_map(zeros, params),
            nu=jax.tree_util.tree_map(zeros, params),
        )

    def update_fn(grads, state, params=None):
        if params is None:
            # The params are a kernel operand (AdamW's decay term); plain
            # Adam (wd == 0) gets a zeros stand-in so callers following
            # the optax "params optional" convention still work.
            params = jax.tree_util.tree_map(jnp.zeros_like, grads)
        count_inc = optax.safe_int32_increment(state.count)
        b1_ = jnp.asarray(b1, jnp.float32)
        b2_ = jnp.asarray(b2, jnp.float32)
        c1 = 1.0 - b1_ ** count_inc
        c2 = 1.0 - b2_ ** count_inc
        hyper = jnp.stack([
            jnp.asarray(learning_rate, jnp.float32),
            b1_, b2_,
            jnp.asarray(eps, jnp.float32),
            jnp.asarray(weight_decay, jnp.float32),
            c1, c2,
            jnp.float32(0.0),
        ]).reshape(1, 8)

        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        p_leaves = treedef.flatten_up_to(params)
        m_leaves = treedef.flatten_up_to(state.mu)
        v_leaves = treedef.flatten_up_to(state.nu)

        # Same-dtype segments: group the floating leaves by dtype so each
        # group concatenates into ONE flat buffer and pays one kernel.
        groups: dict = {}
        for i, g in enumerate(g_leaves):
            if _is_float(g):
                groups.setdefault(jnp.result_type(g), []).append(i)

        u_leaves = [None] * len(g_leaves)
        new_m = list(m_leaves)
        new_v = list(v_leaves)
        for dt, idxs in groups.items():
            sizes = [int(np.prod(g_leaves[i].shape)) for i in idxs]
            offs = np.concatenate([[0], np.cumsum(sizes)]).tolist()

            def flat(leaves):
                return jnp.concatenate([
                    leaves[i].reshape(-1).astype(jnp.float32) for i in idxs
                ]) if idxs else jnp.zeros((0,), jnp.float32)

            du, dm, dv = _segment_update(
                hyper, flat(p_leaves), flat(g_leaves), flat(m_leaves),
                flat(v_leaves),
            )
            for k, i in enumerate(idxs):
                sl = slice(offs[k], offs[k + 1])
                shape = g_leaves[i].shape
                u_leaves[i] = du[sl].reshape(shape).astype(dt)
                new_m[i] = dm[sl].reshape(shape).astype(dt)
                new_v[i] = dv[sl].reshape(shape).astype(dt)
        for i, g in enumerate(g_leaves):
            if u_leaves[i] is None:  # integer leaf: no update
                u_leaves[i] = jnp.zeros_like(g)

        updates = jax.tree_util.tree_unflatten(treedef, u_leaves)
        new_state = FusedAdamState(
            count=count_inc,
            mu=jax.tree_util.tree_unflatten(treedef, new_m),
            nu=jax.tree_util.tree_unflatten(treedef, new_v),
        )
        return updates, new_state

    return optax.GradientTransformation(init_fn, update_fn)


def fused_adamw(learning_rate, b1=0.9, b2=0.999, eps=1e-8,
                weight_decay=0.01):
    """AdamW spelling of :func:`fused_adam` (decoupled weight decay folded
    into the same single kernel pass)."""
    return fused_adam(
        learning_rate, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay
    )


__all__ = ["FusedAdamState", "fused_adam", "fused_adamw"]
