"""Loss functions.

Parity target: ``SparseCategoricalCrossentropy(from_logits=TRUE)``
(/root/reference/README.md:70-73, 300-302). All losses reduce with a plain
``jnp.mean`` so that, under a sharded batch inside a jitted step, XLA emits the
cross-replica reduction itself — the TPU equivalent of the reference's metric
all-reduces (/root/reference/README.md:404-407).

Losses compute in float32 regardless of activation dtype (bf16-safe).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sparse_categorical_crossentropy(logits, labels, from_logits: bool = True):
    """Mean cross-entropy for integer labels. logits: (..., C), labels: (...)."""
    logits = logits.astype(jnp.float32)
    if not from_logits:
        logits = jnp.log(jnp.clip(logits, 1e-9, 1.0))
        logp = logits - jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    else:
        logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return -jnp.mean(ll)


def categorical_crossentropy(logits, onehot, from_logits: bool = True):
    logits = logits.astype(jnp.float32)
    if from_logits:
        logp = jax.nn.log_softmax(logits, axis=-1)
    else:
        logp = jnp.log(jnp.clip(logits, 1e-9, 1.0))
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def mean_squared_error(pred, target):
    pred = pred.astype(jnp.float32)
    return jnp.mean(jnp.square(pred - target))


def cross_entropy_with_ignore(logits, labels, ignore_index: int = -100):
    """Token-level CE that masks out ignore_index labels (LM training)."""
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    safe = jnp.where(labels == ignore_index, 0, labels)
    ll = jnp.take_along_axis(logp, safe[..., None].astype(jnp.int32), axis=-1)[..., 0]
    mask = (labels != ignore_index).astype(jnp.float32)
    return -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


class SparseCategoricalCrossentropy:
    """Class-form matching the reference's loss object construction
    (/root/reference/README.md:300: ``SparseCategoricalCrossentropy(from_logits=True)``)."""

    def __init__(self, from_logits: bool = True):
        self.from_logits = from_logits

    def __call__(self, logits, labels):
        return sparse_categorical_crossentropy(logits, labels, self.from_logits)


def _per_example_sparse_cce(logits, labels):
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]


def _per_example_cce(logits, onehot):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.sum(onehot * logp, axis=-1)


def _per_example_mse(pred, target):
    d = jnp.square(pred.astype(jnp.float32) - target)
    return jnp.mean(d.reshape(d.shape[0], -1), axis=-1)


_REGISTRY = {
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "categorical_crossentropy": categorical_crossentropy,
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
}

# Per-example forms, used for exact masked evaluation on padded final batches.
_PER_EXAMPLE = {
    sparse_categorical_crossentropy: _per_example_sparse_cce,
    categorical_crossentropy: _per_example_cce,
    mean_squared_error: _per_example_mse,
}


def get_per_example(loss_fn):
    """Per-example variant of a known loss, or None for custom callables
    (callers then fall back to whole-batch mean weighted by valid count)."""
    if isinstance(loss_fn, SparseCategoricalCrossentropy):
        if loss_fn.from_logits:
            return _per_example_sparse_cce
        return lambda logits, labels: _per_example_sparse_cce(
            jnp.log(jnp.clip(logits, 1e-9, 1.0)), labels
        )
    return _PER_EXAMPLE.get(loss_fn)


def _register_pallas():
    # Lazy: the Pallas kernels import jax.experimental.pallas, which is not
    # needed unless the fused loss is requested.
    from . import pallas_kernels as pk

    _REGISTRY["pallas_sparse_categorical_crossentropy"] = (
        pk.pallas_sparse_categorical_crossentropy
    )
    _PER_EXAMPLE[pk.pallas_sparse_categorical_crossentropy] = (
        pk.per_example_pallas_xent
    )


def get(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    if name_or_fn == "pallas_sparse_categorical_crossentropy":
        _register_pallas()
    try:
        return _REGISTRY[name_or_fn]
    except KeyError:
        raise ValueError(f"Unknown loss {name_or_fn!r}; known: {sorted(_REGISTRY)}") from None
