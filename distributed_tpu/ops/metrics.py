"""Metrics.

Parity target: ``metrics = 'accuracy'`` (/root/reference/README.md:73, 302).

Protocol: a metric maps (logits, labels) -> (sum, count). Epochs aggregate the
two on device and divide once at the epoch boundary — exact under sharded
batches, mirroring the reference's all-reduced running metrics
(/root/reference/README.md:404-407). Known metrics also expose a per-example
form (a (B,) score vector) so padded evaluation batches can be masked exactly.
"""

from __future__ import annotations

import jax.numpy as jnp


def _accuracy_scores(logits, labels):
    pred = jnp.argmax(logits, axis=-1)
    return (pred == labels.astype(pred.dtype)).astype(jnp.float32)


def accuracy(logits, labels):
    scores = _accuracy_scores(logits, labels)
    return jnp.sum(scores), jnp.float32(scores.size)


def _top_k_scores(k):
    def scores(logits, labels):
        topk = jnp.argsort(logits, axis=-1)[..., -k:]
        hit = jnp.any(topk == labels[..., None].astype(topk.dtype), axis=-1)
        return hit.astype(jnp.float32)

    return scores


def top_k_accuracy(k: int):
    sc = _top_k_scores(k)

    def metric(logits, labels):
        s = sc(logits, labels)
        return jnp.sum(s), jnp.float32(s.size)

    metric.__name__ = f"top_{k}_accuracy"
    metric.per_example = sc
    return metric


accuracy.per_example = _accuracy_scores

_REGISTRY = {
    "accuracy": accuracy,
    "acc": accuracy,
    "top_5_accuracy": top_k_accuracy(5),
}


def get(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    try:
        return _REGISTRY[name_or_fn]
    except KeyError:
        raise ValueError(f"Unknown metric {name_or_fn!r}; known: {sorted(_REGISTRY)}") from None


def per_example(fn):
    """Per-example score vector fn, or None if the metric doesn't expose one."""
    return getattr(fn, "per_example", None)


def name_of(name_or_fn) -> str:
    if isinstance(name_or_fn, str):
        return "accuracy" if name_or_fn == "acc" else name_or_fn
    return getattr(name_or_fn, "__name__", "metric")
