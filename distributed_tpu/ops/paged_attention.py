"""Fused paged-attention decode kernel (vLLM-style PagedAttention).

The serving decode hot loop reads per-slot KV through a block table: the
reference path (``nn.attention.MultiHeadAttention.paged_decode``) first
GATHERS every slot's blocks into a contiguous ``(S, L, H, hd)`` view
(``_paged_view`` — one HBM round-trip for the whole view, L = table
width x block size), then runs dense masked attention over it (a second
pass over the same bytes). This kernel fuses the two: the Pallas grid
walks ``(slot, table_entry)`` with the table dimension innermost and
sequential, the block table rides as a SCALAR-PREFETCH operand so each
grid step's BlockSpec index map picks the pool block to stream into VMEM
(``tables[s, j]`` — the PagedAttention gather, done by the memory system
instead of a materialized gather), and the online-softmax recurrence
(running max / sum / accumulator in VMEM scratch, exactly flash
attention's) folds each block into the context as it arrives. No
``(S, L, H, hd)`` view ever exists.

Covers decode (K=1 query row per slot) and the speculative ``paged_verify``
dispatch (K candidate rows per slot at consecutive positions) with the
same kernel: query row k of slot s attends to absolute positions
``<= positions[s] + k``. Plain f32/bf16 pools and the int8 ``{"q","scale"}``
pools (quant.py idiom) are both handled — int8 payload blocks and their
per-(position, head) scales stream separately and dequantize IN-KERNEL,
per head, in VMEM (the reference path dequantizes the whole gathered view
in HBM first).

The K/V SCATTER of the new rows stays plain XLA in the caller — it is a
tiny ``S`` (or ``S*K``)-row write, not a per-layer L-sized pass; only the
gather + attention read path is worth fusing.

Selection is ambient at trace time (``decode_kernel_scope`` /
``current_decode_kernel``, the same threadlocal idiom as
``parallel.strategy.current_strategy``): ``serving.Engine(decode_kernel=
"fused")`` and ``fleet.EnginePrograms(decode_kernel="fused")`` enter the
scope around their jitted dispatches, so the attention layer picks the
kernel while tracing and the jit cache keys stay per-engine.

CPU/tests run the kernel via Pallas interpret mode (same semantics); on
TPU it compiles to Mosaic. Parity vs the reference path is pinned by
tests/test_paged_kernel.py; the throughput claim is reserved for a real
accelerator (docs/PERF.md "Fused paged attention").
"""

from __future__ import annotations

import contextlib
import functools
import math
import threading

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..quant import QKEY, SKEY
from ._pallas_common import NEG as _NEG, interpret as _interpret

# ------------------------------------------------ kernel selection (ambient)
REFERENCE = "reference"
FUSED = "fused"
KINDS = (REFERENCE, FUSED)

_local = threading.local()


def current_decode_kernel() -> str:
    """The ambient decode-kernel choice ('reference' outside any scope).
    Read at TRACE time by MultiHeadAttention.paged_decode/paged_verify —
    like ``current_strategy``, an ambient-context seam so layer call
    signatures don't grow an engine-plumbing argument."""
    return getattr(_local, "kind", REFERENCE)


@contextlib.contextmanager
def decode_kernel_scope(kind: str):
    """Make ``kind`` ('reference' | 'fused') the ambient decode kernel for
    the duration — wrap the first (tracing) call of a jitted decode/verify
    dispatch so the traced program bakes the chosen kernel in."""
    if kind not in KINDS:
        raise ValueError(
            f"decode_kernel must be one of {KINDS}, got {kind!r}"
        )
    prev = getattr(_local, "kind", REFERENCE)
    _local.kind = kind
    try:
        yield
    finally:
        _local.kind = prev


# ------------------------------------------------------------------ kernels
def _decode_kernel(tables_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, sqrt_hd, bs, h, hd, nb, kw):
    """One (slot s, table entry j) grid step over a PLAIN pool block.

    q_ref (1, kw, h*hd): slot s's kw query rows, heads flattened into the
    lane dim; k_ref/v_ref (1, bs, h*hd): pool block ``tables[s, j]``
    (the scalar-prefetch index map IS the gather). Scratch m/l (kw, h) and
    acc (kw, h*hd) carry the per-head online-softmax state across the
    sequential j dimension; the causal mask compares each block column's
    absolute position ``j*bs + c`` against query row k's own position
    ``pos[s] + k`` (K=1 decode degenerates to ``<= pos[s]``)."""
    s = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    pos = pos_ref[s]
    q = q_ref[0]  # (kw, h*hd)
    k = k_ref[0]  # (bs, h*hd)
    v = v_ref[0]
    col = j * bs + jax.lax.broadcasted_iota(jnp.int32, (kw, bs), 1)
    row = pos + jax.lax.broadcasted_iota(jnp.int32, (kw, bs), 0)
    valid = col <= row
    for hx in range(h):
        sl = slice(hx * hd, (hx + 1) * hd)
        sc = jax.lax.dot_general(
            q[:, sl], k[:, sl], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) / sqrt_hd  # (kw, bs); divide (not scale-multiply) matches the
        # reference path bit-for-bit
        sc = jnp.where(valid, sc, _NEG)
        m_prev = m_ref[:, hx:hx + 1]
        l_prev = l_ref[:, hx:hx + 1]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(sc - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:, sl] = acc_ref[:, sl] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v[:, sl], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:, hx:hx + 1] = m_new
        l_ref[:, hx:hx + 1] = l_new

    @pl.when(j == nb - 1)
    def _finish():
        for hx in range(h):
            sl = slice(hx * hd, (hx + 1) * hd)
            o_ref[0, :, sl] = (
                acc_ref[:, sl]
                / jnp.maximum(l_ref[:, hx:hx + 1], 1e-30)
            ).astype(o_ref.dtype)


def _decode_kernel_quant(tables_ref, pos_ref, q_ref, k_ref, ks_ref,
                         v_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref,
                         *, sqrt_hd, bs, h, hd, nb, kw):
    """int8-pool variant: payload blocks (int8) and their per-(position,
    head) scales (f32, (1, bs, h)) stream as separate operands through the
    same table-indexed BlockSpecs; each head's rows dequantize in VMEM
    (``q * scale`` in f32, rounded once to the query dtype — the same
    single-rounding contract as quant.dequantize) right before its dot."""
    s = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    pos = pos_ref[s]
    q = q_ref[0]   # (kw, h*hd), query dtype
    k = k_ref[0]   # (bs, h*hd), int8
    ks = ks_ref[0]  # (bs, h), f32 scales
    v = v_ref[0]
    vs = vs_ref[0]
    col = j * bs + jax.lax.broadcasted_iota(jnp.int32, (kw, bs), 1)
    row = pos + jax.lax.broadcasted_iota(jnp.int32, (kw, bs), 0)
    valid = col <= row
    for hx in range(h):
        sl = slice(hx * hd, (hx + 1) * hd)
        kh = (
            k[:, sl].astype(jnp.float32) * ks[:, hx:hx + 1]
        ).astype(q.dtype)
        vh = (
            v[:, sl].astype(jnp.float32) * vs[:, hx:hx + 1]
        ).astype(q.dtype)
        sc = jax.lax.dot_general(
            q[:, sl], kh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) / sqrt_hd
        sc = jnp.where(valid, sc, _NEG)
        m_prev = m_ref[:, hx:hx + 1]
        l_prev = l_ref[:, hx:hx + 1]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(sc - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:, sl] = acc_ref[:, sl] * alpha + jax.lax.dot_general(
            p.astype(vh.dtype), vh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:, hx:hx + 1] = m_new
        l_ref[:, hx:hx + 1] = l_new

    @pl.when(j == nb - 1)
    def _finish():
        for hx in range(h):
            sl = slice(hx * hd, (hx + 1) * hd)
            o_ref[0, :, sl] = (
                acc_ref[:, sl]
                / jnp.maximum(l_ref[:, hx:hx + 1], 1e-30)
            ).astype(o_ref.dtype)


# -------------------------------------------------------------- entry point
def paged_attention(q, k_pool, v_pool, block_tables, positions):
    """Fused gather + masked attention over paged KV pools.

    ``q`` (S, K, H, hd): K query rows per slot at consecutive absolute
    positions starting at ``positions[s]`` (K=1 is plain decode, K>1 the
    speculative verify window). ``k_pool``/``v_pool``: a plain
    (num_blocks, bs, H, hd) array or an int8 ``{"q","scale"}`` dict
    (scales (num_blocks, bs, H, 1)). ``block_tables`` (S, NB) int32 maps
    each slot's logical block j to its pool block. Returns the context
    (S, K, H, hd) in ``q.dtype`` — what the reference path's
    ``softmax(q @ view_k / sqrt(hd), causal mask) @ view_v`` computes,
    without materializing the view.
    """
    s, kw, h, hd = q.shape
    quant = isinstance(k_pool, dict)
    kq = k_pool[QKEY] if quant else k_pool
    nblocks, bs = kq.shape[0], kq.shape[1]
    nb = block_tables.shape[1]
    tables = block_tables.astype(jnp.int32)
    pos = positions.astype(jnp.int32)
    q2 = q.reshape(s, kw, h * hd)
    sqrt_hd = float(math.sqrt(hd))

    def q_map(si, j, t, p):
        return (si, 0, 0)

    def pool_map(si, j, t, p):
        return (t[si, j], 0, 0)

    q_spec = pl.BlockSpec((1, kw, h * hd), q_map)
    pool_spec = pl.BlockSpec((1, bs, h * hd), pool_map)
    if quant:
        kernel = _decode_kernel_quant
        scale_spec = pl.BlockSpec((1, bs, h), pool_map)
        in_specs = [q_spec, pool_spec, scale_spec, pool_spec, scale_spec]
        inputs = [
            q2,
            k_pool[QKEY].reshape(nblocks, bs, h * hd),
            k_pool[SKEY].reshape(nblocks, bs, h),
            v_pool[QKEY].reshape(nblocks, bs, h * hd),
            v_pool[SKEY].reshape(nblocks, bs, h),
        ]
    else:
        kernel = _decode_kernel
        in_specs = [q_spec, pool_spec, pool_spec]
        inputs = [
            q2,
            k_pool.reshape(nblocks, bs, h * hd),
            v_pool.reshape(nblocks, bs, h * hd),
        ]

    out = pl.pallas_call(
        functools.partial(
            kernel, sqrt_hd=sqrt_hd, bs=bs, h=h, hd=hd, nb=nb, kw=kw,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(s, nb),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, kw, h * hd), q_map),
            scratch_shapes=[
                pltpu.VMEM((kw, h), jnp.float32),
                pltpu.VMEM((kw, h), jnp.float32),
                pltpu.VMEM((kw, h * hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((s, kw, h * hd), q.dtype),
        interpret=_interpret(),
    )(tables, pos, *inputs)
    return out.reshape(s, kw, h, hd)
