"""Pallas TPU kernels for hot ops.

Currently: fused softmax cross-entropy (forward + backward via custom_vjp).
XLA already fuses the elementwise chain of ``log_softmax + gather`` well at
small class counts, but at large-vocabulary scale (LM heads; fused up to
``MAX_FUSED_CLASSES`` = 64k classes, stock-XLA fallback beyond) the fused
kernel avoids materializing the (N, C) log-probability tensor in HBM: each
block computes max/sum/pick in VMEM and writes only the (N,) losses — HBM
traffic drops from ~3x logits-size to ~1x. The backward
kernel recomputes the softmax from the saved logits (flash-style
rematerialization) instead of storing probabilities.

The reference has nothing comparable in-repo (its compute lives in TF's C++
kernels, SURVEY.md §2b); this is the TPU-native answer for the op tier.

CPU/tests run the same kernels via Pallas interpret mode; on TPU they
compile to Mosaic. Kernels are opt-in: compile with
``loss="pallas_sparse_categorical_crossentropy"`` (registered lazily in
``ops.losses``). Under data parallelism the batch dimension is the grid
dimension, so blocks never span replicas.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._pallas_common import interpret as _interpret, round_up as _round_up


# --------------------------------------------------------------- kernels --
def _xent_fwd_kernel(logits_ref, labels_ref, loss_ref):
    x = logits_ref[...].astype(jnp.float32)          # (bm, c_pad)
    lbl = labels_ref[...][:, 0]                      # (bm,)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    lse = jnp.log(jnp.sum(e, axis=-1)) + m[:, 0]
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    picked = jnp.sum(jnp.where(col == lbl[:, None], x, 0.0), axis=-1)
    loss_ref[...] = (lse - picked)[:, None]


def _xent_bwd_kernel(logits_ref, labels_ref, g_ref, dlogits_ref):
    x = logits_ref[...].astype(jnp.float32)
    lbl = labels_ref[...][:, 0]
    g = g_ref[...][:, 0]
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    p = e / jnp.sum(e, axis=-1, keepdims=True)
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (col == lbl[:, None]).astype(jnp.float32)
    dlogits_ref[...] = ((p - onehot) * g[:, None]).astype(dlogits_ref.dtype)


# --------------------------------------------------------------- wrappers --
# Column padding: exp(NEG - max) == 0, never the row max (shared constant).
from ._pallas_common import NEG as _NEG  # noqa: E402


def _pad_inputs(logits, labels, bm):
    n, c = logits.shape
    n_pad = _round_up(n, bm)
    c_pad = _round_up(max(c, 128), 128)  # TPU lane tile
    lp = jnp.pad(
        logits, ((0, n_pad - n), (0, c_pad - c)), constant_values=_NEG
    )
    yp = jnp.pad(labels.astype(jnp.int32), (0, n_pad - n))[:, None]
    return lp, yp, n_pad, c_pad


def _block_rows(n: int, c_pad: int) -> int:
    # VMEM is ~16MB and the backward kernel holds ~6 block-sized float32
    # temporaries (logits, exp, softmax, onehot, grad-out, spill), so cap
    # the block's logits at 2MB: 6 x 2MB stays under the scoped-vmem limit.
    for bm in (256, 128, 64, 32, 16, 8):
        if bm * c_pad * 4 <= (1 << 21):
            return bm
    return 8


def _check_classes(c: int):
    if c > MAX_FUSED_CLASSES:
        raise ValueError(
            f"fused_softmax_xent supports at most {MAX_FUSED_CLASSES} "
            f"classes (got {c}): a row block would not fit VMEM. Use "
            "losses.sparse_categorical_crossentropy (the registry-level "
            "pallas loss falls back automatically)."
        )


def _xent_forward(logits, labels):
    n, c = logits.shape
    _check_classes(c)
    lp, yp, n_pad, c_pad = _pad_inputs(logits, labels, 8)
    bm = _block_rows(n_pad, c_pad)
    if n_pad % bm:
        bm = 8  # n_pad is a multiple of 8 by construction
    loss = pl.pallas_call(
        _xent_fwd_kernel,
        grid=(n_pad // bm,),
        in_specs=[
            pl.BlockSpec((bm, c_pad), lambda i: (i, 0)),
            pl.BlockSpec((bm, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bm, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        interpret=_interpret(),
    )(lp, yp)
    return loss[:n, 0]


def _xent_backward(logits, labels, g):
    n, c = logits.shape
    lp, yp, n_pad, c_pad = _pad_inputs(logits, labels, 8)
    bm = _block_rows(n_pad, c_pad)
    if n_pad % bm:
        bm = 8
    gp = jnp.pad(g.astype(jnp.float32), (0, n_pad - n))[:, None]
    dl = pl.pallas_call(
        _xent_bwd_kernel,
        grid=(n_pad // bm,),
        in_specs=[
            pl.BlockSpec((bm, c_pad), lambda i: (i, 0)),
            pl.BlockSpec((bm, 1), lambda i: (i, 0)),
            pl.BlockSpec((bm, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bm, c_pad), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, c_pad), logits.dtype),
        interpret=_interpret(),
    )(lp, yp, gp)
    return dl[:n, :c]


# Ceiling for the fused path: the kernel blocks over rows only, so a single
# row's padded class dim must fit the minimum 8-row block within the VMEM
# budget (8 * 65536 * 4B = 2MB of logits; ~12MB with backward temporaries,
# against ~16MB VMEM). Beyond it the registry wrappers fall back to the
# stock XLA loss rather than fail Mosaic compilation.
MAX_FUSED_CLASSES = 65536


@jax.custom_vjp
def fused_softmax_xent(logits, labels):
    """Per-example cross-entropy from logits: (N, C), (N,) -> (N,) float32.

    Equivalent to ``-log_softmax(logits)[labels]`` but computed blockwise in
    VMEM without materializing log-probabilities in HBM. C must be at most
    ``MAX_FUSED_CLASSES``; the registry-level loss falls back automatically.
    """
    return _xent_forward(logits, labels)


def _vjp_fwd(logits, labels):
    return _xent_forward(logits, labels), (logits, labels)


def _vjp_bwd(res, g):
    logits, labels = res
    return _xent_backward(logits, labels, g), None


fused_softmax_xent.defvjp(_vjp_fwd, _vjp_bwd)


_warned_fallback = False


def _stock_fallback(c: int) -> bool:
    global _warned_fallback
    if c <= MAX_FUSED_CLASSES:
        return False
    if not _warned_fallback:
        from ..utils import logging as dlog

        dlog.warning(
            f"pallas loss: {c} classes exceeds the fused ceiling "
            f"({MAX_FUSED_CLASSES}); using the stock XLA loss"
        )
        _warned_fallback = True
    return True


def _sharded_fused_xent(flat_logits, flat_labels):
    """fused_softmax_xent per-shard under the ambient mesh: GSPMD cannot
    partition a Pallas custom call (it would all-gather the logits and run
    the global problem on every device), so batch-sharded rows go through
    shard_map (parallel.auto_shard). Plain call off-mesh."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.auto_shard import ambient_mesh, shard_rows

    mesh, batch_axis, _ = ambient_mesh()
    if mesh is None or batch_axis is None:
        return fused_softmax_xent(flat_logits, flat_labels)
    return shard_rows(
        fused_softmax_xent,
        (flat_logits, flat_labels),
        (P(batch_axis, None), P(batch_axis)),
        P(batch_axis),
    )


def pallas_sparse_categorical_crossentropy(logits, labels):
    """Mean fused cross-entropy — drop-in for the stock loss via
    ``compile(loss="pallas_sparse_categorical_crossentropy")``.

    Leading batch dims are flattened ((B, T, C) token losses included).
    Class counts beyond ``MAX_FUSED_CLASSES`` fall back to the stock loss.
    """
    c = logits.shape[-1]
    if _stock_fallback(c):
        from . import losses

        return losses.sparse_categorical_crossentropy(logits, labels)
    flat = logits.reshape(-1, c)
    return jnp.mean(_sharded_fused_xent(flat, labels.reshape(-1)))


def per_example_pallas_xent(logits, labels):
    c = logits.shape[-1]
    if _stock_fallback(c):
        from . import losses

        return losses._per_example_sparse_cce(logits, labels)
    out = _sharded_fused_xent(logits.reshape(-1, c), labels.reshape(-1))
    return out.reshape(labels.shape)
