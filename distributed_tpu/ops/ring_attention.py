"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context sequence parallelism (absent from the reference, which has no
sequence dimension at all — SURVEY.md §5 "long-context"): Q/K/V stay sharded
on the sequence dimension across the 'seq' mesh axis; K/V blocks rotate
around the ring with ``lax.ppermute`` while each device folds every block
into a running (max, denominator, accumulator) — the online-softmax
recurrence of FlashAttention, distributed. No device ever materializes the
full (T, T) score matrix or an all-gathered K/V: per-device memory is
O(T/n), and on a TPU torus the ppermute is a neighbor hop over ICI that
overlaps with the block matmuls.

Exactness: the result equals dense softmax attention up to float
associativity — verified against the dense path in tests on the 8-device
sim. Causal masking uses global positions, so the blockwise result is
identical to masking the full matrix.

Causal schedule: the naive ring folds every rotated block on every device,
so with causal masking ~half the (device, block) pairs are fully masked —
wasted FLOPs, and imbalanced (the last shard does n live folds, the first
does 1). The default causal path therefore uses the ZIGZAG (striped)
schedule: the sequence is viewed as 2n half-chunks and each device is
re-sharded (boundary ppermutes) to hold chunks (i, 2n-1-i) — one early, one
late. Then every rotated hop has EXACTLY two live chunk-pairs per device,
fully unmasked ((q_hi, k_lo) always; (q_lo, k_lo) when my > src else
(q_hi, k_hi)), and only the resident hop applies triangular masks — ~half
the matmul FLOPs of the naive schedule, perfectly load-balanced, same
O(T/n) memory and ring traffic (docs/PERF.md "ring attention" A/B).
``schedule="naive"`` keeps the old path for reference/debugging.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec

try:  # modern location (jax>=0.8)
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

import inspect

# Replication checking was renamed check_rep -> check_vma in jax 0.8;
# resolve the kwarg once at import, not per call.
_sig = inspect.signature(shard_map).parameters
if "check_vma" in _sig:
    _CHECK_KWARGS = {"check_vma": False}
elif "check_rep" in _sig:  # pragma: no cover — older jax
    _CHECK_KWARGS = {"check_rep": False}
else:  # pragma: no cover
    _CHECK_KWARGS = {}
del _sig


def _online_fold(m, l, acc, qf, kc, vc, scale, mask):
    """One block fold of the distributed online-softmax recurrence.

    Shared by both causal schedules — the numerically delicate guard chain
    (rows with no live key yet have m == -inf; exp(-inf - -inf) would be
    NaN) lives exactly once. ``mask=None`` means the block is fully live.
    """
    s = (
        jnp.einsum(
            "bqhd,bkhd->bhqk", qf, kc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        * scale
    )
    if mask is not None:
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    safe = jnp.isfinite(m_new)
    m_ref = jnp.where(safe, m_new, 0.0)
    alpha = jnp.where(safe, jnp.exp(m - m_ref), 0.0)
    p = jnp.exp(s - m_ref[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = l * alpha + jnp.sum(p, axis=-1)
    acc = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, vc.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return m_new, l, acc


def ring_attention(
    q,
    k,
    v,
    *,
    mesh: Mesh,
    seq_axis: str = "seq",
    batch_axis: Optional[str] = None,
    causal: bool = False,
    schedule: str = "auto",
):
    """Attention over (B, T, H, D) tensors whose T dim is sharded on
    ``seq_axis`` (and optionally B on ``batch_axis``). Returns (B, T, H, D)
    with the same sharding.

    ``schedule``: "auto" (zigzag for causal when the shard splits in half,
    else naive), "zigzag", or "naive" — see the module docstring.
    """
    n = int(mesh.shape[seq_axis])
    if q.shape[1] % n:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by "
            f"{seq_axis}={n} shards"
        )
    if schedule not in ("auto", "zigzag", "naive"):
        raise ValueError(f"unknown schedule {schedule!r}")
    tb = q.shape[1] // n
    use_zigzag = causal and n > 1 and tb % 2 == 0
    if schedule == "zigzag" and not use_zigzag:
        raise ValueError(
            "schedule='zigzag' needs causal=True, >1 seq shard, and an "
            f"even per-shard length (got causal={causal}, shards={n}, "
            f"per-shard={tb})"
        )
    if use_zigzag and schedule != "naive":
        return _ring_attention_zigzag(
            q, k, v, mesh=mesh, seq_axis=seq_axis, batch_axis=batch_axis
        )
    spec = PartitionSpec(batch_axis, seq_axis, None, None)

    def local_fn(ql, kl, vl):
        # ql/kl/vl: (B, Tb, H, D) — this device's block.
        b, tb, h, d = ql.shape
        my = lax.axis_index(seq_axis)
        scale = 1.0 / jnp.sqrt(jnp.float32(d))
        qf = ql.astype(jnp.float32)
        q_pos = my * tb + jnp.arange(tb)

        m0 = jnp.full((b, h, tb), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, h, tb), jnp.float32)
        acc0 = jnp.zeros((b, h, tb, d), jnp.float32)
        perm = [(j, (j + 1) % n) for j in range(n)]

        def fold(m, l, acc, kc, vc, i):
            """Fold one K/V block into the online-softmax accumulators.
            After i rotations each device holds the block that started on
            device (my - i) mod n."""
            src = (my - i) % n
            mask = None
            if causal:
                k_pos = src * tb + jnp.arange(tb)
                mask = q_pos[:, None] >= k_pos[None, :]  # (Tb_q, Tb_k)
            return _online_fold(m, l, acc, qf, kc, vc, scale, mask)

        # Fold the resident block, then scan n-1 rotate-and-fold steps (the
        # rotation leads the fold so no final rotation is wasted — XLA can't
        # DCE a collective inside a loop). lax.scan, not fori_loop: the ring
        # must be reverse-mode differentiable for training.
        m, l, acc = fold(m0, l0, acc0, kl, vl, 0)

        def body(carry, i):
            m, l, acc, kc, vc = carry
            kc = lax.ppermute(kc, seq_axis, perm)
            vc = lax.ppermute(vc, seq_axis, perm)
            m, l, acc = fold(m, l, acc, kc, vc, i)
            return (m, l, acc, kc, vc), None

        (m, l, acc, _, _), _ = lax.scan(
            body, (m, l, acc, kl, vl), jnp.arange(1, n)
        )
        out = acc / jnp.maximum(l, 1e-30)[..., None]  # (B, H, Tb, D)
        return jnp.transpose(out, (0, 2, 1, 3)).astype(ql.dtype)

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **_CHECK_KWARGS,
    )(q, k, v)


def _zigzag_perms(n: int):
    """Boundary permutations between contiguous and zigzag chunk layouts.

    The sequence is 2n half-chunks; contiguous device s holds (2s, 2s+1),
    zigzag device i holds (i, 2n-1-i). Chunk c's zigzag home is device c
    (lo slot) when c < n, else device 2n-1-c (hi slot). Each returned perm
    is a (source, dest) list for one (source slot -> dest slot) ppermute;
    unlisted destinations receive zeros, so slot contents sum cleanly.
    """
    lo_from_lo = [(s, 2 * s) for s in range(n) if 2 * s < n]
    lo_from_hi = [(s, 2 * s + 1) for s in range(n) if 2 * s + 1 < n]
    hi_from_lo = [(s, 2 * n - 1 - 2 * s) for s in range(n) if 2 * s >= n]
    hi_from_hi = [(s, 2 * n - 2 - 2 * s) for s in range(n) if 2 * s + 1 >= n]
    # Inverse: contiguous device d's lo = chunk 2d, hi = chunk 2d+1.
    inv_lo_from_lo = [(2 * d, d) for d in range(n) if 2 * d < n]
    inv_lo_from_hi = [(2 * n - 1 - 2 * d, d) for d in range(n) if 2 * d >= n]
    inv_hi_from_lo = [(2 * d + 1, d) for d in range(n) if 2 * d + 1 < n]
    inv_hi_from_hi = [
        (2 * n - 2 - 2 * d, d) for d in range(n) if 2 * d + 1 >= n
    ]
    return (
        (lo_from_lo, lo_from_hi, hi_from_lo, hi_from_hi),
        (inv_lo_from_lo, inv_lo_from_hi, inv_hi_from_lo, inv_hi_from_hi),
    )


def _ring_attention_zigzag(q, k, v, *, mesh, seq_axis, batch_axis):
    """Causal ring attention on the balanced zigzag schedule (module doc)."""
    n = int(mesh.shape[seq_axis])
    spec = PartitionSpec(batch_axis, seq_axis, None, None)
    fwd_perms, inv_perms = _zigzag_perms(n)

    def local_fn(ql, kl, vl):
        b, tb, h, d = ql.shape
        c = tb // 2
        my = lax.axis_index(seq_axis)
        scale = 1.0 / jnp.sqrt(jnp.float32(d))

        def to_zigzag(x):
            lo, hi = x[:, :c], x[:, c:]
            p_ll, p_lh, p_hl, p_hh = fwd_perms
            new_lo = lax.ppermute(lo, seq_axis, p_ll) + lax.ppermute(
                hi, seq_axis, p_lh
            )
            new_hi = lax.ppermute(lo, seq_axis, p_hl) + lax.ppermute(
                hi, seq_axis, p_hh
            )
            return new_lo, new_hi

        def from_zigzag(lo, hi):
            p_ll, p_lh, p_hl, p_hh = inv_perms
            orig_lo = lax.ppermute(lo, seq_axis, p_ll) + lax.ppermute(
                hi, seq_axis, p_lh
            )
            orig_hi = lax.ppermute(lo, seq_axis, p_hl) + lax.ppermute(
                hi, seq_axis, p_hh
            )
            return jnp.concatenate([orig_lo, orig_hi], axis=1)

        q_lo, q_hi = to_zigzag(ql)
        k_lo, k_hi = to_zigzag(kl)
        v_lo, v_hi = to_zigzag(vl)
        qf_lo = q_lo.astype(jnp.float32)
        qf_hi = q_hi.astype(jnp.float32)

        def fold(m, l, acc, qf, kc, vc, mask):
            # mask=None means fully live (the zigzag invariant for every
            # rotated hop); numerics live in the shared _online_fold.
            return _online_fold(m, l, acc, qf, kc, vc, scale, mask)

        zeros = lambda *shape: jnp.zeros(shape, jnp.float32)
        m_lo = jnp.full((b, h, c), -jnp.inf, jnp.float32)
        m_hi = jnp.full((b, h, c), -jnp.inf, jnp.float32)
        l_lo, l_hi = zeros(b, h, c), zeros(b, h, c)
        acc_lo, acc_hi = zeros(b, h, c, d), zeros(b, h, c, d)

        # Resident hop (src == my): the only hop with masked (triangular)
        # pairs — (q_lo, k_lo) and (q_hi, k_hi) are diagonal chunks,
        # (q_hi, k_lo) is fully live, (q_lo, k_hi) is fully dead.
        tri = jnp.tril(jnp.ones((c, c), bool))
        m_lo, l_lo, acc_lo = fold(m_lo, l_lo, acc_lo, qf_lo, k_lo, v_lo, tri)
        m_hi, l_hi, acc_hi = fold(m_hi, l_hi, acc_hi, qf_hi, k_lo, v_lo,
                                  None)
        m_hi, l_hi, acc_hi = fold(m_hi, l_hi, acc_hi, qf_hi, k_hi, v_hi, tri)

        perm = [(j, (j + 1) % n) for j in range(n)]

        def body(carry, j):
            m_lo, l_lo, acc_lo, m_hi, l_hi, acc_hi, klo, khi, vlo, vhi = carry
            klo = lax.ppermute(klo, seq_axis, perm)
            khi = lax.ppermute(khi, seq_axis, perm)
            vlo = lax.ppermute(vlo, seq_axis, perm)
            vhi = lax.ppermute(vhi, seq_axis, perm)
            src = (my - j) % n
            # Always live: this device's late chunk vs src's early chunk.
            m_hi, l_hi, acc_hi = fold(m_hi, l_hi, acc_hi, qf_hi, klo, vlo,
                                      None)
            # Second live pair depends on ring position: my > src pairs the
            # early q chunk with src's early k chunk; my < src pairs the
            # late q chunk with src's late k chunk. Same shapes, so one
            # predicated fold covers both (src == my impossible here).
            pred = my > src
            q_sel = jnp.where(pred, qf_lo, qf_hi)
            k_sel = jnp.where(pred, klo, khi)
            v_sel = jnp.where(pred, vlo, vhi)
            m_sel = jnp.where(pred, m_lo, m_hi)
            l_sel = jnp.where(pred, l_lo, l_hi)
            acc_sel = jnp.where(pred, acc_lo, acc_hi)
            m2, l2, acc2 = fold(m_sel, l_sel, acc_sel, q_sel, k_sel, v_sel,
                                None)
            m_lo = jnp.where(pred, m2, m_lo)
            l_lo = jnp.where(pred, l2, l_lo)
            acc_lo = jnp.where(pred, acc2, acc_lo)
            m_hi = jnp.where(pred, m_hi, m2)
            l_hi = jnp.where(pred, l_hi, l2)
            acc_hi = jnp.where(pred, acc_hi, acc2)
            return (m_lo, l_lo, acc_lo, m_hi, l_hi, acc_hi,
                    klo, khi, vlo, vhi), None

        carry = (m_lo, l_lo, acc_lo, m_hi, l_hi, acc_hi,
                 k_lo, k_hi, v_lo, v_hi)
        carry, _ = lax.scan(body, carry, jnp.arange(1, n))
        m_lo, l_lo, acc_lo, m_hi, l_hi, acc_hi = carry[:6]

        def finish(acc, l):
            out = acc / jnp.maximum(l, 1e-30)[..., None]  # (B, H, c, D)
            return jnp.transpose(out, (0, 2, 1, 3)).astype(ql.dtype)

        return from_zigzag(finish(acc_lo, l_lo), finish(acc_hi, l_hi))

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **_CHECK_KWARGS,
    )(q, k, v)
