"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context sequence parallelism (absent from the reference, which has no
sequence dimension at all — SURVEY.md §5 "long-context"): Q/K/V stay sharded
on the sequence dimension across the 'seq' mesh axis; K/V blocks rotate
around the ring with ``lax.ppermute`` while each device folds every block
into a running (max, denominator, accumulator) — the online-softmax
recurrence of FlashAttention, distributed. No device ever materializes the
full (T, T) score matrix or an all-gathered K/V: per-device memory is
O(T/n), and on a TPU torus the ppermute is a neighbor hop over ICI that
overlaps with the block matmuls.

Exactness: the result equals dense softmax attention up to float
associativity — verified against the dense path in tests on the 8-device
sim. Causal masking uses global positions, so the blockwise result is
identical to masking the full matrix. (Fully-masked blocks still compute —
an SPMD program can't skip per-device — so causal ring attention does ~2x
the minimal FLOPs; acceptable until a skew-schedule variant lands.)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec

try:  # modern location (jax>=0.8)
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

import inspect

# Replication checking was renamed check_rep -> check_vma in jax 0.8;
# resolve the kwarg once at import, not per call.
_sig = inspect.signature(shard_map).parameters
if "check_vma" in _sig:
    _CHECK_KWARGS = {"check_vma": False}
elif "check_rep" in _sig:  # pragma: no cover — older jax
    _CHECK_KWARGS = {"check_rep": False}
else:  # pragma: no cover
    _CHECK_KWARGS = {}
del _sig


def ring_attention(
    q,
    k,
    v,
    *,
    mesh: Mesh,
    seq_axis: str = "seq",
    batch_axis: Optional[str] = None,
    causal: bool = False,
):
    """Attention over (B, T, H, D) tensors whose T dim is sharded on
    ``seq_axis`` (and optionally B on ``batch_axis``). Returns (B, T, H, D)
    with the same sharding."""
    n = int(mesh.shape[seq_axis])
    if q.shape[1] % n:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by "
            f"{seq_axis}={n} shards"
        )
    spec = PartitionSpec(batch_axis, seq_axis, None, None)

    def local_fn(ql, kl, vl):
        # ql/kl/vl: (B, Tb, H, D) — this device's block.
        b, tb, h, d = ql.shape
        my = lax.axis_index(seq_axis)
        scale = 1.0 / jnp.sqrt(jnp.float32(d))
        qf = ql.astype(jnp.float32)
        q_pos = my * tb + jnp.arange(tb)

        m0 = jnp.full((b, h, tb), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, h, tb), jnp.float32)
        acc0 = jnp.zeros((b, h, tb, d), jnp.float32)
        perm = [(j, (j + 1) % n) for j in range(n)]

        def fold(m, l, acc, kc, vc, i):
            """Fold one K/V block into the online-softmax accumulators.
            After i rotations each device holds the block that started on
            device (my - i) mod n."""
            src = (my - i) % n
            s = (
                jnp.einsum(
                    "bqhd,bkhd->bhqk",
                    qf,
                    kc.astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )
            if causal:
                k_pos = src * tb + jnp.arange(tb)
                mask = q_pos[:, None] >= k_pos[None, :]  # (Tb_q, Tb_k)
                s = jnp.where(mask[None, None], s, -jnp.inf)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            # Guard fully-masked-so-far rows: exp(-inf - -inf) would be NaN.
            safe = jnp.isfinite(m_new)
            m_ref = jnp.where(safe, m_new, 0.0)
            alpha = jnp.where(safe, jnp.exp(m - m_ref), 0.0)
            p = jnp.exp(s - m_ref[..., None])
            p = jnp.where(jnp.isfinite(s), p, 0.0)
            l = l * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[..., None] + jnp.einsum(
                "bhqk,bkhd->bhqd",
                p,
                vc.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            return m_new, l, acc

        # Fold the resident block, then scan n-1 rotate-and-fold steps (the
        # rotation leads the fold so no final rotation is wasted — XLA can't
        # DCE a collective inside a loop). lax.scan, not fori_loop: the ring
        # must be reverse-mode differentiable for training.
        m, l, acc = fold(m0, l0, acc0, kl, vl, 0)

        def body(carry, i):
            m, l, acc, kc, vc = carry
            kc = lax.ppermute(kc, seq_axis, perm)
            vc = lax.ppermute(vc, seq_axis, perm)
            m, l, acc = fold(m, l, acc, kc, vc, i)
            return (m, l, acc, kc, vc), None

        (m, l, acc, _, _), _ = lax.scan(
            body, (m, l, acc, kl, vl), jnp.arange(1, n)
        )
        out = acc / jnp.maximum(l, 1e-30)[..., None]  # (B, H, Tb, D)
        return jnp.transpose(out, (0, 2, 1, 3)).astype(ql.dtype)

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **_CHECK_KWARGS,
    )(q, k, v)
