"""Optimizers: Keras-shaped constructors over optax transforms.

Parity target: ``optimizer_sgd(lr = 0.001)`` / ``tf.keras.optimizers.SGD``
(/root/reference/README.md:71, 301). Optimizer state is an ordinary pytree, so
it replicates/shards with the same ``NamedSharding`` rules as the parameters.

Named constructors build through ``optax.inject_hyperparams``, which lifts
the numeric hyperparameters (learning rate, momentum, ...) into the
optimizer STATE instead of baking them into the jitted update — so
``Model.set_learning_rate`` (and the ``LearningRateScheduler`` /
``ReduceLROnPlateau`` callbacks) can change them between steps without a
recompile, and a checkpointed run resumes with the learning rate it was
actually using. Schedules still work: a callable learning_rate is
re-evaluated against the step count inside the update, as before.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


def SGD(learning_rate: float = 0.001, momentum: float = 0.0, nesterov: bool = False):
    if momentum:
        return optax.inject_hyperparams(optax.sgd)(
            learning_rate, momentum=momentum, nesterov=nesterov
        )
    return optax.inject_hyperparams(optax.sgd)(learning_rate)


def Adam(learning_rate: float = 0.001, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8):
    return optax.inject_hyperparams(optax.adam)(
        learning_rate, b1=b1, b2=b2, eps=eps
    )


def AdamW(learning_rate: float = 0.001, weight_decay: float = 0.01, b1=0.9, b2=0.999):
    return optax.inject_hyperparams(optax.adamw)(
        learning_rate, b1=b1, b2=b2, weight_decay=weight_decay
    )


def fused_adam(learning_rate: float = 0.001, b1: float = 0.9,
               b2: float = 0.999, eps: float = 1e-8):
    """Adam whose whole update — moment EMAs, bias correction, step — runs
    as ONE Pallas kernel pass per same-dtype flat segment of the master
    tree, instead of the stock per-leaf tree walk (ops.fused_update; the
    raw-speed lever measured by ``bench.py fused_update``). Numerically
    operation-for-operation identical to ``Adam``; drops into the same
    ``Strategy.init_opt_state``/``constrain_step`` seams (the moment trees
    shard exactly like stock Adam state under ZeRO-1/FSDP), and the
    ``inject_hyperparams`` wrapper keeps the learning rate runtime-mutable
    and checkpointable. CPU backends run the kernel in interpret mode
    (same semantics, no speedup — see docs/PERF.md)."""
    from ..ops import fused_update  # lazy: pulls in pallas

    return optax.inject_hyperparams(fused_update.fused_adam)(
        learning_rate, b1=b1, b2=b2, eps=eps
    )


def fused_adamw(learning_rate: float = 0.001, weight_decay: float = 0.01,
                b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8):
    """AdamW spelling of :func:`fused_adam` — the decoupled weight decay
    folds into the same single kernel pass."""
    from ..ops import fused_update  # lazy: pulls in pallas

    return optax.inject_hyperparams(fused_update.fused_adam)(
        learning_rate, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay
    )


def RMSprop(learning_rate: float = 0.001, decay: float = 0.9,
            momentum: float = 0.0, eps: float = 1e-7):
    return optax.inject_hyperparams(optax.rmsprop)(
        learning_rate, decay=decay, momentum=momentum, eps=eps
    )


def Adagrad(learning_rate: float = 0.001, eps: float = 1e-7):
    return optax.inject_hyperparams(optax.adagrad)(learning_rate, eps=eps)


def Lamb(learning_rate: float = 0.001, weight_decay: float = 0.0,
         b1: float = 0.9, b2: float = 0.999):
    """Layer-wise adaptive large-batch optimizer — the standard choice for
    the data-parallel global-batch scaling this framework's mesh enables."""
    return optax.inject_hyperparams(optax.lamb)(
        learning_rate, b1=b1, b2=b2, weight_decay=weight_decay
    )


def _tree_get(opt_state, name: str):
    """optax.tree_utils.tree_get with this module's failure semantics:
    a missing hyperparameter (raw optax transform) and a schedule-driven
    one (tree_get's 'multiple values' — the schedule's wrapped state also
    carries the name, and re-evaluates over whatever we write) both raise
    a KeyError that says what to do instead."""
    import optax.tree_utils as otu

    try:
        value = otu.tree_get(opt_state, name)
    except KeyError as e:
        raise KeyError(
            f"hyperparameter {name!r} is schedule-driven in this optimizer "
            "state — a per-step schedule recomputes it inside the update, "
            "so runtime mutation would be silently overwritten. Mutate the "
            "schedule (recompile) or use a constant hyperparameter."
        ) from e
    if value is None:
        raise KeyError(
            f"optimizer state carries no injectable hyperparameter "
            f"{name!r} — build the optimizer via dtpu.optim names/"
            "constructors (optax.inject_hyperparams) to make it mutable"
        )
    return value


def set_hyperparam(opt_state, name: str, value):
    """Return ``opt_state`` with injected hyperparameter ``name`` replaced
    (e.g. 'learning_rate'), searching through chained/nested states.
    Raises KeyError for raw optax transforms (nothing injected) and for
    schedule-driven hyperparameters (mutation would be a silent no-op)."""
    import jax.numpy as jnp
    import optax.tree_utils as otu

    current = _tree_get(opt_state, name)
    return otu.tree_set(
        opt_state,
        **{name: jnp.asarray(value, getattr(current, "dtype", None))},
    )


def get_hyperparam(opt_state, name: str):
    """Read an injected hyperparameter from ``opt_state`` (see
    ``set_hyperparam``)."""
    return _tree_get(opt_state, name)


class LossScaleState(NamedTuple):
    """State of ``dynamic_loss_scaling``: the live scale (f32 scalar), the
    count of consecutive finite steps since the last scale change, and the
    wrapped transform's state. A NamedTuple pytree, so it shards/replicates
    with the usual NamedSharding rules, checkpoints leaf-for-leaf (the live
    scale survives save/restore), and stays transparent to
    ``optax.tree_utils`` — ``set_hyperparam('learning_rate', ...)`` reaches
    through it into the wrapped optimizer."""

    scale: Any
    growth_count: Any
    inner_state: Any


def dynamic_loss_scaling(
    inner,
    *,
    init_scale: float = 2.0 ** 15,
    growth_interval: int = 2000,
    factor: float = 2.0,
    min_scale: float = 1.0,
):
    """Dynamic-loss-scale wrapper for float16 training (the optax-style
    half of the Micikevicius et al. 2018 recipe; bf16 does not need it).

    The model's step multiplies the loss by ``state.scale`` before
    autodiff, so the incoming gradients here are SCALED. ``update``:

    1. unscales the gradients (divide by the live scale, in f32),
    2. checks every leaf for finiteness,
    3. finite   -> applies the wrapped transform to the unscaled grads and,
       after ``growth_interval`` consecutive finite steps, doubles the
       scale (``factor``),
    4. non-finite -> SKIPS the step: zero updates, the wrapped state is
       kept (not advanced), and the scale is halved (floored at
       ``min_scale``).

    The skip keeps params and optimizer statistics untouched while the
    scale searches back down to the representable range — overflow costs
    one step of progress, never a poisoned Adam moment."""
    inner = get(inner)

    def init_fn(params):
        return LossScaleState(
            jnp.float32(init_scale), jnp.int32(0), inner.init(params)
        )

    def update_fn(grads, state, params=None):
        inv = jnp.float32(1.0) / state.scale
        unscaled = jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype)
            if jnp.issubdtype(jnp.result_type(g), jnp.floating) else g,
            grads,
        )
        leaves = jax.tree_util.tree_leaves(unscaled)
        finite = jnp.array(True)
        for leaf in leaves:
            if jnp.issubdtype(jnp.result_type(leaf), jnp.floating):
                finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(leaf)))
        new_updates, new_inner = inner.update(
            unscaled, state.inner_state, params
        )
        # Elementwise select: on a skipped step the zero update and the
        # retained old inner state win; any NaN/inf in the not-taken
        # branch is discarded by the select, never propagated.
        updates = jax.tree_util.tree_map(
            lambda u: jnp.where(finite, u, jnp.zeros_like(u)), new_updates
        )
        inner_state = jax.tree_util.tree_map(
            lambda n, o: jnp.where(finite, n, o), new_inner,
            state.inner_state,
        )
        grown = state.growth_count + 1
        should_grow = jnp.logical_and(finite, grown >= growth_interval)
        new_scale = jnp.where(
            finite,
            jnp.where(should_grow, state.scale * factor, state.scale),
            jnp.maximum(state.scale / factor, jnp.float32(min_scale)),
        )
        new_count = jnp.where(
            jnp.logical_and(finite, jnp.logical_not(should_grow)),
            grown, jnp.int32(0),
        )
        return updates, LossScaleState(new_scale, new_count, inner_state)

    return optax.GradientTransformation(init_fn, update_fn)


def loss_scale_value(opt_state):
    """The live loss scale of an optimizer state built through
    ``dynamic_loss_scaling`` (the wrapper is always outermost), or None
    when no loss scaling is active. Model step bodies read this to
    multiply the loss before autodiff."""
    if isinstance(opt_state, LossScaleState):
        return opt_state.scale
    return None


class EmaBaseline:
    """Exponential-moving-average reward baseline for policy-gradient
    advantages (``rl.PostTrainer``): ``advantage = reward - baseline``.
    Host-side scalar state, like the learning-rate hyperparams — small
    enough to live outside the jitted step, and it must NOT shard (every
    rollout subtracts the same baseline or the gradient gains a spurious
    per-shard offset). ``state_dict``/``load_state`` round-trip it through
    checkpoint metadata."""

    def __init__(self, decay: float = 0.9):
        if not 0.0 <= decay < 1.0:
            raise ValueError(f"decay must be in [0, 1); got {decay}")
        self.decay = float(decay)
        self.value = None  # None until the first update (cold start)

    def update(self, reward_mean: float) -> float:
        """Fold one iteration's mean reward in; returns the new baseline.
        The first update adopts the observed mean outright (a 0-init
        baseline would hand the whole first batch a large spurious
        advantage)."""
        r = float(reward_mean)
        if self.value is None:
            self.value = r
        else:
            self.value = self.decay * self.value + (1.0 - self.decay) * r
        return self.value

    def state_dict(self):
        return {"decay": self.decay, "value": self.value}

    def load_state(self, state):
        self.decay = float(state["decay"])
        self.value = None if state["value"] is None else float(state["value"])


class AdaptiveKLCoef:
    """PPO-style adaptive KL-penalty coefficient (Schulman et al., 2017):
    after each policy update, grow the coefficient when the observed
    policy-vs-reference KL overshoots ``target`` and shrink it when the
    policy is moving too timidly. ``rl.PostTrainer`` accepts an instance
    anywhere a fixed ``kl_coef`` float goes and calls ``update`` with the
    measured post-update KL each iteration."""

    def __init__(self, init_coef: float = 0.1, target: float = 0.01,
                 factor: float = 1.5, tolerance: float = 1.5):
        if init_coef < 0 or target <= 0 or factor <= 1 or tolerance < 1:
            raise ValueError(
                "need init_coef >= 0, target > 0, factor > 1, "
                f"tolerance >= 1; got {init_coef}, {target}, {factor}, "
                f"{tolerance}"
            )
        self.coef = float(init_coef)
        self.target = float(target)
        self.factor = float(factor)
        self.tolerance = float(tolerance)

    def update(self, observed_kl: float) -> float:
        """Adapt to one iteration's measured KL; returns the new coef."""
        kl = float(observed_kl)
        if kl > self.target * self.tolerance:
            self.coef *= self.factor
        elif kl < self.target / self.tolerance:
            self.coef /= self.factor
        return self.coef

    def state_dict(self):
        return {"coef": self.coef, "target": self.target,
                "factor": self.factor, "tolerance": self.tolerance}

    def load_state(self, state):
        self.coef = float(state["coef"])
        self.target = float(state["target"])
        self.factor = float(state["factor"])
        self.tolerance = float(state["tolerance"])


def sgd_with_cosine(learning_rate: float, steps: int, warmup: int = 0, momentum: float = 0.9):
    return optax.sgd(cosine_schedule(learning_rate, steps, warmup),
                     momentum=momentum)


def cosine_schedule(learning_rate: float, steps: int, warmup: int = 0):
    """Warmup-then-cosine decay schedule; pass as any optimizer's
    learning_rate (optax schedules are plain callables)."""
    return optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, max(warmup, 1), max(steps, warmup + 1)
    )


def exponential_schedule(learning_rate: float, decay_rate: float,
                         decay_steps: int, warmup: int = 0):
    if warmup:
        return optax.warmup_exponential_decay_schedule(
            0.0, learning_rate, warmup, decay_steps, decay_rate
        )
    return optax.exponential_decay(learning_rate, decay_steps, decay_rate)


_REGISTRY = {
    "sgd": SGD,
    "adam": Adam,
    "adamw": AdamW,
    "fused_adam": fused_adam,
    "fused_adamw": fused_adamw,
    "rmsprop": RMSprop,
    "adagrad": Adagrad,
    "lamb": Lamb,
}


def get(name_or_tx, **kwargs):
    """Resolve 'sgd'/'adam'/'adamw' by name, or pass an optax transform through.

    A (init_fn, update_fn) sequence is rebuilt into a GradientTransformation:
    ``GradientTransformation`` is a NamedTuple, and language bridges flatten
    NamedTuples to plain lists (reticulate converts Python tuples to R lists,
    so an optimizer built in R via ``dtpu()$optim$get(...)`` comes back as a
    list of its two functions — caught by tests/test_reticulate_semantics.py).
    """
    if isinstance(name_or_tx, str):
        try:
            return _REGISTRY[name_or_tx.lower()](**kwargs)
        except KeyError:
            raise ValueError(f"Unknown optimizer {name_or_tx!r}") from None
    if (
        isinstance(name_or_tx, (list, tuple))
        and not isinstance(name_or_tx, optax.GradientTransformation)
        and len(name_or_tx) == 2
        and all(callable(f) for f in name_or_tx)
    ):
        return optax.GradientTransformation(*name_or_tx)
    return name_or_tx
