from .auto_shard import Candidate, Feasibility, Plan, plan_sharding
from .mesh import AXES, batch_sharding, make_mesh, replicated
from .strategy import (
    CompositeParallel,
    DataParallel,
    DataSeqParallel,
    DataExpertParallel,
    DataTensorParallel,
    FSDP,
    FullyShardedDataParallel,
    MultiWorkerMirroredStrategy,
    SingleDevice,
    Strategy,
    ZeroDataParallel,
    current_strategy,
)

__all__ = [
    "AXES",
    "Candidate",
    "Feasibility",
    "Plan",
    "plan_sharding",
    "make_mesh",
    "replicated",
    "batch_sharding",
    "Strategy",
    "SingleDevice",
    "CompositeParallel",
    "DataParallel",
    "DataSeqParallel",
    "DataExpertParallel",
    "DataTensorParallel",
    "FSDP",
    "FullyShardedDataParallel",
    "MultiWorkerMirroredStrategy",
    "ZeroDataParallel",
    "current_strategy",
]
