"""Automatic sharding: a cost-model planner, plus Pallas kernel routing.

Two jobs live here:

1. **The auto-shard planner** (``plan_sharding`` / ``Plan`` /
   ``Feasibility``), shipped to users as
   ``model.compile(strategy="auto", hbm_cap_bytes=..., measure=False)``.
   The configuration matrix this framework grew — DP x ZeRO-1 x FSDP x TP
   x ``grad_accum`` x ``steps_per_execution`` x precision — is navigable
   by experts only; the planner picks the fastest FEASIBLE config from a
   cost model, with every input it needs already measurable through
   existing seams:

   - per-device state bytes via ``jax.eval_shape`` over the module's init
     (abstract ``ShapeDtypeStruct`` trees with the candidate strategy's
     ``params_sharding`` / ``opt_state_sharding`` attached, priced by
     ``utils.profiler.tree_bytes_per_device`` — no 30M-param tree is ever
     materialized per candidate);
   - per-step collective traffic via ``Strategy.comm_bytes_estimate``
     (unified schema across all strategies, int8/bf16-aware);
   - an HBM-cap feasibility predicate (``Feasibility``) generalizing the
     ``bench.py zero`` hbm_cap_row check;
   - a rank over survivors: estimated step seconds = compute (analytic
     FLOPs / device peak, precision-aware) + comm (bytes / link bandwidth)
     + dispatch overhead (amortized by ``steps_per_execution``). Constants
     are order-of-magnitude per backend — only RATIOS between candidates
     matter, and ties (within ``TIE_REL_TOL``) break toward more HBM
     headroom under a cap, else toward the simpler config.

   ``measure=True`` additionally times the top-k shortlist with short real
   dispatches before committing (the only path that materializes params).
   The chosen ``Plan`` — config, predicted bytes/traffic, and the pruned
   candidates' rationale — lands in ``model.last_fit_telemetry["plan"]``
   and the JSONL event log (``auto_shard_plan``).

2. **Pallas kernel routing** (``shard_rows``): XLA's SPMD partitioner
   cannot see inside a Pallas kernel, so under a sharded mesh it wraps the
   call in all-gather(inputs) -> replicated compute -> dynamic-slice
   (output): correct, but the kernel then runs the GLOBAL problem on every
   device (verified by compiling flash attention under a 'data'-sharded
   batch and finding the all-gather in the HLO). The fix is shard_map: run
   the kernel per-shard on local data, which is exactly right for
   row/batch-blocked kernels (fused xent, flash attention) whose grid
   never crosses rows. ``shard_rows(fn, arrays, specs)`` wraps fn in
   shard_map over the ambient strategy's mesh when — and only when — that
   is safe:

   - every mesh axis of size > 1 is either the strategy's batch axis or
     the Megatron 'model' axis (axes with bespoke schedules — 'pipe',
     'seq' — keep the plain path; their strategies have their own
     machinery);
   - every array dim sharded by a spec divides evenly.

   Otherwise the plain call runs (GSPMD replication on multi-device, which
   is still correct — and free on a single device, where there is nothing
   to replicate).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

try:  # modern location (jax>=0.8)
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

import inspect

# Replication checking was renamed check_rep -> check_vma in jax 0.8.
_sig = inspect.signature(shard_map).parameters
if "check_vma" in _sig:
    _CHECK_KWARGS = {"check_vma": False}
elif "check_rep" in _sig:  # pragma: no cover - older jax
    _CHECK_KWARGS = {"check_rep": False}
else:  # pragma: no cover
    _CHECK_KWARGS = {}
del _sig


def ambient_mesh() -> Tuple[Optional[Mesh], Optional[str], Optional[str]]:
    """(mesh, batch_axis, model_axis) from the ambient strategy scope.

    model_axis is 'model' when present in the mesh (the Megatron TP axis,
    parallel.mesh.AXES), else None. mesh is None outside any mesh strategy.
    """
    from .strategy import current_strategy

    strat = current_strategy()
    mesh = getattr(strat, "mesh", None)
    if mesh is None:
        return None, None, None
    batch_axis = getattr(strat, "axis", None)
    if batch_axis not in mesh.axis_names:
        batch_axis = None
    model_axis = "model" if "model" in mesh.axis_names else None
    return mesh, batch_axis, model_axis


def shard_rows(fn, arrays: Sequence, in_specs: Sequence[PartitionSpec],
               out_spec: PartitionSpec, *, allowed_axes=None):
    """Apply fn(*arrays) under shard_map over the ambient mesh when safe
    (see module docstring), else call it plainly.

    ``allowed_axes``: override the default {batch, model} axis allowlist —
    for callers that deliberately shard over another axis (e.g. Ulysses
    attention sharding heads over 'seq') and have already validated it."""
    mesh, batch_axis, model_axis = ambient_mesh()
    if mesh is None:
        return fn(*arrays)
    if allowed_axes is not None:
        allowed = set(allowed_axes) | {None}
    else:
        allowed = {batch_axis, model_axis, None}
    for name in mesh.axis_names:
        if int(mesh.shape[name]) > 1 and name not in allowed:
            return fn(*arrays)
    # Divisibility of every sharded dim, or fall back.
    for arr, spec in zip(arrays, in_specs):
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            if int(mesh.shape[axis]) > 1 and arr.shape[dim] % int(
                mesh.shape[axis]
            ):
                return fn(*arrays)
    return shard_map(
        fn, mesh=mesh, in_specs=tuple(in_specs), out_specs=out_spec,
        **_CHECK_KWARGS,
    )(*arrays)


# ===========================================================================
# The auto-shard planner (ROADMAP item 3): estimate -> prune -> rank ->
# (optionally) measure. Everything below is pure w.r.t. its inputs — same
# module/topology/knobs => byte-identical Plan (pinned by tests).
# ===========================================================================

#: Relative cost band treated as a tie (dispatch jitter on small models is
#: far larger than this; the tie-break rules below decide inside the band).
TIE_REL_TOL = 0.05

#: Analytic per-device peak FLOP/s and per-device collective bandwidth by
#: backend. Order-of-magnitude on purpose: the cost model ranks candidates
#: for ONE model on ONE backend, so only the relative weight of compute vs
#: comm vs dispatch matters, not the absolute seconds.
_BACKEND_CONSTANTS = {
    "tpu": {"peak_flops": 2.0e14, "comm_bw": 9.0e10, "dispatch_s": 5e-4,
            "reduced_speedup": 2.0},
    "gpu": {"peak_flops": 1.0e14, "comm_bw": 5.0e10, "dispatch_s": 8e-4,
            "reduced_speedup": 2.0},
    # XLA:CPU EMULATES bf16 (BENCH_precision measured mixed at 0.83x f32),
    # so reduced precision gets a PENALTY there, not a speedup — the
    # planner must not recommend a policy the backend runs slower.
    "cpu": {"peak_flops": 5.0e10, "comm_bw": 1.0e10, "dispatch_s": 1.5e-3,
            "reduced_speedup": 0.85},
}

_STRATEGY_RANK = {  # simplicity order for tie-breaking (lower = simpler)
    "single_device": 0, "dp": 1, "zero1": 2, "fsdp": 3, "tp": 4, "pp": 5,
}


def _backend_constants(backend: Optional[str] = None) -> dict:
    backend = backend or jax.default_backend()
    return _BACKEND_CONSTANTS.get(backend, _BACKEND_CONSTANTS["tpu"])


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point of the configuration matrix the planner scores."""

    strategy: str                # single_device | dp | zero1 | fsdp | tp | pp
    model_parallel: int = 1            # > 1 only for strategy == "tp"
    pipeline_parallel: int = 1         # > 1 only for strategy == "pp"
    num_microbatches: int = 1          # pipeline schedule M (pp only)
    precision: Optional[str] = None    # None | precision preset name
    grad_accum: int = 1
    steps_per_execution: int = 1

    def label(self) -> str:
        parts = [self.strategy]
        if self.model_parallel > 1:
            parts[-1] += f"{self.model_parallel}"
        if self.pipeline_parallel > 1:
            parts[-1] += f"{self.pipeline_parallel}"
            parts.append(f"m{self.num_microbatches}")
        if self.precision:
            parts.append(self.precision)
        if self.grad_accum > 1:
            parts.append(f"accum{self.grad_accum}")
        if self.steps_per_execution > 1:
            parts.append(f"k{self.steps_per_execution}")
        return "/".join(parts)

    def config(self) -> dict:
        return {
            "strategy": self.strategy,
            "model_parallel": self.model_parallel,
            "pipeline_parallel": self.pipeline_parallel,
            "num_microbatches": self.num_microbatches,
            "precision": self.precision,
            "grad_accum": self.grad_accum,
            "steps_per_execution": self.steps_per_execution,
        }

    def complexity(self) -> tuple:
        """Tie-break key: simpler configs sort first."""
        return (
            _STRATEGY_RANK.get(self.strategy, 99),
            self.model_parallel,
            self.pipeline_parallel,
            self.num_microbatches,
            0 if self.precision is None else 1,
            self.grad_accum,
            self.steps_per_execution,
        )

    def build_strategy(self, devices=None):
        """Instantiate the concrete Strategy for this candidate over
        ``devices`` (default: all local devices)."""
        from . import strategy as S

        devices = list(devices) if devices is not None else list(jax.devices())
        if self.strategy == "single_device":
            return S.SingleDevice(devices[0])
        if self.strategy == "dp":
            return S.DataParallel(devices)
        if self.strategy == "zero1":
            return S.ZeroDataParallel(devices)
        if self.strategy == "fsdp":
            return S.FSDP(devices)
        if self.strategy == "tp":
            return S.DataTensorParallel(
                devices, model_parallel=self.model_parallel
            )
        if self.strategy == "pp":
            return S.DataPipelineParallel(
                devices, pipeline_parallel=self.pipeline_parallel,
                num_microbatches=self.num_microbatches,
            )
        raise ValueError(f"unknown candidate strategy {self.strategy!r}")


class Feasibility:
    """Reusable HBM-cap predicate — the generalization of the
    ``bench.py zero`` hbm_cap_row check (replicated 378MB > 256MB cap =>
    cannot train; FSDP 47MB fits). ``check`` returns None when the
    candidate fits, else a human-readable pruning reason recorded in the
    Plan."""

    def __init__(self, hbm_cap_bytes: Optional[int] = None):
        self.hbm_cap_bytes = (
            int(hbm_cap_bytes) if hbm_cap_bytes is not None else None
        )

    def check(self, state_bytes_per_device: int,
              activation_bytes_per_device: int = 0) -> Optional[str]:
        if self.hbm_cap_bytes is None:
            return None
        need = int(state_bytes_per_device) + int(activation_bytes_per_device)
        if need <= self.hbm_cap_bytes:
            return None
        return (
            f"needs {need} bytes/device (state {int(state_bytes_per_device)}"
            f" + activations {int(activation_bytes_per_device)}) "
            f"> hbm_cap {self.hbm_cap_bytes}"
        )


@dataclasses.dataclass
class Plan:
    """The planner's decision record: the chosen config + its predicted
    numbers, every candidate's row, and the rationale for pruned ones.
    ``summary()`` is the JSON-safe dict that lands in
    ``model.last_fit_telemetry["plan"]``, the JSONL event log, and
    BENCH_autoshard.json."""

    chosen: dict
    candidates: List[dict]
    pruned: List[dict]
    devices: int
    backend: str
    batch_size: int
    n_params: int
    hbm_cap_bytes: Optional[int]
    measured: Optional[List[dict]] = None
    tie_break: Optional[str] = None

    def chosen_candidate(self) -> Candidate:
        return Candidate(**self.chosen["config"])

    def summary(self) -> dict:
        return {
            "chosen": self.chosen,
            "devices": self.devices,
            "backend": self.backend,
            "batch_size": self.batch_size,
            "n_params": self.n_params,
            "hbm_cap_bytes": self.hbm_cap_bytes,
            "candidates": self.candidates,
            "pruned": self.pruned,
            "measured": self.measured,
            "tie_break": self.tie_break,
        }


# ------------------------------------------------------------ abstraction --
def abstract_model_state(module, input_shape, tx, *, seed: int = 0) -> dict:
    """Abstract (ShapeDtypeStruct) params/state/opt-state of ``module`` +
    ``tx`` via ``jax.eval_shape`` — the dry-run twin of Model.build that
    costs shapes, not HBM. One call serves every candidate (shapes don't
    depend on the strategy)."""
    key = jax.random.PRNGKey(seed)
    params, state = jax.eval_shape(
        lambda k: module.init(k, tuple(input_shape))[:2], key
    )
    opt = jax.eval_shape(tx.init, params)
    n_params = sum(
        int(np.prod(l.shape, dtype=np.int64))
        for l in jax.tree_util.tree_leaves(params)
    )
    return {
        "params": params,
        "state": state,
        "opt": opt,
        "hints": module.sharding_hints(),
        "n_params": n_params,
    }


def probe_forward(module, params, state, input_shape, batch_size: int):
    """Abstract forward probe: ``(x_dtype, logits ShapeDtypeStruct)``.
    Tries float32 input first (images/features), then int32 (token
    models — a float index makes the embedding gather raise at trace
    time, which is the detection)."""
    import jax.numpy as jnp

    last_err = None
    for dtype in (jnp.float32, jnp.int32):
        x = jax.ShapeDtypeStruct((int(batch_size),) + tuple(input_shape),
                                 dtype)
        try:
            logits = jax.eval_shape(
                lambda p, s, xx: module.apply(p, s, xx, train=False)[0],
                params, state, x,
            )
            return dtype, logits
        except Exception as e:  # wrong input dtype (or rank) for this model
            last_err = e
    raise TypeError(
        f"could not trace {type(module).__name__} abstractly with float32 "
        f"or int32 input of shape {tuple(input_shape)}: {last_err}"
    )


def _attach_shardings(tree, sharding_tree):
    """ShapeDtypeStructs with shardings attached, for
    tree_bytes_per_device's abstract path. ``sharding_tree=None`` (the
    SingleDevice case) leaves leaves bare — counted once."""
    if sharding_tree is None:
        return tree
    return jax.tree_util.tree_map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        tree, sharding_tree,
    )


# -------------------------------------------------------------- estimation --
def _check_divisibility(cand: Candidate, n_devices: int, batch_size: int,
                        abstracts: dict) -> Optional[str]:
    """Structural feasibility: batch math and TP/PP shard divisibility.
    Returns a pruning reason or None."""
    if cand.strategy != "single_device" and n_devices % cand.model_parallel:
        return (f"{n_devices} devices not divisible by model_parallel="
                f"{cand.model_parallel}")
    if n_devices % cand.pipeline_parallel:
        return (f"{n_devices} devices not divisible by pipeline_parallel="
                f"{cand.pipeline_parallel}")
    replicas = (
        1 if cand.strategy == "single_device"
        else n_devices // (cand.model_parallel * cand.pipeline_parallel)
    )
    if batch_size % cand.grad_accum:
        return (f"grad_accum={cand.grad_accum} does not divide the global "
                f"batch {batch_size}")
    micro = batch_size // cand.grad_accum
    if micro % replicas:
        return (f"microbatch {micro} not divisible by {replicas} replicas")
    if cand.strategy == "tp":
        m = cand.model_parallel
        bad = _tp_indivisible(abstracts["params"], abstracts["hints"], m)
        if bad:
            return (f"TP shard dim {bad[1]} of {bad[0]} not divisible by "
                    f"model_parallel={m}")
    if cand.strategy == "pp":
        pp = cand.pipeline_parallel
        stages = _pipe_stage_count(abstracts["params"], abstracts["hints"])
        if stages is None:
            return "no 'pipe'-hinted stacks to place stages from"
        if stages % pp:
            return (f"{stages} pipeline stages not divisible by "
                    f"pipeline_parallel={pp}")
        per_replica = micro // max(replicas, 1)
        if per_replica % cand.num_microbatches:
            return (f"per-replica batch {per_replica} not divisible by "
                    f"num_microbatches={cand.num_microbatches}")
    return None


def _pipe_stage_count(params, hints) -> Optional[int]:
    """Leading (stage) dim of the first 'pipe'-hinted leaf — the number of
    schedulable stages a PipelinedBlocks stack exposes. None when nothing
    is pipe-hinted (the module has no pipeline stack to place)."""

    def walk(p, h):
        if isinstance(p, dict):
            for k, v in p.items():
                hit = walk(v, h.get(k, {}) if isinstance(h, dict) else h)
                if hit is not None:
                    return hit
            return None
        shape = tuple(getattr(p, "shape", ()))
        if h == "pipe" and shape:
            return int(shape[0])
        return None

    return walk(params, hints or {})


def _tp_indivisible(params, hints, m: int):
    """First (path, shape) whose hinted TP dim doesn't divide by ``m``."""

    def walk(p, h, path):
        if isinstance(p, dict):
            for k, v in p.items():
                hit = walk(v, h.get(k, {}) if isinstance(h, dict) else h,
                           path + (k,))
                if hit:
                    return hit
            return None
        role = h if isinstance(h, str) else None
        shape = tuple(getattr(p, "shape", ()))
        dim = None
        if role == "col" and shape:
            dim = shape[-1]
        elif role == "row" and shape:
            dim = shape[0]
        elif role == "row1" and len(shape) >= 2:
            dim = shape[1]
        if dim is not None and dim % m:
            return ("/".join(path), shape)
        return None

    return walk(params, hints or {}, ())


def estimate_candidate(cand: Candidate, ctx: dict) -> dict:
    """One candidate's predicted row: per-device state/activation bytes,
    per-step comm traffic, and the cost-model step seconds. Pure
    arithmetic over the shared abstract trees — nothing is placed."""
    from .. import precision as precision_lib
    from ..utils.profiler import tree_bytes_per_device

    abstracts, devices = ctx["abstracts"], ctx["devices"]
    consts = ctx["consts"]
    batch_size, tokens = ctx["batch_size"], ctx["tokens"]
    strat = cand.build_strategy(devices)
    hints = abstracts["hints"]
    policy = precision_lib.get(cand.precision)
    compute_dtype = policy.compute_dtype if policy is not None else None
    compute_itemsize = (
        policy.compute_itemsize if policy is not None else 4
    )

    from .strategy import _params_sharding_tree

    params_sh = _params_sharding_tree(strat, abstracts["params"], hints)
    state_sh = _params_sharding_tree(strat, abstracts["state"], None)
    opt_sh = strat.opt_state_sharding(
        abstracts["opt"], abstracts["params"], hints
    )
    trees = [
        _attach_shardings(abstracts["params"], params_sh),
        _attach_shardings(abstracts["state"], state_sh),
        _attach_shardings(abstracts["opt"], opt_sh),
    ]
    if cand.grad_accum > 1:
        # The in-jit accumulation scan carries an f32 params-shaped
        # gradient accumulator, placed like the params.
        acc = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, jax.numpy.float32),
            abstracts["params"],
        )
        trees.append(_attach_shardings(acc, params_sh))
    state_bytes = tree_bytes_per_device(*trees)["max_bytes_per_device"]

    replicas = int(getattr(strat, "num_replicas_in_sync", 1))
    n_active = 1 if cand.strategy == "single_device" else len(devices)
    tokens_local = max(tokens // max(replicas, 1), 1)

    # Coarse activation proxy — the two tensors knowable without tracing
    # the module's internals: the input and the logits (whose cotangent
    # doubles them in backward), per microbatch, plus the staged
    # super-batch when steps_per_execution stacks K inputs on device.
    input_bytes = ctx["input_bytes"]
    logits_bytes = ctx["logits_elems"] * compute_itemsize
    act_bytes = (
        (input_bytes + 2 * logits_bytes)
        // max(replicas, 1) // cand.grad_accum
        + input_bytes * cand.steps_per_execution // max(replicas, 1)
    )

    comm = strat.comm_bytes_estimate(
        abstracts["params"], compute_dtype=compute_dtype, hints=hints
    )
    # Per optimizer step: FSDP/ZeRO gathers repeat per microbatch; the
    # gradient reduce happens once on the accumulated value; TP's
    # activation all-reduces total the same tokens regardless of M.
    comm_bytes = (
        comm["gathered_param_bytes_per_device"] * cand.grad_accum
        + comm["grad_reduce_bytes_per_device"]
        + comm["activation_reduce_bytes_per_token_per_device"] * tokens_local
        + comm.get("pipeline_hop_bytes_per_token_per_device", 0)
        * tokens_local
    )

    flops = 6.0 * abstracts["n_params"] * tokens
    speed = consts["peak_flops"] * n_active
    if compute_dtype is not None and compute_itemsize < 4:
        speed *= consts["reduced_speedup"]
    compute_s = flops / speed
    if cand.model_parallel > 1:
        # Megatron splitting narrows every sharded matmul's contraction or
        # output dim by the TP factor, dropping arithmetic efficiency
        # (under-filled MXU tiles, per-layer blocking all-reduces on the
        # critical path) — the standard reason TP is sized to the minimum
        # that fits, not the maximum available. Priced as a +15% compute
        # penalty per doubling of the TP factor.
        compute_s *= 1.0 + 0.15 * float(np.log2(cand.model_parallel))
    if cand.pipeline_parallel > 1:
        # GPipe bubble: of M+n-1 schedule ticks only M do useful work per
        # stage, so devices idle a (n-1)/(M+n-1) fraction of the step —
        # the planner prices pipelining as slower at equal memory, picking
        # it only when flat layouts are pruned (the design intent: PP is
        # the capacity axis of last resort, like TP's efficiency penalty).
        m_pipe = max(int(cand.num_microbatches), 1)
        compute_s *= (m_pipe + cand.pipeline_parallel - 1) / m_pipe
    comm_s = comm_bytes / consts["comm_bw"]
    dispatch_s = consts["dispatch_s"] / cand.steps_per_execution
    return {
        "config": cand.config(),
        "label": cand.label(),
        "state_bytes_per_device": int(state_bytes),
        "activation_bytes_per_device": int(act_bytes),
        "comm_bytes_per_step_per_device": int(comm_bytes),
        "comm_bytes_estimate": comm,
        "est_step_seconds": compute_s + comm_s + dispatch_s,
        "cost_breakdown": {
            "compute_s": compute_s,
            "comm_s": comm_s,
            "dispatch_s": dispatch_s,
        },
    }


# -------------------------------------------------------------- enumeration --
def enumerate_candidates(
    n_devices: int,
    *,
    hints=None,
    precisions: Sequence[Optional[str]] = (None,),
    grad_accums: Sequence[int] = (1, 2, 4),
    steps_per_execution: Sequence[int] = (1, 8),
    include_tp: bool = True,
    include_pp: bool = True,
) -> List[Candidate]:
    """The candidate matrix for a device count: strategies x precision x
    grad_accum x steps_per_execution. TP mesh shapes come from the
    divisors of the device count and are proposed only when the module
    carries Megatron sharding hints (an unhinted model would shard
    nothing); PP stage counts likewise come from the divisors and are
    proposed only when the hints carry a 'pipe' role (a PipelinedBlocks
    stack), each at microbatch counts M in {n, 2n} — the bubble/MXU
    trade's two canonical points."""
    strategies: List[Tuple[str, int, int, int]] = []  # (name, tp, pp, M)
    if n_devices == 1:
        strategies.append(("single_device", 1, 1, 1))
    else:
        strategies += [("single_device", 1, 1, 1), ("dp", 1, 1, 1),
                       ("zero1", 1, 1, 1), ("fsdp", 1, 1, 1)]
        if include_tp and hints:
            for m in range(2, n_devices + 1):
                if n_devices % m == 0:
                    strategies.append(("tp", m, 1, 1))
        if include_pp and _hints_have_pipe(hints):
            for pp in range(2, n_devices + 1):
                if n_devices % pp == 0:
                    for mb in (pp, 2 * pp):
                        strategies.append(("pp", 1, pp, mb))
    out = []
    for name, m, pp, mb in strategies:
        for prec in precisions:
            for ga in grad_accums:
                for k in steps_per_execution:
                    out.append(Candidate(
                        strategy=name, model_parallel=m,
                        pipeline_parallel=pp, num_microbatches=mb,
                        precision=prec,
                        grad_accum=int(ga), steps_per_execution=int(k),
                    ))
    return out


def _hints_have_pipe(hints) -> bool:
    """True when any node of the hint tree carries the 'pipe' role."""
    if hints == "pipe":
        return True
    if isinstance(hints, dict):
        return any(_hints_have_pipe(v) for v in hints.values())
    return False


# ------------------------------------------------------------------ planning --
def plan_sharding(
    module,
    input_shape,
    *,
    tx=None,
    optimizer="adam",
    batch_size: int = 32,
    devices=None,
    hbm_cap_bytes: Optional[int] = None,
    precisions: Optional[Sequence[Optional[str]]] = None,
    grad_accums: Optional[Sequence[int]] = None,
    steps_per_execution: Optional[Sequence[int]] = None,
    include_tp: bool = True,
    include_pp: bool = True,
    measure: bool = False,
    measure_fn: Optional[
        Callable[[Candidate, dict], Optional[float]]
    ] = None,
    top_k: int = 3,
    seed: int = 0,
) -> Plan:
    """Plan the fastest feasible sharding config for ``module`` on the
    live topology. Deterministic for fixed inputs (measure=False).

    ``tx``: the optax transform whose state is being priced (defaults to
    ``optim.get(optimizer)``). ``precisions`` defaults backend-aware:
    ``(None, "mixed_bfloat16")`` on accelerators, ``(None,)`` on XLA:CPU
    (which emulates bf16 — recommending it there would be a lie the
    BENCH_precision artifact already measured at 0.83x). ``measure=True``
    times the ``top_k`` estimate-ranked survivors with ``measure_fn``
    (seconds per step, or None to skip one candidate) and commits to the
    fastest measured."""
    from .. import optim

    devices = list(devices) if devices is not None else list(jax.devices())
    backend = devices[0].platform
    consts = _backend_constants(backend)
    if tx is None:
        tx = optim.get(optimizer)
    if precisions is None:
        precisions = (
            (None, "mixed_bfloat16") if backend in ("tpu", "gpu")
            else (None,)
        )
    if grad_accums is None:
        grad_accums = (1, 2, 4)
    if steps_per_execution is None:
        steps_per_execution = (1, 8)

    abstracts = abstract_model_state(module, input_shape, tx, seed=seed)
    x_dtype, logits = probe_forward(
        module, abstracts["params"], abstracts["state"], input_shape,
        batch_size,
    )
    tokens = int(np.prod(logits.shape[:-1], dtype=np.int64))
    ctx = {
        "abstracts": abstracts,
        "devices": devices,
        "consts": consts,
        "batch_size": int(batch_size),
        "tokens": tokens,
        "input_bytes": int(
            np.prod((batch_size,) + tuple(input_shape), dtype=np.int64)
        ) * jax.numpy.dtype(x_dtype).itemsize,
        "logits_elems": int(np.prod(logits.shape, dtype=np.int64)),
        "logits_shape": tuple(logits.shape),
        "x_dtype": x_dtype,
    }

    feasibility = Feasibility(hbm_cap_bytes)
    candidates = enumerate_candidates(
        len(devices), hints=abstracts["hints"], precisions=precisions,
        grad_accums=grad_accums, steps_per_execution=steps_per_execution,
        include_tp=include_tp, include_pp=include_pp,
    )
    feasible, pruned = [], []
    for cand in candidates:
        reason = _check_divisibility(cand, len(devices), batch_size,
                                     abstracts)
        if reason is not None:
            pruned.append({"config": cand.config(), "label": cand.label(),
                           "reason": reason})
            continue
        row = estimate_candidate(cand, ctx)
        reason = feasibility.check(
            row["state_bytes_per_device"],
            row["activation_bytes_per_device"],
        )
        if reason is not None:
            row["reason"] = reason
            pruned.append(row)
        else:
            row["reason"] = None
            feasible.append((cand, row))
    if not feasible:
        raise ValueError(
            "auto-shard planner found NO feasible candidate under "
            f"hbm_cap_bytes={hbm_cap_bytes} for batch {batch_size}: "
            + "; ".join(f"{p['label']}: {p['reason']}" for p in pruned[:6])
        )

    # Rank: cost ascending; inside the tie band prefer more HBM headroom
    # when a cap binds (activations/fragmentation live in the slack), else
    # the simpler config.
    feasible.sort(key=lambda cr: cr[1]["est_step_seconds"])
    best_cost = feasible[0][1]["est_step_seconds"]
    band = [
        cr for cr in feasible
        if cr[1]["est_step_seconds"] <= best_cost * (1.0 + TIE_REL_TOL)
    ]
    if hbm_cap_bytes is not None and len(band) > 1:
        band.sort(key=lambda cr: (cr[1]["state_bytes_per_device"],
                                  cr[0].complexity()))
        tie_break = "hbm_headroom"
    else:
        band.sort(key=lambda cr: cr[0].complexity())
        tie_break = "simplicity"
    ordered = band + [cr for cr in feasible if cr not in band]

    measured_rows = None
    if measure and measure_fn is not None:
        shortlist = ordered[: max(1, int(top_k))]
        measured_rows = []
        timed = []
        for cand, row in shortlist:
            secs = measure_fn(cand, ctx)
            measured_rows.append({
                "config": cand.config(), "label": cand.label(),
                "seconds_per_step": secs,
            })
            if secs is not None:
                timed.append((secs, cand, row))
        if timed:
            timed.sort(key=lambda t: t[0])
            _, cand0, row0 = timed[0]
            ordered = (
                [(cand0, row0)]
                + [cr for cr in ordered if cr[0] is not cand0]
            )
            tie_break = "measured"

    chosen_cand, chosen_row = ordered[0]
    plan = Plan(
        chosen=chosen_row,
        candidates=[r for _, r in ordered],
        pruned=pruned,
        devices=len(devices),
        backend=backend,
        batch_size=int(batch_size),
        n_params=abstracts["n_params"],
        hbm_cap_bytes=(
            int(hbm_cap_bytes) if hbm_cap_bytes is not None else None
        ),
        measured=measured_rows,
        tie_break=tie_break,
    )
    plan._ctx = ctx  # probe results, for Model's measure path
    return plan
