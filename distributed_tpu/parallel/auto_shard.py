"""Route Pallas kernels around GSPMD's custom-call replication.

XLA's SPMD partitioner cannot see inside a Pallas kernel, so under a sharded
mesh it wraps the call in all-gather(inputs) -> replicated compute ->
dynamic-slice(output): correct, but the kernel then runs the GLOBAL problem
on every device (verified by compiling flash attention under a 'data'-sharded
batch and finding the all-gather in the HLO). The fix is shard_map: run the
kernel per-shard on local data, which is exactly right for row/batch-blocked
kernels (fused xent, flash attention) whose grid never crosses rows.

``shard_rows(fn, arrays, specs)`` wraps fn in shard_map over the ambient
strategy's mesh when — and only when — that is safe:

- every mesh axis of size > 1 is either the strategy's batch axis or the
  Megatron 'model' axis (axes with bespoke schedules — 'pipe', 'seq' — keep
  the plain path; their strategies have their own machinery);
- every array dim sharded by a spec divides evenly.

Otherwise the plain call runs (GSPMD replication on multi-device, which is
still correct — and free on a single device, where there is nothing to
replicate).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, PartitionSpec

try:  # modern location (jax>=0.8)
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

import inspect

# Replication checking was renamed check_rep -> check_vma in jax 0.8.
_sig = inspect.signature(shard_map).parameters
if "check_vma" in _sig:
    _CHECK_KWARGS = {"check_vma": False}
elif "check_rep" in _sig:  # pragma: no cover - older jax
    _CHECK_KWARGS = {"check_rep": False}
else:  # pragma: no cover
    _CHECK_KWARGS = {}
del _sig


def ambient_mesh() -> Tuple[Optional[Mesh], Optional[str], Optional[str]]:
    """(mesh, batch_axis, model_axis) from the ambient strategy scope.

    model_axis is 'model' when present in the mesh (the Megatron TP axis,
    parallel.mesh.AXES), else None. mesh is None outside any mesh strategy.
    """
    from .strategy import current_strategy

    strat = current_strategy()
    mesh = getattr(strat, "mesh", None)
    if mesh is None:
        return None, None, None
    batch_axis = getattr(strat, "axis", None)
    if batch_axis not in mesh.axis_names:
        batch_axis = None
    model_axis = "model" if "model" in mesh.axis_names else None
    return mesh, batch_axis, model_axis


def shard_rows(fn, arrays: Sequence, in_specs: Sequence[PartitionSpec],
               out_spec: PartitionSpec, *, allowed_axes=None):
    """Apply fn(*arrays) under shard_map over the ambient mesh when safe
    (see module docstring), else call it plainly.

    ``allowed_axes``: override the default {batch, model} axis allowlist —
    for callers that deliberately shard over another axis (e.g. Ulysses
    attention sharding heads over 'seq') and have already validated it."""
    mesh, batch_axis, model_axis = ambient_mesh()
    if mesh is None:
        return fn(*arrays)
    if allowed_axes is not None:
        allowed = set(allowed_axes) | {None}
    else:
        allowed = {batch_axis, model_axis, None}
    for name in mesh.axis_names:
        if int(mesh.shape[name]) > 1 and name not in allowed:
            return fn(*arrays)
    # Divisibility of every sharded dim, or fall back.
    for arr, spec in zip(arrays, in_specs):
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            if int(mesh.shape[axis]) > 1 and arr.shape[dim] % int(
                mesh.shape[axis]
            ):
                return fn(*arrays)
    return shard_map(
        fn, mesh=mesh, in_specs=tuple(in_specs), out_specs=out_spec,
        **_CHECK_KWARGS,
    )(*arrays)
