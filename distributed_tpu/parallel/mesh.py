"""Device-mesh construction.

The mesh is the framework's single source of truth for parallelism: every
strategy (DP today; TP/PP/SP/EP compose later) is an axis of one
``jax.sharding.Mesh``. This replaces the reference's flat worker list in
``TF_CONFIG`` (/root/reference/README.md:84-89, 322-327): where the reference
enumerates gRPC endpoints, we enumerate chips and name axes, and XLA emits the
collectives over ICI/DCN.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Canonical axis names, in fixed order. DP is one axis of a general design so
# the others compose later without re-plumbing (SURVEY.md §2c implication).
AXES = ("data", "fsdp", "pipe", "seq", "expert", "model")


def make_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over `devices` (default: all) with named axes.

    ``axis_sizes`` maps axis name -> size; omitted axes get size 1 and are
    dropped unless explicitly given. With no arguments, all devices go on the
    'data' axis (pure DP — exactly the reference's MultiWorkerMirrored layout,
    /root/reference/README.md:122,364, re-expressed as a mesh).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axis_sizes:
        axis_sizes = {"data": n}
    names = [a for a in AXES if a in axis_sizes]
    unknown = set(axis_sizes) - set(AXES)
    if unknown:
        raise ValueError(f"Unknown mesh axes {sorted(unknown)}; valid: {AXES}")
    sizes = [int(axis_sizes[a]) for a in names]
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(
            f"Mesh axes {dict(zip(names, sizes))} need {total} devices, got {n}"
        )
    try:
        dev_array = mesh_utils.create_device_mesh(sizes, devices=devices)
    except Exception:
        dev_array = np.array(devices).reshape(sizes)
    return Mesh(dev_array, axis_names=tuple(names))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim across `axis`."""
    return NamedSharding(mesh, PartitionSpec(axis))
