"""Device-mesh construction.

The mesh is the framework's single source of truth for parallelism: every
strategy (DP today; TP/PP/SP/EP compose later) is an axis of one
``jax.sharding.Mesh``. This replaces the reference's flat worker list in
``TF_CONFIG`` (/root/reference/README.md:84-89, 322-327): where the reference
enumerates gRPC endpoints, we enumerate chips and name axes, and XLA emits the
collectives over ICI/DCN.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Canonical axis names, in fixed order. DP is one axis of a general design so
# the others compose later without re-plumbing (SURVEY.md §2c implication).
AXES = ("data", "fsdp", "pipe", "seq", "expert", "model")


def _slice_ids_of(devices) -> list:
    """Per-device slice index (0 everywhere on single-slice systems)."""
    out = []
    for d in devices:
        sid = getattr(d, "slice_index", None)
        out.append(0 if sid is None else int(sid))
    return out


def _hybrid_device_array(devices, names, sizes, dcn_axis, slice_ids):
    """Arrange a multi-slice device set so ``dcn_axis`` is slice-major:
    each slice contributes a contiguous block of that axis, and every
    other axis stays within one slice. Collectives over non-dcn axes then
    ride ICI; only the dcn axis crosses the data-center network — the
    standard hybrid recipe (data over DCN, model/fsdp within a slice)."""
    groups: Dict[int, list] = {}
    for d, s in zip(devices, slice_ids):
        groups.setdefault(s, []).append(d)
    n_slices = len(groups)
    dcn_i = names.index(dcn_axis)
    if sizes[dcn_i] % n_slices:
        raise ValueError(
            f"dcn axis {dcn_axis!r} size {sizes[dcn_i]} not divisible by "
            f"{n_slices} slices"
        )
    per = list(sizes)
    per[dcn_i] = sizes[dcn_i] // n_slices
    per_count = int(np.prod(per))
    subs = []
    for s in sorted(groups):
        devs = groups[s]
        if len(devs) != per_count:
            raise ValueError(
                f"slice {s} has {len(devs)} devices; the hybrid mesh "
                f"needs {per_count} per slice"
            )
        subs.append(np.array(devs, dtype=object).reshape(per))
    return np.concatenate(subs, axis=dcn_i)


def make_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    dcn_axis: Optional[str] = None,
    slice_ids: Optional[Sequence[int]] = None,
) -> Mesh:
    """Build a Mesh over `devices` (default: all) with named axes.

    ``axis_sizes`` maps axis name -> size; omitted axes get size 1 and are
    dropped unless explicitly given. With no arguments, all devices go on the
    'data' axis (pure DP — exactly the reference's MultiWorkerMirrored layout,
    /root/reference/README.md:122,364, re-expressed as a mesh).

    ``dcn_axis`` names the axis laid across TPU slices on a multi-slice
    (Megascale/DCN) system — typically 'data', so gradient all-reduce is
    the only cross-slice collective while model/fsdp/seq axes stay on ICI
    (BASELINE.json configs[4]'s multi-host shape). Ignored when every
    device reports the same slice. ``slice_ids`` overrides the per-device
    slice detection (tests use this to mock a 2-slice device set).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axis_sizes:
        axis_sizes = {"data": n}
    names = [a for a in AXES if a in axis_sizes]
    unknown = set(axis_sizes) - set(AXES)
    if unknown:
        raise ValueError(f"Unknown mesh axes {sorted(unknown)}; valid: {AXES}")
    sizes = [int(axis_sizes[a]) for a in names]
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(
            f"Mesh axes {dict(zip(names, sizes))} need {total} devices, got {n}"
        )
    ids = list(slice_ids) if slice_ids is not None else _slice_ids_of(devices)
    if len(ids) != n:
        raise ValueError(f"slice_ids has {len(ids)} entries for {n} devices")
    if dcn_axis is not None and len(set(ids)) > 1:
        if dcn_axis not in names:
            raise ValueError(
                f"dcn_axis {dcn_axis!r} not among mesh axes {names}"
            )
        if slice_ids is None:
            # Real multi-slice hardware: let jax's hybrid topology helper
            # optimize within-slice ordering; fall back to the plain
            # slice-major arrangement when it can't.
            try:
                dcn_shape = [1] * len(sizes)
                dcn_i = names.index(dcn_axis)
                n_slices = len(set(ids))
                per = list(sizes)
                per[dcn_i] = sizes[dcn_i] // n_slices
                dcn_shape[dcn_i] = n_slices
                dev_array = mesh_utils.create_hybrid_device_mesh(
                    per, dcn_shape, devices=devices
                )
                return Mesh(dev_array, axis_names=tuple(names))
            except Exception:
                pass
        dev_array = _hybrid_device_array(devices, names, sizes, dcn_axis, ids)
        return Mesh(dev_array, axis_names=tuple(names))
    try:
        dev_array = mesh_utils.create_device_mesh(sizes, devices=devices)
    except Exception:
        dev_array = np.array(devices).reshape(sizes)
    return Mesh(dev_array, axis_names=tuple(names))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim across `axis`."""
    return NamedSharding(mesh, PartitionSpec(axis))
